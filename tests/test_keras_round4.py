"""Round-4 Keras mapper golden tests: LayerNormalization, Permute/Reshape,
ConvLSTM2D, LocallyConnected, SeparableConv1D, MultiHeadAttention,
Attention, preprocessing layers — each built with in-env keras and compared
elementwise (reference modelimport test pattern, SURVEY §5.4)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.imports.keras_import import (
    KerasLayerMapper, import_keras_model)


def assert_outputs_match(model, net, x, rtol=1e-4, atol=1e-5):
    golden = model(x, training=False).numpy()
    got = net.output(x)
    np.testing.assert_allclose(got, golden, rtol=rtol, atol=atol)


class TestRound4Mappers:
    def test_mapper_count_at_least_80(self):
        from deeplearning4j_tpu.imports.keras_import import _MERGE_LAYERS

        total = len(KerasLayerMapper.MAPPERS) + len(_MERGE_LAYERS)
        assert total >= 80, total

    def test_layer_normalization(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((10,)),
            tf.keras.layers.Dense(8, activation="relu"),
            tf.keras.layers.LayerNormalization(),
            tf.keras.layers.Dense(3),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(0).randn(4, 10).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_group_normalization(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((6, 6, 8)),
            tf.keras.layers.GroupNormalization(groups=4),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(1).randn(2, 6, 6, 8).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_permute_reshape(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4, 6)),
            tf.keras.layers.Permute((2, 1)),
            tf.keras.layers.Reshape((3, 8)),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(2).randn(3, 4, 6).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_conv_lstm_2d(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((5, 8, 8, 3)),
            tf.keras.layers.ConvLSTM2D(4, (3, 3), padding="same",
                                       return_sequences=False),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(3).rand(2, 5, 8, 8, 3).astype(np.float32)
        assert_outputs_match(model, net, x, rtol=1e-3, atol=1e-4)

    def test_conv_lstm_2d_return_sequences(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4, 6, 6, 2)),
            tf.keras.layers.ConvLSTM2D(3, (3, 3), padding="valid",
                                       return_sequences=True),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(4).rand(2, 4, 6, 6, 2).astype(np.float32)
        assert_outputs_match(model, net, x, rtol=1e-3, atol=1e-4)

    def test_locally_connected_1d_oracle(self):
        """Keras 3 dropped LocallyConnected*, so the mapper is golden-tested
        against a numpy oracle in the LEGACY keras weight layout
        (output_len, k*cin, filters), position p consuming x[p*s : p*s+k]."""
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.imports.keras_import import KerasLayerMapper

        r = np.random.RandomState(5)
        t_in, cin, k, filt = 10, 4, 3, 6
        ot = t_in - k + 1
        kw_ = r.randn(ot, k * cin, filt).astype(np.float32)
        b = r.randn(ot, filt).astype(np.float32)
        cfg = {"filters": filt, "kernel_size": [k], "strides": [1],
               "activation": "linear", "use_bias": True, "name": "lc1"}
        lc, p = KerasLayerMapper.MAPPERS["LocallyConnected1D"](cfg, [kw_, b])
        bld = nn.builder().seed(0).list()
        bld.layer(lc)
        net = nn.MultiLayerNetwork(
            bld.set_input_type(nn.InputType.recurrent(cin, t_in)).build()).init()
        net.params[0].update({kk: np.asarray(v) for kk, v in p.items()})
        x = r.randn(2, t_in, cin).astype(np.float32)
        want = np.zeros((2, ot, filt), np.float32)
        for pos in range(ot):
            win = x[:, pos:pos + k, :].reshape(2, -1)  # (k, cin) flatten
            want[:, pos] = win @ kw_[pos] + b[pos]
        np.testing.assert_allclose(net.output(x), want, rtol=1e-4, atol=1e-5)

    def test_locally_connected_2d_oracle(self):
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.imports.keras_import import KerasLayerMapper

        r = np.random.RandomState(6)
        h = w = 6
        cin, kh, kw_sz, filt = 2, 3, 3, 4
        oh, ow = h - kh + 1, w - kw_sz + 1
        kern = r.randn(oh * ow, kh * kw_sz * cin, filt).astype(np.float32)
        b = r.randn(oh, ow, filt).astype(np.float32)
        cfg = {"filters": filt, "kernel_size": [kh, kw_sz], "strides": [1, 1],
               "activation": "linear", "use_bias": True, "name": "lc2"}
        lc, p = KerasLayerMapper.MAPPERS["LocallyConnected2D"](cfg, [kern, b])
        bld = nn.builder().seed(0).list()
        bld.layer(lc)
        net = nn.MultiLayerNetwork(
            bld.set_input_type(nn.InputType.convolutional(h, w, cin)).build()).init()
        net.params[0].update({kk: np.asarray(v) for kk, v in p.items()})
        x = r.randn(2, h, w, cin).astype(np.float32)
        want = np.zeros((2, oh, ow, filt), np.float32)
        for i in range(oh):
            for j in range(ow):
                # legacy keras layout: (kh, kw, C)-major patch flatten
                win = x[:, i:i + kh, j:j + kw_sz, :].reshape(2, -1)
                want[:, i, j] = win @ kern[i * ow + j] + b[i, j]
        np.testing.assert_allclose(net.output(x), want, rtol=1e-4, atol=1e-5)

    def test_separable_conv1d(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((12, 4)),
            tf.keras.layers.SeparableConv1D(6, 3, padding="same",
                                            activation="relu"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(7).randn(2, 12, 4).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_preprocessing_layers(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((6,)),
            tf.keras.layers.Rescaling(scale=2.0, offset=0.5),
            tf.keras.layers.UnitNormalization(),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(8).randn(4, 6).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_normalization_adapted(self):
        norm = tf.keras.layers.Normalization()
        data = np.random.RandomState(9).randn(64, 5).astype(np.float32) * 3 + 1
        norm.adapt(data)
        model = tf.keras.Sequential([tf.keras.layers.Input((5,)), norm])
        net = import_keras_model(model)
        x = data[:4]
        assert_outputs_match(model, net, x)

    def test_multi_head_attention_functional(self):
        inp = tf.keras.layers.Input((6, 16))
        mha = tf.keras.layers.MultiHeadAttention(num_heads=4, key_dim=4)
        out = mha(inp, inp)  # self-attention
        out = tf.keras.layers.Dense(3)(out)
        model = tf.keras.Model(inp, out)
        net = import_keras_model(model)
        x = np.random.RandomState(10).randn(2, 6, 16).astype(np.float32)
        golden = model(x, training=False).numpy()
        got = net.output(x)[0]  # functional import -> ComputationGraph
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_attention_functional(self):
        q_in = tf.keras.layers.Input((5, 8))
        v_in = tf.keras.layers.Input((7, 8))
        out = tf.keras.layers.Attention()([q_in, v_in])
        model = tf.keras.Model([q_in, v_in], out)
        net = import_keras_model(model)
        r = np.random.RandomState(11)
        q = r.randn(2, 5, 8).astype(np.float32)
        v = r.randn(2, 7, 8).astype(np.float32)
        golden = model([q, v], training=False).numpy()
        got = net.output(q, v)[0]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_identity_mappers_warn(self):
        with pytest.warns(UserWarning, match="identity"):
            model = tf.keras.Sequential([
                tf.keras.layers.Input((4,)),
                tf.keras.layers.ActivityRegularization(l2=0.1),
            ])
            net = import_keras_model(model)
        x = np.random.RandomState(12).randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(net.output(x), x)

    def test_lambda_requires_registration(self):
        from deeplearning4j_tpu.imports.keras_import import (
            register_lambda)
        from deeplearning4j_tpu.nn import conf as C

        cfg = {"name": "my_double"}
        with pytest.raises(NotImplementedError, match="register_lambda"):
            KerasLayerMapper.MAPPERS["Lambda"](cfg, [])
        register_lambda("my_double", lambda c, w: (
            C.RescaleLayer(scale=2.0, name=c.get("name")), {}))
        lc, _ = KerasLayerMapper.MAPPERS["Lambda"](cfg, [])
        assert lc.scale == 2.0

    def test_merge_minimum_functional(self):
        a = tf.keras.layers.Input((6,))
        b = tf.keras.layers.Input((6,))
        out = tf.keras.layers.Minimum()([a, b])
        model = tf.keras.Model([a, b], out)
        net = import_keras_model(model)
        r = np.random.RandomState(13)
        xa = r.randn(3, 6).astype(np.float32)
        xb = r.randn(3, 6).astype(np.float32)
        golden = model([xa, xb], training=False).numpy()
        got = net.output(xa, xb)[0]
        np.testing.assert_allclose(got, golden, rtol=1e-5)

    def test_conv1d_transpose(self):
        for pad, stride in (("same", 2), ("valid", 1), ("same", 1)):
            model = tf.keras.Sequential([
                tf.keras.layers.Input((8, 3)),
                tf.keras.layers.Conv1DTranspose(5, 3, strides=stride,
                                                padding=pad,
                                                activation="relu"),
            ])
            net = import_keras_model(model)
            x = np.random.RandomState(14).randn(2, 8, 3).astype(np.float32)
            assert_outputs_match(model, net, x)

    def test_permute_then_dense(self):
        """Permute keeps a structured InputType so a following Dense applies
        to the (permuted) trailing axis, exactly like keras."""
        model = tf.keras.Sequential([
            tf.keras.layers.Input((4, 6)),
            tf.keras.layers.Permute((2, 1)),
            tf.keras.layers.Dense(3),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(15).randn(2, 4, 6).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_layer_norm_direct_build(self):
        """LayerNormalization/GroupNormalization infer n_out at build like
        BatchNormalization (no (0,)-shaped params)."""
        from deeplearning4j_tpu import nn

        bld = nn.builder().seed(0).list()
        bld.layer(nn.DenseLayer(n_out=6, activation="tanh"))
        bld.layer(nn.conf.LayerNormalization())
        net = nn.MultiLayerNetwork(
            bld.set_input_type(nn.InputType.feed_forward(4)).build()).init()
        assert net.params[1]["gain"].shape == (6,)
        x = np.random.RandomState(16).randn(3, 4).astype(np.float32)
        out = net.output(x)
        assert out.shape == (3, 6) and np.isfinite(out).all()


class TestKerasV3FileImport:
    """Own-parsing of the Keras-3 .keras zip format (config.json +
    model.weights.h5 with snake_case(class)+counter weight groups)."""

    def test_sequential_keras_file(self, tmp_path):
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        model = tf.keras.Sequential([
            tf.keras.layers.Input((8, 8, 3)),
            tf.keras.layers.Conv2D(4, 3, activation="relu", name="convA"),
            tf.keras.layers.Flatten(name="flat"),
            tf.keras.layers.Dense(5, activation="tanh", name="zz"),
            tf.keras.layers.Dense(2, activation="softmax", name="aa"),
        ])
        path = str(tmp_path / "m.keras")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = np.random.RandomState(0).rand(2, 8, 8, 3).astype(np.float32)
        golden = model(x, training=False).numpy()
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4,
                                   atol=1e-5)

    def test_same_class_layer_ordering(self, tmp_path):
        """Three Dense layers whose user names sort AGAINST model order —
        the counter rule must still assign groups by model order."""
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        model = tf.keras.Sequential([
            tf.keras.layers.Input((6,)),
            tf.keras.layers.Dense(7, activation="relu", name="zzz"),
            tf.keras.layers.Dense(5, activation="relu", name="mmm"),
            tf.keras.layers.Dense(2, name="aaa"),
        ])
        path = str(tmp_path / "m2.keras")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = np.random.RandomState(1).randn(3, 6).astype(np.float32)
        golden = model(x, training=False).numpy()
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4,
                                   atol=1e-5)

    def test_batchnorm_in_keras_file(self, tmp_path):
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        model = tf.keras.Sequential([
            tf.keras.layers.Input((5,)),
            tf.keras.layers.Dense(8, name="d"),
            tf.keras.layers.BatchNormalization(name="bn"),
            tf.keras.layers.Activation("relu"),
        ])
        # make running stats non-trivial
        model.compile(optimizer="sgd", loss="mse")
        data = np.random.RandomState(2).randn(64, 5).astype(np.float32)
        model.fit(data, np.random.RandomState(3).randn(64, 8)
                  .astype(np.float32), epochs=1, verbose=0)
        path = str(tmp_path / "m3.keras")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = data[:4]
        golden = model(x, training=False).numpy()
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4,
                                   atol=1e-4)

    def test_rnn_layers_in_keras_file(self, tmp_path):
        """RNN weights live under cell/vars (Bidirectional under
        forward_layer/backward_layer) — the recursion must flatten them in
        get_weights() order."""
        from deeplearning4j_tpu.imports.keras_import import (
            import_keras_model_and_weights)

        model = tf.keras.Sequential([
            tf.keras.layers.Input((6, 3)),
            tf.keras.layers.LSTM(4, return_sequences=True, name="l"),
            tf.keras.layers.Bidirectional(
                tf.keras.layers.LSTM(3, return_sequences=False), name="bi"),
            tf.keras.layers.Dense(2, name="out"),
        ])
        path = str(tmp_path / "rnn.keras")
        model.save(path)
        net = import_keras_model_and_weights(path)
        x = np.random.RandomState(4).randn(2, 6, 3).astype(np.float32)
        golden = model(x, training=False).numpy()
        np.testing.assert_allclose(net.output(x), golden, rtol=1e-4,
                                   atol=1e-5)


class TestFinalMappers:
    def test_resizing_and_center_crop(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((10, 12, 3)),
            tf.keras.layers.Resizing(20, 24, interpolation="bilinear"),
            tf.keras.layers.CenterCrop(8, 8),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(0).rand(2, 10, 12, 3).astype(np.float32)
        assert_outputs_match(model, net, x, rtol=1e-3, atol=1e-4)

    def test_resizing_nearest(self):
        model = tf.keras.Sequential([
            tf.keras.layers.Input((6, 6, 2)),
            tf.keras.layers.Resizing(12, 12, interpolation="nearest"),
        ])
        net = import_keras_model(model)
        x = np.random.RandomState(1).rand(2, 6, 6, 2).astype(np.float32)
        assert_outputs_match(model, net, x)

    def test_dot_merge_functional(self):
        a = tf.keras.layers.Input((5, 8))
        b = tf.keras.layers.Input((7, 8))
        out = tf.keras.layers.Dot(axes=2)([a, b])  # (N, 5, 7)
        model = tf.keras.Model([a, b], out)
        net = import_keras_model(model)
        r = np.random.RandomState(2)
        xa = r.randn(2, 5, 8).astype(np.float32)
        xb = r.randn(2, 7, 8).astype(np.float32)
        golden = model([xa, xb], training=False).numpy()
        got = net.output(xa, xb)[0]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_dot_merge_normalized(self):
        a = tf.keras.layers.Input((4,))
        b = tf.keras.layers.Input((4,))
        out = tf.keras.layers.Dot(axes=1, normalize=True)([a, b])
        model = tf.keras.Model([a, b], out)
        net = import_keras_model(model)
        r = np.random.RandomState(3)
        xa = r.randn(3, 4).astype(np.float32)
        xb = r.randn(3, 4).astype(np.float32)
        golden = model([xa, xb], training=False).numpy()
        got = net.output(xa, xb)[0]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)

    def test_dot_merge_feeds_dense(self):
        """Dot output consumed downstream: shape inference must give the
        following Dense a real n_in (cosine-similarity-head pattern)."""
        a = tf.keras.layers.Input((4,))
        b = tf.keras.layers.Input((4,))
        sim = tf.keras.layers.Dot(axes=1, normalize=True)([a, b])
        out = tf.keras.layers.Dense(2)(sim)
        model = tf.keras.Model([a, b], out)
        net = import_keras_model(model)
        r = np.random.RandomState(5)
        xa = r.randn(3, 4).astype(np.float32)
        xb = r.randn(3, 4).astype(np.float32)
        golden = model([xa, xb], training=False).numpy()
        got = net.output(xa, xb)[0]
        np.testing.assert_allclose(got, golden, rtol=1e-4, atol=1e-5)


from deeplearning4j_tpu import nn  # noqa: E402
import json  # noqa: E402


class TestLegacyRecurrentForms:
    """Round-5 verdict item 9: CuDNNLSTM/CuDNNGRU h5 files and the generic
    RNN(cell=...)/StackedRNNCells wrappers. Golden-tested via config+weight
    assembly against the standard LSTM/GRU mappers (the CuDNN layers ARE
    LSTM/GRU with a different bias layout; tf2 no longer exports them, so
    files are emulated at the spec level)."""

    def _lstm_weights(self, i, h, r):
        k = (r.randn(i, 4 * h) * 0.2).astype(np.float32)
        rec = (r.randn(h, 4 * h) * 0.2).astype(np.float32)
        b = (r.randn(4 * h) * 0.1).astype(np.float32)
        return k, rec, b

    def test_cudnn_lstm_matches_lstm(self):
        from deeplearning4j_tpu.imports.keras_import import (
            KerasLayerMapper, _assemble_sequential)
        r = np.random.RandomState(0)
        k, rec, b = self._lstm_weights(3, 4, r)
        b_cudnn = np.concatenate([b * 0.5, b * 0.5])  # (8H,) split bias
        cfg = {"units": 4, "name": "l", "return_sequences": True}
        net_a = _assemble_sequential(
            [("LSTM", dict(cfg, activation="tanh",
                           recurrent_activation="sigmoid"), [k, rec, b])],
            nn.InputType.recurrent(3))
        net_b = _assemble_sequential(
            [("CuDNNLSTM", dict(cfg), [k, rec, b_cudnn])],
            nn.InputType.recurrent(3))
        x = r.randn(2, 5, 3).astype(np.float32)
        np.testing.assert_allclose(net_b.output(x), net_a.output(x),
                                   atol=1e-5)

    def test_cudnn_gru_matches_gru(self):
        from deeplearning4j_tpu.imports.keras_import import _assemble_sequential
        r = np.random.RandomState(1)
        i, h = 3, 4
        k = (r.randn(i, 3 * h) * 0.2).astype(np.float32)
        rec = (r.randn(h, 3 * h) * 0.2).astype(np.float32)
        b2 = (r.randn(2, 3 * h) * 0.1).astype(np.float32)
        cfg = {"units": h, "name": "g", "return_sequences": True}
        net_a = _assemble_sequential(
            [("GRU", dict(cfg, reset_after=True, activation="tanh",
                          recurrent_activation="sigmoid"), [k, rec, b2])],
            nn.InputType.recurrent(i))
        net_b = _assemble_sequential(
            [("CuDNNGRU", dict(cfg), [k, rec, b2.reshape(-1)])],
            nn.InputType.recurrent(i))
        x = r.randn(2, 5, i).astype(np.float32)
        np.testing.assert_allclose(net_b.output(x), net_a.output(x),
                                   atol=1e-5)

    def test_rnn_cell_wrapper(self):
        from deeplearning4j_tpu.imports.keras_import import _assemble_sequential
        r = np.random.RandomState(2)
        k, rec, b = self._lstm_weights(3, 4, r)
        cell = {"class_name": "LSTMCell",
                "config": {"units": 4, "activation": "tanh",
                           "recurrent_activation": "sigmoid"}}
        net_a = _assemble_sequential(
            [("RNN", {"cell": cell, "name": "w",
                      "return_sequences": True}, [k, rec, b])],
            nn.InputType.recurrent(3))
        net_b = _assemble_sequential(
            [("LSTM", {"units": 4, "activation": "tanh",
                       "recurrent_activation": "sigmoid",
                       "return_sequences": True}, [k, rec, b])],
            nn.InputType.recurrent(3))
        x = r.randn(2, 5, 3).astype(np.float32)
        np.testing.assert_allclose(net_a.output(x), net_b.output(x),
                                   atol=1e-5)

    def test_stacked_rnn_cells_expand(self):
        from deeplearning4j_tpu.imports.keras_import import _assemble_sequential
        r = np.random.RandomState(3)
        k1, rec1, b1 = self._lstm_weights(3, 4, r)
        k2, rec2, b2 = self._lstm_weights(4, 2, r)
        stacked = {"class_name": "StackedRNNCells", "config": {"cells": [
            {"class_name": "LSTMCell", "config": {"units": 4}},
            {"class_name": "LSTMCell", "config": {"units": 2}},
        ]}}
        net = _assemble_sequential(
            [("RNN", {"cell": stacked, "name": "s",
                      "return_sequences": True},
              [k1, rec1, b1, k2, rec2, b2])],
            nn.InputType.recurrent(3))
        x = r.randn(2, 5, 3).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 5, 2)
        assert len(net.layers) == 2  # expanded to two LSTM layers

    def test_cudnn_lstm_h5_golden(self, tmp_path):
        """End-to-end: a hand-written legacy h5 with a CuDNNLSTM layer
        imports through the public read path."""
        import h5py
        from deeplearning4j_tpu.imports.keras_import import import_keras_model_and_weights as import_keras
        r = np.random.RandomState(4)
        k, rec, b = self._lstm_weights(3, 4, r)
        b8 = np.concatenate([b, np.zeros_like(b)])
        arch = {"class_name": "Sequential", "config": {"name": "m", "layers": [
            {"class_name": "CuDNNLSTM",
             "config": {"name": "cl", "units": 4, "return_sequences": True,
                        "batch_input_shape": [None, 5, 3]}},
        ]}}
        path = str(tmp_path / "legacy.h5")
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(arch)
            mw = f.create_group("model_weights")
            f.attrs["layer_names"] = [b"cl"]
            g = mw.create_group("cl")
            g.attrs["weight_names"] = [b"cl/kernel:0", b"cl/recurrent_kernel:0",
                                       b"cl/bias:0"]
            g.create_dataset("cl/kernel:0", data=k)
            g.create_dataset("cl/recurrent_kernel:0", data=rec)
            g.create_dataset("cl/bias:0", data=b8)
        net = import_keras(path)
        x = r.randn(2, 5, 3).astype(np.float32)
        assert net.output(x).shape == (2, 5, 4)


class TestEinsumDense:
    def test_matches_keras(self):
        keras = tf.keras
        try:
            EinsumDense = keras.layers.EinsumDense
        except AttributeError:
            pytest.skip("no EinsumDense in this keras")
        model = keras.Sequential([
            keras.layers.Input((6,)),
            EinsumDense("ab,bc->ac", output_shape=8, bias_axes="c",
                        activation="relu"),
            keras.layers.Dense(3, activation="softmax"),
        ])
        x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
        net = import_keras_model(model)
        assert_outputs_match(model, net, x)

    def test_sequence_equation(self):
        keras = tf.keras
        try:
            EinsumDense = keras.layers.EinsumDense
        except AttributeError:
            pytest.skip("no EinsumDense in this keras")
        model = keras.Sequential([
            keras.layers.Input((5, 6)),
            EinsumDense("abc,cd->abd", output_shape=(None, 8),
                        bias_axes="d"),
        ])
        x = np.random.RandomState(1).randn(2, 5, 6).astype(np.float32)
        net = import_keras_model(model)
        assert_outputs_match(model, net, x)

    def test_einsum_dense_conf_roundtrip(self):
        from deeplearning4j_tpu import nn
        from deeplearning4j_tpu.nn import conf as C
        lc = nn.EinsumDenseLayer(equation="ab,bc->ac", out_shape=(8,),
                                 bias_shape=(8,))
        assert C.LayerConf.from_dict(lc.to_dict()) == lc


class TestTabularPreprocessing:
    def test_discretization_category_encoding_chain(self):
        keras = tf.keras
        bounds = [0.0, 1.0, 2.0]
        model = keras.Sequential([
            keras.layers.Input((4,)),
            keras.layers.Discretization(bin_boundaries=bounds),
            keras.layers.CategoryEncoding(num_tokens=4,
                                          output_mode="multi_hot"),
        ])
        x = np.asarray([[-1.0, 0.5, 1.5, 3.0],
                        [0.0, 0.0, 2.5, 2.5]], np.float32)
        net = import_keras_model(model)
        golden = model(x).numpy()
        np.testing.assert_allclose(net.output(x), golden, atol=1e-6)

    def test_count_mode(self):
        keras = tf.keras
        model = keras.Sequential([
            keras.layers.Input((5,)),
            keras.layers.CategoryEncoding(num_tokens=3, output_mode="count"),
        ])
        x = np.asarray([[0, 0, 1, 2, 2], [1, 1, 1, 0, 2]], np.float32)
        net = import_keras_model(model)
        np.testing.assert_allclose(net.output(x), model(x).numpy(), atol=1e-6)

    def test_one_hot_mode_squeezes(self):
        keras = tf.keras
        model = keras.Sequential([
            keras.layers.Input((1,)),
            keras.layers.CategoryEncoding(num_tokens=4,
                                          output_mode="one_hot"),
        ])
        x = np.asarray([[0], [2], [3]], np.float32)
        net = import_keras_model(model)
        np.testing.assert_allclose(net.output(x), model(x).numpy(), atol=1e-6)
