#!/usr/bin/env python
"""Kernel autotuner CLI (docs/KERNELS.md) — measure, persist, verify.

    python tools/tune.py --smoke --json     # make tune-smoke / gate stage
    python tools/tune.py                    # full ladders (run on-chip)
    python tools/tune.py --ops dot_product_attention,matmul_int8

Runs ``ops.tuning.autotune`` (AOT-timed candidates, nothing enters the jit
cache), writes the measured table to the tuning cache dir
(``DL4J_TPU_TUNING_DIR``), then VERIFIES the measurement is live: reloads
the table, resolves ``dot_product_attention`` on both sides of the tuned
``flash_min_t`` under forced-pallas mode, and asserts via the
``dl4j_tpu_helper_dispatch_total`` counters that the small shape dispatched
to the XLA generic and the large shape to the Pallas helper. One JSON line
(``"tool": "tune"``) on stdout is the machine contract; exit 0 iff the
table saved and the dispatch proof held.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _verify_dispatch() -> dict:
    """Prove the tuned threshold steers resolve, via the dispatch counters."""
    import jax.numpy as jnp

    import deeplearning4j_tpu.ops  # registers the catalog + helpers
    from deeplearning4j_tpu import observe
    from deeplearning4j_tpu.environment import environment
    from deeplearning4j_tpu.ops import tuning
    from deeplearning4j_tpu.ops.pallas_attention import (
        flash_min_t, reset_flash_min_t_cache)
    from deeplearning4j_tpu.ops.registry import registry

    tuning.reset_tables()  # pick up the table autotune just saved
    reset_flash_min_t_cache()
    threshold = flash_min_t()
    desc = registry().get("dot_product_attention")
    env = environment()
    old = env.helper_mode
    env.helper_mode = "pallas"  # force platform-table resolution off-TPU
    before = dict(observe.dispatch_summary())
    try:
        t_lo = max(threshold // 2, 8)
        t_hi = max(2 * threshold, 16)
        lo = jnp.zeros((2, t_lo, 16), jnp.float32)
        hi = jnp.zeros((2, t_hi, 16), jnp.float32)
        below = desc.resolve(lo, lo, lo)
        above = desc.resolve(hi, hi, hi)
    finally:
        env.helper_mode = old
    after = observe.dispatch_summary()
    delta = {k: after.get(k, 0) - before.get(k, 0) for k in after
             if after.get(k, 0) != before.get(k, 0)}
    below_xla = below is desc.fn and delta.get(
        "dot_product_attention/generic/not_usable", 0) >= 1
    above_pallas = above is desc.platform_impls.get("tpu") and delta.get(
        "dot_product_attention/tpu/usable", 0) >= 1
    return {"flash_min_t": threshold,
            "below_dispatch": "xla" if below_xla else "FAIL",
            "above_dispatch": "pallas" if above_pallas else "FAIL",
            "counters": delta,
            "ok": bool(below_xla and above_pallas)}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shape ladders (seconds on CPU; the gate/"
                         "make tune-smoke mode)")
    ap.add_argument("--json", action="store_true",
                    help="one machine-parsable JSON line on stdout")
    ap.add_argument("--ops", default=None,
                    help="comma-separated op subset (default: all tuners)")
    ap.add_argument("--no-save", action="store_true",
                    help="measure only; do not write the cache table")
    args = ap.parse_args()

    from deeplearning4j_tpu.ops import tuning

    ops = args.ops.split(",") if args.ops else None
    table, report = tuning.autotune(ops=ops, smoke=args.smoke,
                                    save=not args.no_save)

    verify = None
    ok = True
    if not args.no_save and (ops is None or "dot_product_attention" in ops):
        verify = _verify_dispatch()
        ok = verify["ok"]

    line = {"tool": "tune", **report.to_dict(), "smoke": args.smoke,
            "ok": ok}
    if verify is not None:
        line["verify"] = verify
    if args.json:
        print(json.dumps(line, sort_keys=True))
    else:
        print(f"device kind: {report.device_kind}")
        print(f"tuned ops:   {', '.join(report.ops)}")
        print(f"measured:    {report.measurements} candidates in "
              f"{report.seconds}s")
        if report.table_path:
            print(f"table:       {report.table_path}")
        if verify is not None:
            print(f"dispatch:    below->{verify['below_dispatch']} "
                  f"above->{verify['above_dispatch']} "
                  f"(flash_min_t={verify['flash_min_t']})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
