#!/usr/bin/env python
"""Locktrace smoke — runtime cross-validation of the graftlock static
lock-order graph (docs/LINT.md § graftlock, docs/ROBUSTNESS.md § Lock
discipline).

Wraps the REAL locks of a live threaded serving + checkpoint stack in
``testing/locktrace.py`` shadow locks, drives a small workload across
worker threads, and checks the honesty contract:

  * the statically derived lock-order graph is acyclic;
  * every lock-order edge actually OBSERVED at runtime lies inside the
    transitive closure of the static graph (an edge outside it means the
    analyzer's call graph has a blind spot — fix rules_concurrency, do
    not baseline);
  * the union of static and observed edges stays acyclic.

Three legs, one shared tracer:

  frontend    SLOFrontend over a serving GenerativeEngine — admission
              under the frontend RLock reaching the scheduler pending
              lock through submit_request
  cluster     2-engine ClusterRouter under concurrent submitters —
              routing snapshots and engine lifecycle locks
  checkpoint  TrainingCheckpointer with the async writer — the writer
              condition variable and the io lock from both the trainer
              thread and the writer thread

Contract (same as lint/check/chaos): ONE JSON summary line on stdout
with ``"tool": "locktrace"``; exit 0 iff ``ok``. ``make locktrace-smoke``
pins JAX_PLATFORMS=cpu; ``tools/gate.py``'s ``locktrace`` stage enforces
it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import types

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fake_net(value: float, seed: int = 0):
    r = np.random.RandomState(seed)
    net = types.SimpleNamespace()
    net.params = {"W": (r.randn(4, 4) * 0 + value).astype(np.float32)}
    net.opt_state = {"W": np.zeros((4, 4), np.float32)}
    net.net_state = {}
    net.iteration_count = int(value)
    net.epoch_count = 0
    return net


def _instrument_engine(eng, tracer):
    from deeplearning4j_tpu.testing.locktrace import instrument_lock
    instrument_lock(eng, "_lifecycle", "GenerativeEngine._lifecycle",
                    tracer)
    instrument_lock(eng.scheduler, "_plock", "SlotScheduler._plock",
                    tracer)
    if eng.prefix is not None:
        instrument_lock(eng.prefix, "_lock", "RadixPrefixCache._lock",
                        tracer)


def leg_frontend(tracer, n_requests: int) -> dict:
    """SLO admission on a live serving engine: submitter threads push
    through ``SLOFrontend.submit`` (frontend RLock -> scheduler pending
    lock via submit_request) while the engine's worker thread drains."""
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine
    from deeplearning4j_tpu.serving.frontend import SLOFrontend
    from deeplearning4j_tpu.testing.locktrace import instrument_lock

    cfg = GptConfig.tiny(vocab_size=256)
    model = GptModel(cfg, seed=0)
    eng = GenerativeEngine(model, max_slots=2, page_size=8,
                           max_pages_per_seq=6, max_prompt=16, seed=0,
                           default_deadline_s=300.0, restart_backoff_s=0.01)
    eng.generate([np.array([1, 2], np.int32)], max_new_tokens=2,
                 eos_token=-1)  # compile before the clock starts
    _instrument_engine(eng, tracer)
    fe = SLOFrontend(eng)
    instrument_lock(fe, "_lock", "SLOFrontend._lock", tracer)
    eng.start()
    futs: list = []
    futs_mu = threading.Lock()

    def submitter(seed: int) -> None:
        rr = np.random.RandomState(seed)
        for _ in range(n_requests // 2):
            p = rr.randint(1, cfg.vocab_size,
                           size=rr.randint(2, 8)).astype(np.int32)
            f = fe.submit(p, max_new_tokens=4, eos_token=-1)
            with futs_mu:
                futs.append(f)

    threads = [threading.Thread(target=submitter, args=(s,))
               for s in (1, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = [f.result(timeout=300) for f in futs]
    fe.snapshot()
    eng.stop()
    return {"submitted": len(futs),
            "unresolved": sum(1 for f in futs if not f.done()),
            "terminal": len(results)}


def leg_cluster(tracer, n_requests: int) -> dict:
    """Two engines behind a ClusterRouter, two submitter threads — the
    router lock, engine lifecycle locks, and scheduler pending locks all
    live at once."""
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import ClusterRouter, GenerativeEngine
    from deeplearning4j_tpu.testing.locktrace import instrument_lock

    cfg = GptConfig.tiny(vocab_size=256)
    model = GptModel(cfg, seed=0)
    engines = [GenerativeEngine(model, max_slots=2, page_size=8,
                                max_pages_per_seq=6, max_prompt=16,
                                seed=0, default_deadline_s=300.0,
                                restart_backoff_s=0.01)
               for _ in range(2)]
    for e in engines:
        e.generate([np.array([1, 2], np.int32)], max_new_tokens=2,
                   eos_token=-1)
        _instrument_engine(e, tracer)
    router = ClusterRouter(engines)
    instrument_lock(router, "_lock", "ClusterRouter._lock", tracer)
    router.start()
    futs: list = []
    futs_mu = threading.Lock()

    def submitter(seed: int) -> None:
        rr = np.random.RandomState(seed)
        for _ in range(n_requests // 2):
            p = rr.randint(1, cfg.vocab_size,
                           size=rr.randint(2, 8)).astype(np.int32)
            f = router.submit(p, max_new_tokens=4, eos_token=-1)
            with futs_mu:
                futs.append(f)

    threads = [threading.Thread(target=submitter, args=(s,))
               for s in (3, 4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = [f.result(timeout=300) for f in futs]
    router.stop()
    return {"submitted": len(futs),
            "unresolved": sum(1 for f in futs if not f.done()),
            "terminal": len(results)}


def leg_checkpoint(tracer, n_saves: int) -> dict:
    """Async checkpointing: the writer condition variable crossed by the
    trainer thread (submit/backpressure) and the writer thread (drain),
    plus the io lock around record/retention."""
    from deeplearning4j_tpu.parallel.checkpoint import TrainingCheckpointer
    from deeplearning4j_tpu.testing.locktrace import (
        instrument_condition, instrument_lock)

    with tempfile.TemporaryDirectory() as d:
        ck = TrainingCheckpointer(d, keep_last=2, use_orbax=False,
                                  max_queue=2, overflow="block")
        instrument_lock(ck, "_io_lock", "TrainingCheckpointer._io_lock",
                        tracer)
        instrument_condition(ck._writer, "_cv", "_AsyncWriter._cv",
                             tracer)
        for step in range(n_saves):
            ck.save_async(step, _fake_net(float(step)))
        drained = ck.wait_until_finished(timeout=120)
        ck.close()
        return {"saves": n_saves, "drained": bool(drained),
                "failures": len(ck.drain_failures())}


def run(n_requests: int, n_saves: int) -> dict:
    from deeplearning4j_tpu.testing.locktrace import LockTracer

    tracer = LockTracer()
    legs = {
        "frontend": leg_frontend(tracer, n_requests),
        "cluster": leg_cluster(tracer, n_requests),
        "checkpoint": leg_checkpoint(tracer, n_saves),
    }
    report = tracer.check(repo_root=REPO)
    workload_ok = (legs["frontend"]["unresolved"] == 0
                   and legs["cluster"]["unresolved"] == 0
                   and legs["checkpoint"]["drained"]
                   and legs["checkpoint"]["failures"] == 0
                   and len(report["observed_edges"]) > 0)
    return {
        "tool": "locktrace",
        "ok": bool(report["ok"] and workload_ok),
        "static_acyclic": report["static_cycle"] is None,
        "static_edges": report["static_edges"],
        "observed_edges": report["observed_edges"],
        "unknown_edges": report["unknown_edges"],
        "combined_cycle": report["combined_cycle"],
        "legs": legs,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per serving leg")
    ap.add_argument("--saves", type=int, default=6,
                    help="async checkpoint saves")
    args = ap.parse_args()
    summary = run(args.requests, args.saves)
    print(json.dumps(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
