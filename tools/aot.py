#!/usr/bin/env python
"""AOT warm-boot smoke — cold-process restart with and without the cache.

The shape-polymorphic AOT serving gate (docs/SERVING.md § AOT warm
boot): three FRESH child processes run the identical randomized-shape
replay (``serving/replay.py run_randomized_replay`` — prompt lengths
across the whole 1..max_prompt range, prefix cache + speculation armed):

  * **cold** — no ``DL4J_TPU_COMPILE_CACHE``: the plain jit path, every
    compiled fn paid for in-process;
  * **populate** — empty cache dir: every engine fn exports through
    ``jax.export`` into the persistent cache (``serving/aot.py``), and
    the leg runs the exported executables it just stored;
  * **warm** — the now-populated cache in another fresh process: every
    fn restores by deserialization.

Assertions (the acceptance criteria, not a vibe check):

  * the warm leg's ledger records ZERO serving ``first_compile`` events
    — every compiled fn it dispatched arrived as a ``cache_hit``;
  * outputs are **bit-identical** across all three legs (greedy replay,
    same seed — the exported artifact must reproduce the in-process jit
    token-for-token);
  * ZERO ``new_shape`` events on every leg — the symbolic/bucketed
    executables absorb the full shape diversity;
  * warm cold-start TTFT (process boot + first token) is within 2x the
    cache-off leg — restoring must never be slower than recompiling.

Contract (same as lint/check/spec/prefix/...): ONE JSON summary line on
stdout with ``"tool": "aot"``; exit 0 iff ``ok``. ``make aot-smoke``
pins JAX_PLATFORMS=cpu; ``tools/gate.py``'s ``aot`` stage parses the
line. ``--child`` runs a single leg in-process (the mode the parent —
and bench.py's BENCH_COLD_RESTART model — spawns).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ENV_DIR = "DL4J_TPU_COMPILE_CACHE"


def run_child_leg(requests: int, seed: int) -> dict:
    """One replay leg in THIS process (spawned via ``--child``). The
    parent controls the cache through the environment; the engine's
    constructor does the warm boot / export."""
    from deeplearning4j_tpu.serving.replay import run_randomized_replay

    t0 = time.perf_counter()
    out = run_randomized_replay(n_requests=requests, seed=seed)
    return {
        "outputs": out["outputs"],
        "boot_s": out["boot_s"],
        "ttft_first_ms": out["ttft_first_ms"],
        "cold_start_ttft_ms": (
            None if out["ttft_first_ms"] is None
            else round(out["boot_s"] * 1e3 + out["ttft_first_ms"], 3)),
        "first_compile_keys": out["first_compile_keys"],
        "cache_hit_keys": out["cache_hit_keys"],
        "new_shape_events": out["new_shape_events"],
        "all_terminal": all(out["all_terminal"] for _ in (0,)),
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def spawn_leg(leg: str, cache_dir, requests: int, seed: int,
              timeout_s: float = 600.0) -> dict:
    """Run one leg in a FRESH python process — the restart the gate is
    about. Returns the child's JSON record."""
    env = dict(os.environ)
    env.pop(ENV_DIR, None)
    if cache_dir is not None:
        env[ENV_DIR] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", leg,
         "--requests", str(requests), "--seed", str(seed)],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=REPO)
    for ln in proc.stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{") and '"leg"' in ln:
            return json.loads(ln)
    raise RuntimeError(
        f"{leg} leg emitted no record (rc={proc.returncode}): "
        f"{proc.stderr[-800:]}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: exactly one JSON line on stdout")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--cache-dir", default=None,
                    help="reuse (and keep) this cache dir instead of a "
                         "throwaway tempdir")
    ap.add_argument("--child", default=None, metavar="LEG",
                    help=argparse.SUPPRESS)  # internal: run one leg inline
    args = ap.parse_args()

    if args.child:
        rec = run_child_leg(args.requests, args.seed)
        rec["leg"] = args.child
        print(json.dumps(rec), flush=True)
        return 0

    t0 = time.perf_counter()
    tmp = None
    cache_dir = args.cache_dir
    if cache_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="dl4j_tpu_aot_")
        cache_dir = tmp.name
    try:
        cold = spawn_leg("cold", None, args.requests, args.seed)
        populate = spawn_leg("populate", cache_dir, args.requests, args.seed)
        warm = spawn_leg("warm", cache_dir, args.requests, args.seed)
    finally:
        if tmp is not None:
            tmp.cleanup()

    identical = (cold["outputs"] == warm["outputs"]
                 and cold["outputs"] == populate["outputs"])
    warm_first_compiles = warm["first_compile_keys"]
    new_shape = (cold["new_shape_events"] + populate["new_shape_events"]
                 + warm["new_shape_events"])
    all_terminal = all(r["all_terminal"] for r in (cold, populate, warm))
    ttft_cold = cold["cold_start_ttft_ms"]
    ttft_warm = warm["cold_start_ttft_ms"]
    ttft_ok = (ttft_cold is not None and ttft_warm is not None
               and ttft_warm <= 2.0 * ttft_cold)
    ratio = (round(ttft_cold / ttft_warm, 3)
             if ttft_cold and ttft_warm else None)

    ok = (warm_first_compiles == []
          and len(warm["cache_hit_keys"]) > 0
          and identical
          and all_terminal
          and new_shape == 0
          and ttft_ok)

    rec = {
        "tool": "aot", "ok": ok,
        "warm_first_compile_keys": warm_first_compiles,
        "warm_cache_hit_keys": warm["cache_hit_keys"],
        "outputs_identical": identical,
        "all_terminal": all_terminal,
        "new_shape_events": new_shape,
        "cold_restart_ttft_ratio": ratio,
        "ttft_cold_off_ms": ttft_cold,
        "ttft_populate_ms": populate["cold_start_ttft_ms"],
        "ttft_warm_ms": ttft_warm,
        "boot_cold_s": cold["boot_s"],
        "boot_populate_s": populate["boot_s"],
        "boot_warm_s": warm["boot_s"],
        "cold_first_compile_keys": cold["first_compile_keys"],
        "requests_per_leg": args.requests,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(rec), flush=True)
    if not args.json:
        print(f"aot: {'OK' if ok else 'FAIL'} — warm first_compiles="
              f"{warm_first_compiles}, cache_hits={warm['cache_hit_keys']}, "
              f"identical={identical}, new_shape={new_shape}, "
              f"ttft cold/warm={ttft_cold}/{ttft_warm}ms (x{ratio})",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
