#!/usr/bin/env python
"""Shapetrace smoke — runtime cross-validation of the graftshape static
jit-boundary inventory (docs/LINT.md § graftshape).

Snapshots the RecompileLedger, drives two shape-hostile workloads, then
holds every CompileEvent recorded since against the static inventory
(``lint/rules_shape.static_shape_inventory``) via
``testing/shapetrace.py``. The honesty contract:

  * every recompile event's ``callsite`` lands inside a statically known
    ``note_jit_signature`` / ``ledger.record`` registration span — an
    unattributed event means the analyzer's dataflow missed a
    registration path (fix rules_shape, do not baseline);
  * every ``new_shape`` event attributes to a module the static scan
    flagged as a shape hazard — a new_shape out of a statically clean
    module is a broken bucketing contract or an analyzer false negative;
  * leg-local discipline: the randomized-shape serving replay (prefix
    cache + speculation armed, prompt lengths across the whole bucket
    range) retires every request with ZERO serving new_shape, and the
    checkpoint-resumed training leg replays its restore with ZERO mln
    new_shape — resume re-traces nothing.

Two legs, one shared tracer window:

  serving     run_randomized_replay — 1..max_prompt prompt lengths,
              varied generation lengths, shared-prefix mixes
  training    supervised MLN fit -> checkpoint -> restore into a FRESH
              net -> resumed fit over the same batch geometry

Contract (same as lint/check/chaos/locktrace): ONE JSON summary line on
stdout with ``"tool": "shapetrace"``; exit 0 iff ``ok``. ``make
shapetrace-smoke`` pins JAX_PLATFORMS=cpu; ``tools/gate.py``'s
``shapetrace`` stage enforces it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _train_net(seed=7, hidden=16, feat=2, depth=1):
    from deeplearning4j_tpu import nn

    b = (nn.builder().seed(seed).updater(nn.Adam(learning_rate=0.02))
         .weight_init("xavier").list())
    for _ in range(depth):
        b = b.layer(nn.DenseLayer(n_out=hidden, activation="tanh"))
    return nn.MultiLayerNetwork(
        b.layer(nn.OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(feat)).build()).init()


def _train_data(n=96, seed=0, feat=2):
    r = np.random.RandomState(seed)
    x = r.rand(n, feat).astype(np.float32)
    y = np.zeros((n, 2), np.float32)
    y[np.arange(n), r.randint(0, 2, n)] = 1.0
    return x, y


def leg_serving(n_requests: int) -> dict:
    """The randomized-shape replay: arbitrary request geometry, one
    bucketing contract. Zero serving new_shape or the leg fails."""
    from deeplearning4j_tpu.serving.replay import run_randomized_replay

    out = run_randomized_replay(n_requests=n_requests)
    return {
        "requests": out["requests"],
        "distinct_prompt_lens": len(out["prompt_lens"]),
        "generated_tokens": out["generated_tokens"],
        "prefix_hit_tokens": out["prefix_hit_tokens"],
        "first_compile_keys": out["first_compile_keys"],
        "all_terminal": out["all_terminal"],
        "new_shape_events": out["new_shape_events"],
        "ok": bool(out["all_terminal"]
                   and out["new_shape_events"] == 0
                   and len(out["prompt_lens"]) >= 4),
    }


def leg_training(epochs: int) -> dict:
    """Checkpoint-resumed training: fit, save, restore into a FRESH net,
    resume over the same batch geometry. The resumed fit must re-trace
    NOTHING — zero mln new_shape across the whole leg."""
    from deeplearning4j_tpu import observe
    from deeplearning4j_tpu.parallel import TrainingCheckpointer

    x, y = _train_data()
    batch = 16  # 96/16 = 6 exact batches — one jit signature, no tail

    def mln_new_shape():
        return sum(1 for e in observe.ledger().events()
                   if e.graph == "mln" and e.cause == "new_shape")

    before = mln_new_shape()
    net = _train_net()
    net.fit(x, y, epochs=epochs, batch_size=batch)
    with tempfile.TemporaryDirectory(prefix="shapetrace_train_") as d:
        ck = TrainingCheckpointer(d, keep_last=2, use_orbax=False)
        ck.save(net.iteration_count, net)
        fresh = _train_net(seed=11)
        step = ck.restore(fresh)
        resumed_from = step
        fresh.fit(x, y, epochs=epochs, batch_size=batch)
    params_match_shape = (net.params_flat().shape
                          == fresh.params_flat().shape)
    new_shape = mln_new_shape() - before
    return {
        "epochs": epochs,
        "batch": batch,
        "resumed_from_step": resumed_from,
        "params_shape_match": bool(params_match_shape),
        "new_shape_events": int(new_shape),
        "ok": bool(resumed_from is not None and params_match_shape
                   and new_shape == 0),
    }


def run(n_requests: int, epochs: int) -> dict:
    from deeplearning4j_tpu.lint.rules_shape import static_shape_inventory
    from deeplearning4j_tpu.testing.shapetrace import ShapeTracer

    tracer = ShapeTracer()
    legs = {
        "serving": leg_serving(n_requests),
        "training": leg_training(epochs),
    }
    inventory = static_shape_inventory(REPO)
    report = tracer.check(REPO, inventory=inventory)
    legs_ok = all(leg["ok"] for leg in legs.values())
    # the window must actually contain ledger traffic for the
    # cross-validation to mean anything
    exercised = report["events"] > 0
    return {
        "tool": "shapetrace",
        "ok": bool(report["ok"] and legs_ok and exercised),
        "events": report["events"],
        "by_cause": report["by_cause"],
        "external": report["external"],
        "unattributed": report["unattributed"],
        "new_shape_total": report["new_shape_total"],
        "new_shape_unexplained": report["new_shape_unexplained"],
        "static": {
            "jit_sites": report["jit_sites"],
            "registration_span_files": report["registration_span_files"],
            "hazard_modules": report["hazard_modules"],
            "clean_modules": report["clean_modules"],
        },
        "legs": legs,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=16,
                    help="requests on the randomized-shape serving leg")
    ap.add_argument("--epochs", type=int, default=2,
                    help="epochs per training-leg fit")
    args = ap.parse_args()
    summary = run(args.requests, args.epochs)
    print(json.dumps(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
