"""Profile the ResNet-50 train step on the real chip (VERDICT r3 item 1).

Prints XLA cost analysis (flops, bytes) for the fused train step, measures
achieved step time over a scanned window, derives MFU against the device
peak, and optionally captures a jax.profiler trace for op-level analysis.

Usage: python tools/profile_resnet.py [--batch 128] [--image 224]
       [--trace /tmp/rn50_trace] [--dtype mixed]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


# bf16 peak matmul TFLOP/s by TPU generation (public spec sheets)
PEAK_TFLOPS = {
    "v5 lite": 197.0,  # v5e
    "v5litepod": 197.0,
    "v4": 275.0,
    "v5p": 459.0,
    "v6e": 918.0,
}


def device_peak_tflops() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for k, v in PEAK_TFLOPS.items():
        if k in kind:
            return v
    return 197.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--iters", type=int, default=60)
    ap.add_argument("--dtype", default="mixed")
    ap.add_argument("--trace", default=None,
                    help="directory to write a jax.profiler trace into")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import models, nn
    from deeplearning4j_tpu.datasets.image import synthetic_image_batch

    net = models.ResNet50(num_classes=1000,
                          input_shape=(args.image, args.image, 3),
                          updater=nn.Nesterovs(learning_rate=0.1, momentum=0.9),
                          dtype=args.dtype).init()
    imgs, labels = synthetic_image_batch(args.batch, args.image, args.image, 3,
                                         1000, seed=0)
    y = np.zeros((args.batch, 1000), np.float32)
    y[np.arange(args.batch), labels] = 1.0
    x = jnp.asarray(imgs)
    yj = jnp.asarray(y)

    # warm (compile)
    t0 = time.perf_counter()
    losses = net.fit_scanned(x, yj, steps=args.iters)
    print(f"compile+first run: {time.perf_counter() - t0:.1f}s "
          f"loss={float(losses[-1]):.3f}")

    t0 = time.perf_counter()
    losses = net.fit_scanned(x, yj, steps=args.iters)
    dt = time.perf_counter() - t0
    step_ms = dt / args.iters * 1e3
    img_s = args.batch * args.iters / dt
    print(f"steady: {step_ms:.2f} ms/step  {img_s:.1f} img/s")

    # analytic FLOPs: ResNet-50 fwd ~4.1 GFLOP @224; train ~3x fwd
    gflop_per_img = 4.1 * 3 * (args.image / 224) ** 2
    achieved = img_s * gflop_per_img / 1e3  # TFLOP/s
    peak = device_peak_tflops()
    print(f"analytic: {achieved:.1f} TFLOP/s of {peak:.0f} peak "
          f"-> MFU {achieved / peak * 100:.1f}%")

    # XLA's own numbers for ONE jitted step (not the scanned loop)
    step_fn = net._jit_cache.get("train_step") or net._make_train_step()
    in_name = net.conf.network_inputs[0]
    out_name = net.conf.network_outputs[0]
    lowered = jax.jit(step_fn).lower(
        net.params, net.opt_state, net.net_state,
        jnp.asarray(0, jnp.int32), jax.random.key(0),
        {in_name: x}, {out_name: yj}, None, None)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    flops = cost.get("flops", 0.0)
    bytes_ = cost.get("bytes accessed", 0.0)
    print(f"xla cost: {flops / 1e12:.2f} TFLOP/step, "
          f"{bytes_ / 1e9:.2f} GB accessed/step")
    if flops and bytes_:
        # roofline: time if compute-bound vs if HBM-bound (v5e ~819 GB/s)
        t_comp = flops / (peak * 1e12) * 1e3
        t_mem = bytes_ / (819e9) * 1e3
        print(f"roofline: compute {t_comp:.2f} ms vs memory {t_mem:.2f} ms "
              f"(measured {step_ms:.2f} ms)")

    if args.trace:
        with jax.profiler.trace(args.trace):
            net.fit_scanned(x, yj, steps=4)
        print(f"trace written to {args.trace}")


if __name__ == "__main__":
    main()
