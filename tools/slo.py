#!/usr/bin/env python
"""SLO smoke — goodput under overload, frontend on vs off.

The ROADMAP 2(d) gate stage (docs/SERVING.md § SLO admission frontend):
run the shared overload ramp (``serving/overload.py``) twice — once
through the :class:`SLOFrontend`, once against raw ``engine.submit`` —
with an IDENTICAL offered schedule, and assert the frontend earns its
place instead of trusting it:

  * frontend-on **goodput** (completed-within-deadline tokens/sec) >=
    frontend-off goodput under a >= 2× capacity open-loop ramp;
  * every submitted request (including injected burst arrivals) reaches a
    TERMINAL state on both legs — shed/deadline are results, not hangs;
  * the degradation ladder actually engaged (states beyond ``ok``
    visited) — an overload run that never left ``ok`` proved nothing;
  * ZERO ``new_shape`` RecompileLedger serving events on either leg —
    degradation transitions must never cost a recompile.

Contract (same as lint/check/obs/tune/chaos): ONE JSON summary line on
stdout with ``"tool": "slo"``; exit 0 iff ``ok``. ``make slo-smoke`` pins
JAX_PLATFORMS=cpu; ``tools/gate.py``'s ``slo`` stage parses the line.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: exactly one JSON line on stdout")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--tokens", type=int, default=12)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--factor", type=float, default=3.0,
                    help="offered load as a multiple of measured capacity")
    ap.add_argument("--trials", type=int, default=3,
                    help="paired on/off trials; the MEDIAN goodputs are "
                         "compared (host-load spikes hit single trials)")
    args = ap.parse_args()

    from deeplearning4j_tpu.serving.overload import run_overload_ramp

    t0 = time.perf_counter()
    # throwaway warm-up leg: the FIRST ramp in a process absorbs the
    # slow early XLA steps into its latency signal — neither measured
    # leg should pay that, whichever runs first. Measured legs run with
    # slow_decode armed (deterministic 50ms service floor) so the on/off
    # comparison survives a noisy shared CPU; paired trials + median
    # absorb whole-host load spikes that hit one trial.
    run_overload_ramp(frontend_on=False, n_requests=3,
                      gen_tokens=args.tokens, max_slots=args.slots,
                      overload_factor=args.factor)
    cap = None
    ons, offs = [], []
    for _ in range(max(1, args.trials)):
        on = run_overload_ramp(
            frontend_on=True, n_requests=args.requests,
            gen_tokens=args.tokens, max_slots=args.slots,
            overload_factor=args.factor, slow_decode=True,
            capacity_tokens_per_sec=cap)
        cap = on["capacity_tokens_per_sec"]  # one schedule for ALL legs
        off = run_overload_ramp(
            frontend_on=False, n_requests=args.requests,
            gen_tokens=args.tokens, max_slots=args.slots,
            overload_factor=args.factor, slow_decode=True,
            capacity_tokens_per_sec=cap)
        ons.append(on)
        offs.append(off)

    g_on = statistics.median(r["goodput_tokens_per_sec"] for r in ons)
    g_off = statistics.median(r["goodput_tokens_per_sec"] for r in offs)
    on, off = ons[-1], offs[-1]  # full detail from the last pair
    all_terminal = all(r["all_terminal"] for r in ons + offs)
    new_shape = sum(r["new_shape_events"] for r in ons + offs)
    ladder_engaged = any(s != "ok"
                         for r in ons for s in r.get("states_visited", []))
    ok = (g_on >= g_off
          and all_terminal
          and ladder_engaged
          and new_shape == 0)

    rec = {
        "tool": "slo", "ok": ok,
        "goodput_on": g_on, "goodput_off": g_off,
        "goodput_ratio": round(g_on / g_off, 3) if g_off else None,
        "strictly_better": g_on > g_off,
        "overload_factor": args.factor,
        "trials": len(ons),
        "goodput_on_trials": [r["goodput_tokens_per_sec"] for r in ons],
        "goodput_off_trials": [r["goodput_tokens_per_sec"] for r in offs],
        "ladder_engaged": ladder_engaged,
        "all_terminal": all_terminal,
        "new_shape_events": new_shape,
        "frontend_on": on, "frontend_off": off,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(rec), flush=True)
    if not args.json:
        print(f"slo: {'OK' if ok else 'FAIL'} — goodput on/off "
              f"{g_on}/{g_off} tok/s at {args.factor}x capacity, states "
              f"{on.get('states_visited')}, reasons on={on['reasons']} "
              f"off={off['reasons']}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
