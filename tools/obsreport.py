#!/usr/bin/env python
"""obsreport — summarize the runtime telemetry of a run (docs/OBSERVABILITY.md).

Two modes:

* default / ``--json``: run the built-in smoke workload — a small
  MultiLayerNetwork fit (two batch shapes, so the recompile ledger records
  both a ``first_compile`` and a ``new_shape`` event) plus a multithreaded
  ``ParallelInference`` serving burst — then print a human report (or, with
  ``--json``, ONE machine-parsable line: the gate-stage contract, same as
  lint/check). This is the acceptance probe: nonzero step counts, at least
  one recompile event with a cause, serving p50/p99.
* ``--log PATH``: summarize an existing ``DL4J_TPU_OBS_LOG`` JSONL file
  instead of running anything (post-hoc analysis of a training/serving run).

Backend safety: the default JAX backend is probed in a subprocess with a
timeout (bench.py's PR-2 hardening) and the process pins itself to CPU when
the probe fails, so an unreachable TPU degrades to a CPU smoke run instead
of a hang.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter as _Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# ONE backend probe for the whole repo: bench.py owns the subprocess-probe/
# CPU-fallback logic (PR 2); reuse it instead of growing a drifting copy
from bench import _ensure_backend  # noqa: E402


def _demo_workload() -> None:
    """Small MLN fit (two feed shapes) + concurrent ParallelInference."""
    import threading

    import numpy as np

    from deeplearning4j_tpu import nn
    from deeplearning4j_tpu.parallel.mesh import ParallelInference

    n_in, n_out = 8, 4
    conf = (nn.builder().seed(0).updater(nn.Adam(learning_rate=1e-2)).list()
            .layer(nn.DenseLayer(n_out=16, activation="relu"))
            .layer(nn.OutputLayer(n_out=n_out, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(n_in)).build())
    net = nn.MultiLayerNetwork(conf).init()
    r = np.random.RandomState(0)
    x = r.randn(64, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[r.randint(0, n_out, 64)]
    net.fit(x, y, epochs=2, batch_size=16)          # first_compile @ b=16
    net.fit(x[:48], y[:48], epochs=1, batch_size=24)  # new_shape @ b=24

    pi = ParallelInference(net, max_batch=8, window_ms=2.0).start()
    errors = []
    try:
        pi.predict(x[0])  # warm the compiled serving path

        def client(seed: int) -> None:
            rr = np.random.RandomState(seed)
            try:
                for _ in range(8):
                    out = pi.predict(rr.randn(n_in).astype(np.float32))
                    assert out.shape[-1] == n_out
            except Exception as e:  # re-raised below: a dead serving path
                errors.append(e)    # must fail the smoke, not pass it
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        pi.stop()
    if errors:
        raise RuntimeError(f"{len(errors)} serving client(s) failed: "
                           f"{errors[0]!r}")


def _fmt_ms(v) -> str:
    return "n/a" if v is None else f"{v:.2f} ms"


def _report(backend: str) -> dict:
    """Assemble the summary dict from the live registry/ledger."""
    from deeplearning4j_tpu import observe

    s = observe.summary()
    events = [ev.to_dict() for ev in observe.ledger().events()]
    return {"backend": backend, "summary": s, "recompile_events": events}


def _print_human(rep: dict) -> None:
    s = rep["summary"]
    print("== dl4j-tpu observability report ==")
    print(f"backend: {rep['backend']}")
    tr = s.get("train")
    if tr:
        print(f"train: {tr['steps']} steps, {tr['examples']} examples; "
              f"step latency p50 {_fmt_ms(tr['step_p50_ms'])}, "
              f"p95 {_fmt_ms(tr['step_p95_ms'])}, "
              f"p99 {_fmt_ms(tr['step_p99_ms'])}")
    rec = s.get("recompiles")
    if rec:
        causes = ", ".join(f"{k}: {v}"
                           for k, v in sorted(rec["by_cause"].items()))
        print(f"recompiles: {rec['total']} total ({causes})")
        for ev in rep["recompile_events"][-10:]:
            extra = ""
            if ev.get("compile_seconds") is not None:
                extra = (f"  trace {ev.get('trace_seconds')}s"
                         f" compile {ev.get('compile_seconds')}s")
            print(f"  [{ev['seq']}] {ev['graph']}/{ev['key']} "
                  f"cause={ev['cause']} sig={ev['signature']}{extra}")
    sv = s.get("serving")
    if sv:
        print(f"serving: {sv['requests']} requests in {sv['batches']} "
              f"batches; latency p50 {_fmt_ms(sv['p50_ms'])}, "
              f"p95 {_fmt_ms(sv['p95_ms'])}, p99 {_fmt_ms(sv['p99_ms'])}; "
              f"batch occupancy mean {sv['batch_occupancy_mean']}")
    disp = s.get("dispatch")
    if disp:
        print("helper dispatch (op/impl/reason):")
        for key, count in disp.items():
            print(f"  {key}: {count}")
    if not (tr or rec or sv or disp):
        print("no telemetry recorded (did the workload run?)")


def _summarize_log(path: str, json_mode: bool) -> int:
    """Post-hoc summary of a DL4J_TPU_OBS_LOG JSONL file."""
    kinds: "_Counter[str]" = _Counter()
    causes: "_Counter[str]" = _Counter()
    fusion_hits: "_Counter[str]" = _Counter()
    train_steps = 0
    serving_rows = 0
    bad = 0
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            kind = rec.get("kind", "?")
            kinds[kind] += 1
            if kind == "recompile":
                causes[rec.get("cause", "?")] += 1
                # fusion-tier hits ride the recompile event (CompileEvent
                # carries the live OptimizeStats.fusions section)
                for fk, fv in (rec.get("fusions") or {}).items():
                    fusion_hits[fk] += int(fv)
            elif kind == "train_epoch":
                train_steps += int(rec.get("steps", 0))
            elif kind == "serving_batch":
                serving_rows += int(rec.get("rows", 0))
    out = {"tool": "obsreport", "log": path, "events": sum(kinds.values()),
           "by_kind": dict(kinds), "recompile_causes": dict(causes),
           "fusion_hits": dict(fusion_hits),
           "train_steps": train_steps, "serving_rows": serving_rows,
           "unparsable_lines": bad}
    if json_mode:
        print(json.dumps(out, sort_keys=True))
    else:
        print(f"== obs log summary: {path} ==")
        for k, v in sorted(kinds.items()):
            print(f"  {k}: {v}")
        if causes:
            print("  recompile causes: "
                  + ", ".join(f"{k}={v}" for k, v in sorted(causes.items())))
        if fusion_hits:
            print("  fusion hits: "
                  + ", ".join(f"{k}={v}"
                              for k, v in sorted(fusion_hits.items())))
        print(f"  train steps: {train_steps}; serving rows: {serving_rows}")
        if bad:
            print(f"  WARNING: {bad} unparsable lines")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="one machine-parsable JSON line (gate contract)")
    ap.add_argument("--log", metavar="PATH",
                    help="summarize an existing DL4J_TPU_OBS_LOG JSONL file "
                         "instead of running the smoke workload")
    args = ap.parse_args()

    if args.log:
        return _summarize_log(args.log, args.json)

    backend = _ensure_backend()
    _demo_workload()
    rep = _report(backend)

    if args.json:
        s = rep["summary"]
        tr = s.get("train") or {}
        sv = s.get("serving") or {}
        rec = s.get("recompiles") or {}
        line = {"tool": "obsreport", "backend": backend,
                "train_steps": tr.get("steps", 0),
                "step_p99_ms": tr.get("step_p99_ms"),
                "recompiles": rec.get("total", 0),
                "recompile_causes": rec.get("by_cause", {}),
                "serving_requests": sv.get("requests", 0),
                "serving_p50_ms": sv.get("p50_ms"),
                "serving_p99_ms": sv.get("p99_ms")}
        ok = (line["train_steps"] > 0 and line["recompiles"] > 0
              and line["serving_requests"] > 0
              and line["serving_p99_ms"] is not None)
        line["ok"] = ok
        print(json.dumps(line, sort_keys=True))
        return 0 if ok else 1
    _print_human(rep)
    return 0


if __name__ == "__main__":
    sys.exit(main())
