#!/usr/bin/env python
"""Speculative-decoding smoke — greedy replay, spec on vs off.

The ROADMAP 2(b) gate stage (docs/SERVING.md § Speculative decoding):
run the replay harness (``serving/replay.py``) twice — once with
draft-propose/target-verify speculation, once with the plain one-token
decode loop, IDENTICAL greedy request plan, both under the deterministic
50ms ``slow_decode`` target-step floor — and assert speculation earns
its place instead of trusting it:

  * **accepted draft tokens > 0** (a replay that never accepted proved
    nothing — and would have LOST throughput to draft overhead);
  * **tokens/sec >= spec-off** (median of paired trials — host-load
    spikes hit single trials);
  * greedy outputs **bit-identical** on both legs — acceptance, the
    correction token, and rollback must reproduce non-speculative greedy
    decoding token-for-token (the lossless property);
  * EXACTLY the expected ``first_compile`` ledger events on each leg
    (on: prefill + write_prompt + draft_prefill + draft_decode + verify;
    off: prefill + write_prompt + decode) and ZERO ``new_shape`` events
    — speculation rides two extra
    compiled functions, it never recompiles across admits/evicts/
    rejections;
  * allocator + draft/target length invariants hold after every leg
    (checked inside the harness) and every request retires complete.

Contract (same as lint/check/obs/tune/chaos/slo/prefix): ONE JSON
summary line on stdout with ``"tool": "spec"``; exit 0 iff ``ok``.
``make spec-smoke`` pins JAX_PLATFORMS=cpu; ``tools/gate.py``'s ``spec``
stage parses the line.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: the ledger contract per leg — any drift (a surprise recompile, a
#: silently-dead path) fails the stage
EXPECTED_ON = ["draft_decode", "draft_prefill", "prefill", "verify",
               "write_prompt"]
EXPECTED_OFF = ["decode", "prefill", "write_prompt"]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: exactly one JSON line on stdout")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--tokens", type=int, default=12)
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--trials", type=int, default=3,
                    help="paired on/off trials; MEDIAN tokens/sec are "
                         "compared (host-load spikes hit single trials)")
    args = ap.parse_args()

    from deeplearning4j_tpu.serving.replay import run_spec_replay

    t0 = time.perf_counter()
    ons, offs = [], []
    for trial in range(max(1, args.trials)):
        ons.append(run_spec_replay(
            spec_on=True, n_requests=args.requests,
            gen_tokens=args.tokens, spec_k=args.spec_k, seed=trial))
        offs.append(run_spec_replay(
            spec_on=False, n_requests=args.requests,
            gen_tokens=args.tokens, spec_k=args.spec_k, seed=trial))

    tps_on = statistics.median(r["tokens_per_sec"] for r in ons)
    tps_off = statistics.median(r["tokens_per_sec"] for r in offs)
    speedup = tps_on / tps_off if tps_off else 0.0
    accepted = sum(r["accepted_tokens"] for r in ons)
    proposed = sum(r["proposed_tokens"] for r in ons)
    identical = all(a["outputs"] == b["outputs"]
                    for a, b in zip(ons, offs))
    all_terminal = all(r["all_terminal"] for r in ons + offs)
    new_shape = sum(r["new_shape_events"] for r in ons + offs)
    compiles_ok = (all(r["first_compile_keys"] == EXPECTED_ON for r in ons)
                   and all(r["first_compile_keys"] == EXPECTED_OFF
                           for r in offs))

    ok = (accepted > 0
          and identical
          and all_terminal
          and speedup >= 1.0
          and new_shape == 0
          and compiles_ok)

    on = ons[-1]  # full detail from the last pair
    rec = {
        "tool": "spec", "ok": ok,
        "tokens_per_sec_on": tps_on, "tokens_per_sec_off": tps_off,
        "speedup": round(speedup, 3),
        "spec_k": args.spec_k,
        "accepted_tokens": accepted,
        "proposed_tokens": proposed,
        "acceptance_rate": round(accepted / proposed, 4) if proposed
        else None,
        "requests_per_leg": args.requests,
        "trials": len(ons),
        "tps_on_trials": [r["tokens_per_sec"] for r in ons],
        "tps_off_trials": [r["tokens_per_sec"] for r in offs],
        "outputs_identical": identical,
        "all_terminal": all_terminal,
        "new_shape_events": new_shape,
        "first_compiles_ok": compiles_ok,
        "first_compile_keys_on": on["first_compile_keys"],
        "reasons_on": on["reasons"], "reasons_off": offs[-1]["reasons"],
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(rec), flush=True)
    if not args.json:
        print(f"spec: {'OK' if ok else 'FAIL'} — {tps_on}/{tps_off} tok/s "
              f"on/off (x{rec['speedup']}), {accepted}/{proposed} draft "
              f"tokens accepted, identical={identical}, "
              f"new_shape={new_shape}, compiles_ok={compiles_ok}",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
