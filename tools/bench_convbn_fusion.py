"""Measure the Pallas fused BN→matmul→stats kernel vs the unfused XLA chain.

The round-4 perf analysis claimed ResNet-50 is bound by BN activation
traffic but could not prove it (cost_analysis bytes overcount fusion
reuse).  This tool produces the kernel evidence: for each real
bottleneck 1×1-conv shape of ResNet-50 @ b128 it times

  * the unfused chain   (BN-affine+relu pass → XLA matmul → stats pass)
  * the Pallas kernel   (one HBM pass, prologue/epilogue fused)

on the real chip (device-side lax.scan loop; wall timing of single
dispatches through the axon tunnel is noise), and prints XLA
cost-analysis bytes for both so the traffic delta is explicit.

Besides the human table, the tool emits a TUNING-TABLE FRAGMENT (the
ops/tuning.py dl4j_tpu_tuning_v1 schema): the best-measured Pallas block_m
per shape bucket. Fragments are NOT loaded automatically — merge one into
the committed default table or into <cache dir>/<device_kind>.json (the
file the loader reads) via ``TuningTable.merge`` so the kernel's block
picker uses the measured winners (docs/KERNELS.md § Re-tuning).
Fragment path: SWEEP_TABLE_OUT env, default
<cache dir>/fragment_convbn_<device_kind>.json.

Usage: python tools/bench_convbn_fusion.py [--iters 50] [--blocks 256,512]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# (label, M, K, N) — every distinct 1×1 conv+BN shape in ResNet-50 @ b128
SHAPES = [
    ("s1_c1", 128 * 56 * 56, 256, 64),
    ("s1_c3", 128 * 56 * 56, 64, 256),
    ("s2_c1", 128 * 28 * 28, 512, 128),
    ("s2_c3", 128 * 28 * 28, 128, 512),
    ("s3_c1", 128 * 14 * 14, 1024, 256),
    ("s3_c3", 128 * 14 * 14, 256, 1024),
    ("s4_c1", 128 * 7 * 7, 2048, 512),
    ("s4_c3", 128 * 7 * 7, 512, 2048),
]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--shapes", default=None,
                    help="comma-separated labels to run (default: all)")
    ap.add_argument("--blocks", default="0,256,512",
                    help="comma-separated block_m candidates for the Pallas "
                         "kernel (0 = the kernel's own pick)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_convbn import (
        fused_bn_matmul_stats, reference_bn_matmul_stats)

    want = set(args.shapes.split(",")) if args.shapes else None
    results = []
    for label, m, k, n in SHAPES:
        if want and label not in want:
            continue
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(m, k).astype(np.float32)).astype(jnp.bfloat16)
        sc = jnp.asarray(r.rand(k).astype(np.float32) + 0.5)
        sh = jnp.asarray(r.randn(k).astype(np.float32) * 0.1)
        w = jnp.asarray((r.randn(k, n) * k ** -0.5).astype(np.float32)).astype(jnp.bfloat16)
        ss = jnp.asarray(r.randn(n).astype(np.float32) * 0.1)

        def make(fn):
            @jax.jit
            def bench(x, sc, sh, w, ss, eps):
                # chain each iteration through the (tiny) stats vector with a
                # runtime-zero eps, and probe one column of z — XLA cannot
                # fold either away (a literal *0 gets DCE'd and the first
                # version of this bench measured empty scans)
                def body(carry, _):
                    z, mean, var = fn(x, sc, sh, w, carry)
                    probe = jnp.sum(z[:, :1].astype(jnp.float32))
                    return carry + eps * (mean + var + probe), probe
                c, ps = jax.lax.scan(body, ss, None, length=args.iters)
                return jnp.sum(c), ps[-1]
            return bench

        def run(bench):
            zero = jnp.float32(0.0)
            _ = jax.block_until_ready(bench(x, sc, sh, w, ss, zero))  # compile
            t0 = time.perf_counter()
            _ = jax.block_until_ready(bench(x, sc, sh, w, ss, zero))
            return (time.perf_counter() - t0) / args.iters * 1e3

        def cost_bytes(fn):
            lowered = jax.jit(lambda x, sc, sh, w, ss: fn(x, sc, sh, w, ss)
                              ).lower(x, sc, sh, w, ss)
            c = lowered.compile().cost_analysis()
            if isinstance(c, list):
                c = c[0]
            return c.get("bytes accessed", 0.0)

        import functools
        ref = functools.partial(reference_bn_matmul_stats, materialize=True)
        t_ref = run(make(ref))
        # block-candidate sweep for the Pallas kernel: the best block_m per
        # shape bucket lands in the tuning fragment. Candidates that do not
        # divide this shape's m are skipped; if none survive, fall back to
        # the kernel's own pick (0) so one ragged shape cannot kill the run
        t_fused, best_bm = None, 0
        cands = [int(b) for b in args.blocks.split(",")]
        if not any(not bm or m % bm == 0 for bm in cands):
            cands = [0]
        for bm in cands:
            if bm and m % bm:
                continue
            t = run(make(functools.partial(fused_bn_matmul_stats,
                                           block_m=bm)))
            if t_fused is None or t < t_fused:
                t_fused, best_bm = t, bm
        by_ref = cost_bytes(ref)
        # cost analysis must describe the SAME configuration that was timed
        by_fused = cost_bytes(functools.partial(fused_bn_matmul_stats,
                                                block_m=best_bm))
        # one-pass ideal traffic: read x + w, write z (+ stats, negligible)
        ideal = (m * k + k * n + m * n) * 2
        row = {"shape": label, "m": m, "k": k, "n": n,
               "xla_ms": round(t_ref, 3), "pallas_ms": round(t_fused, 3),
               "best_block_m": best_bm,
               "speedup": round(t_ref / t_fused, 3),
               "xla_bytes_mb": round(by_ref / 1e6, 1),
               "pallas_bytes_mb": round(by_fused / 1e6, 1),
               "ideal_bytes_mb": round(ideal / 1e6, 1)}
        results.append(row)
        print(json.dumps(row))

    if results:
        tot_x = sum(r["xla_ms"] for r in results)
        tot_p = sum(r["pallas_ms"] for r in results)
        print(json.dumps({"total_xla_ms": round(tot_x, 2),
                          "total_pallas_ms": round(tot_p, 2),
                          "speedup": round(tot_x / tot_p, 3)}))

        # tuning-table fragment (ops/tuning.py schema): measured block_m
        # winners per shape bucket for this device kind
        from deeplearning4j_tpu.ops import tuning

        # justified: runs after the sweep already exercised the backend
        kind = tuning.normalize_device_kind(jax.devices()[0].device_kind)  # graftlint: disable=GL002
        frag = tuning.TuningTable(device_kind=kind)
        for r in results:
            if r["best_block_m"]:
                frag.set_block("fused_bn_matmul_stats",
                               tuning.bucket_mkn(r["m"], r["k"], r["n"]),
                               "block_m", r["best_block_m"])
        out_path = os.environ.get(
            "SWEEP_TABLE_OUT",
            os.path.join(tuning.tuning_dir(),
                         f"fragment_convbn_{kind}.json"))
        frag.save(out_path)
        print(f"tuning fragment -> {out_path}")


if __name__ == "__main__":
    main()
