"""Measure the Pallas fused BN→matmul→stats kernel vs the unfused XLA chain.

The round-4 perf analysis claimed ResNet-50 is bound by BN activation
traffic but could not prove it (cost_analysis bytes overcount fusion
reuse).  This tool produces the kernel evidence: for each real
bottleneck 1×1-conv shape of ResNet-50 @ b128 it times

  * the unfused chain   (BN-affine+relu pass → XLA matmul → stats pass)
  * the Pallas kernel   (one HBM pass, prologue/epilogue fused)

on the real chip (device-side lax.scan loop; wall timing of single
dispatches through the axon tunnel is noise), and prints XLA
cost-analysis bytes for both so the traffic delta is explicit.

Usage: python tools/bench_convbn_fusion.py [--iters 50]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


# (label, M, K, N) — every distinct 1×1 conv+BN shape in ResNet-50 @ b128
SHAPES = [
    ("s1_c1", 128 * 56 * 56, 256, 64),
    ("s1_c3", 128 * 56 * 56, 64, 256),
    ("s2_c1", 128 * 28 * 28, 512, 128),
    ("s2_c3", 128 * 28 * 28, 128, 512),
    ("s3_c1", 128 * 14 * 14, 1024, 256),
    ("s3_c3", 128 * 14 * 14, 256, 1024),
    ("s4_c1", 128 * 7 * 7, 2048, 512),
    ("s4_c3", 128 * 7 * 7, 512, 2048),
]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--shapes", default=None,
                    help="comma-separated labels to run (default: all)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_convbn import (
        fused_bn_matmul_stats, reference_bn_matmul_stats)

    want = set(args.shapes.split(",")) if args.shapes else None
    results = []
    for label, m, k, n in SHAPES:
        if want and label not in want:
            continue
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(m, k).astype(np.float32)).astype(jnp.bfloat16)
        sc = jnp.asarray(r.rand(k).astype(np.float32) + 0.5)
        sh = jnp.asarray(r.randn(k).astype(np.float32) * 0.1)
        w = jnp.asarray((r.randn(k, n) * k ** -0.5).astype(np.float32)).astype(jnp.bfloat16)
        ss = jnp.asarray(r.randn(n).astype(np.float32) * 0.1)

        def make(fn):
            @jax.jit
            def bench(x, sc, sh, w, ss, eps):
                # chain each iteration through the (tiny) stats vector with a
                # runtime-zero eps, and probe one column of z — XLA cannot
                # fold either away (a literal *0 gets DCE'd and the first
                # version of this bench measured empty scans)
                def body(carry, _):
                    z, mean, var = fn(x, sc, sh, w, carry)
                    probe = jnp.sum(z[:, :1].astype(jnp.float32))
                    return carry + eps * (mean + var + probe), probe
                c, ps = jax.lax.scan(body, ss, None, length=args.iters)
                return jnp.sum(c), ps[-1]
            return bench

        def run(bench):
            zero = jnp.float32(0.0)
            _ = jax.block_until_ready(bench(x, sc, sh, w, ss, zero))  # compile
            t0 = time.perf_counter()
            _ = jax.block_until_ready(bench(x, sc, sh, w, ss, zero))
            return (time.perf_counter() - t0) / args.iters * 1e3

        def cost_bytes(fn):
            lowered = jax.jit(lambda x, sc, sh, w, ss: fn(x, sc, sh, w, ss)
                              ).lower(x, sc, sh, w, ss)
            c = lowered.compile().cost_analysis()
            if isinstance(c, list):
                c = c[0]
            return c.get("bytes accessed", 0.0)

        import functools
        ref = functools.partial(reference_bn_matmul_stats, materialize=True)
        t_ref = run(make(ref))
        t_fused = run(make(fused_bn_matmul_stats))
        by_ref = cost_bytes(ref)
        by_fused = cost_bytes(fused_bn_matmul_stats)
        # one-pass ideal traffic: read x + w, write z (+ stats, negligible)
        ideal = (m * k + k * n + m * n) * 2
        row = {"shape": label, "m": m, "k": k, "n": n,
               "xla_ms": round(t_ref, 3), "pallas_ms": round(t_fused, 3),
               "speedup": round(t_ref / t_fused, 3),
               "xla_bytes_mb": round(by_ref / 1e6, 1),
               "pallas_bytes_mb": round(by_fused / 1e6, 1),
               "ideal_bytes_mb": round(ideal / 1e6, 1)}
        results.append(row)
        print(json.dumps(row))

    if results:
        tot_x = sum(r["xla_ms"] for r in results)
        tot_p = sum(r["pallas_ms"] for r in results)
        print(json.dumps({"total_xla_ms": round(tot_x, 2),
                          "total_pallas_ms": round(tot_p, 2),
                          "speedup": round(tot_x / tot_p, 3)}))


if __name__ == "__main__":
    main()
