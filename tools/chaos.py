#!/usr/bin/env python
"""Chaos smoke — serving + checkpoints under an injected fault schedule.

The robustness-tier gate stage (docs/ROBUSTNESS.md): arm a schedule over
the ``deeplearning4j_tpu/faults/`` injection points, drive the
continuous-batching ``GenerativeEngine`` through it, and assert the
supervised-degradation contract instead of trusting it:

  * every submitted request reaches a TERMINAL finish reason (shed /
    deadline / error are acceptable; a hung future is not);
  * faults actually fired (a chaos run where nothing broke proves nothing);
  * the engine restarted within its cap, and recovery never recompiled —
    zero ``new_shape`` RecompileLedger events across all restarts;
  * the paged KV cache invariants hold after the dust settles;
  * a torn checkpoint write is detected by ``restore()``, which falls back
    to the newest intact checkpoint;
  * the SLO frontend's ladder survives chaos end-to-end: under injected
    ``slow_decode`` plus ``burst_arrival`` floods, goodput with the
    frontend must not lose to the frontend-off baseline, burst-injected
    requests included in the every-request-terminal invariant;
  * ``page_oom`` routed through the PREFIX admission path (shared pages
    already mapped when the injected pool pressure fires) leaves every
    request terminal and the refcounted allocator + radix tree invariants
    intact (docs/SERVING.md § Radix prefix cache);
  * ``decode_step_error`` fired inside the SPECULATIVE verify step
    leaves every request terminal with greedy outputs still equal to the
    non-speculative oracle (supervised retries restart from the prompt —
    lossless), draft/target lengths in agreement, and zero ``new_shape``
    (docs/SERVING.md § Speculative decoding);
  * TRAINING killed mid-fit (torn checkpoint writes + an async-writer
    death + hard ``preemption`` kills) resumes to a BIT-EXACT loss/param
    trajectory vs the uninterrupted oracle with zero ``new_shape``
    (docs/ROBUSTNESS.md § Preemption-proof training). ``--leg training``
    runs ONLY this leg plus the async-overhead measurement and emits a
    ``"tool": "trainchaos"`` line (the ``trainchaos`` gate stage /
    ``make train-chaos-smoke``);
  * a whole ENGINE hard-killed mid-flight (``engine_death``) inside a
    3-engine ClusterRouter leaves every request terminal, migrates >= 1
    in-flight request with greedy output token-for-token identical to
    the single-engine oracle, degrades goodput no worse than
    proportionally to the capacity lost, and shows zero ``new_shape``
    on survivors (docs/ROBUSTNESS.md § Cluster failure domains).
    ``--leg cluster`` runs ONLY this leg and emits a ``"tool":
    "cluster"`` line (the ``cluster`` gate stage /
    ``make cluster-chaos-smoke``).

Contract (same as lint/check/obs/tune): ONE JSON summary line on stdout
with ``"tool": "chaos"``; exit 0 iff ``ok``. ``make chaos-smoke`` pins
JAX_PLATFORMS=cpu; ``tools/gate.py``'s ``chaos`` stage fails unless
faults fired > 0 and unresolved requests == 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import types

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fake_net(value: float, seed: int = 0):
    """A minimal training-state carrier for the checkpoint leg — the
    checkpointer only reads/writes these attributes."""
    r = np.random.RandomState(seed)
    net = types.SimpleNamespace()
    net.params = {"W": (r.randn(8, 8) * 0 + value).astype(np.float32)}
    net.opt_state = {"W": np.zeros((8, 8), np.float32)}
    net.net_state = {}
    net.iteration_count = int(value)
    net.epoch_count = 0
    return net


def run_serving_chaos(n_requests: int, gen_tokens: int):
    """The serving leg: threaded engine under page_oom + decode error +
    slow decode + worker death, with a bounded queue and deadlines."""
    from deeplearning4j_tpu import faults, observe
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine
    from deeplearning4j_tpu.serving.scheduler import FINISH_REASONS
    from deeplearning4j_tpu.testing.lifetrace import ResourceTracer

    cfg = GptConfig.tiny(vocab_size=256)
    model = GptModel(cfg, seed=0)
    max_restarts = 6
    eng = GenerativeEngine(
        model, max_slots=2, page_size=8, max_pages_per_seq=6, max_prompt=16,
        seed=0, max_queue=max(2, n_requests // 2), default_deadline_s=300.0,
        max_restarts=max_restarts, restart_backoff_s=0.01)

    r = np.random.RandomState(0)
    prompts = [r.randint(1, cfg.vocab_size, size=r.randint(2, 10))
               .astype(np.int32) for _ in range(n_requests)]
    # warm both compiled paths FIRST so the fault schedule exercises
    # recovery, not first-compile latency
    eng.generate([prompts[0][:2]], max_new_tokens=2)
    # lifecycle tracer (docs/LINT.md § graftlife): every chaos run also
    # asserts rc-clean pages, exactly-once terminals, and no leaked
    # threads — created AFTER the warm generate so the terminal ledger
    # starts at zero
    tracer = ResourceTracer()
    tracer.attach_engine(eng)

    # the schedule: count-deterministic pool pressure + decode crash (the
    # acceptance-criterion triple, with the torn checkpoint below),
    # probabilistic injected latency, and a mid-run worker death
    faults.arm("page_oom", prob=1.0, after_n=2, max_fires=2)
    faults.arm("slow_decode", prob=0.2, seed=1)
    faults.arm("decode_step_error", prob=1.0, after_n=4, max_fires=2)
    faults.arm("worker_death", prob=1.0, after_n=12, max_fires=1)

    eng.start()
    futs = []
    try:
        # burst-submit ahead of service so the bounded queue sheds, plus
        # one pre-expired request so "deadline" is deterministically seen
        futs.append(eng.submit(prompts[0], max_new_tokens=gen_tokens,
                               deadline_s=0.0))
        for p in prompts[1:]:
            # budget for every crash the schedule can throw (2 decode
            # errors + 1 worker death): all-crash survivors should FINISH,
            # proving retries actually re-admit, not just fail politely
            futs.append(eng.submit(p, max_new_tokens=gen_tokens,
                                   max_retries=4))
        results = [f.result(timeout=600) for f in futs]
    finally:
        eng.stop()
        faults.reset()

    reasons: dict = {}
    for res in results:
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1
    unresolved = sum(1 for f in futs if not f.done())
    bad_reasons = [k for k in reasons if k not in FINISH_REASONS]
    eng.cache.check_invariants()
    # the runtime half of graftlife: rc bookkeeping balanced, every
    # request exactly one terminal, no leaked threads (the static
    # inventory walk is the lifetrace smoke's job — skip it here)
    lifetrace = tracer.check(REPO, build_inventory=False)
    serving_events = [e for e in observe.ledger().events()
                      if e.graph == "serving"]
    new_shape = sum(1 for e in serving_events if e.cause == "new_shape")
    return {
        "submitted": len(futs),
        "terminal": len(results),
        "unresolved": unresolved,
        "reasons": reasons,
        "bad_reasons": bad_reasons,
        "restarts": eng.restarts,
        "max_restarts": max_restarts,
        "stopped_cleanly": eng.stopped_cleanly,
        "new_shape_events": new_shape,
        "invariants_ok": True,  # check_invariants above would have raised
        "lifetrace": lifetrace,
    }


def run_frontend_chaos():
    """The SLO-frontend leg (docs/SERVING.md § SLO admission frontend):
    the shared overload ramp under probabilistic ``slow_decode`` plus the
    ``burst_arrival`` injection point (which floods the frontend with
    synthetic lowest-class arrivals), frontend on vs off with an
    identical offered schedule. Proves the ladder end-to-end under
    chaos: goodput with the frontend must not lose to the baseline,
    every request (bursts included) must reach a terminal state, and no
    degradation transition may recompile."""
    from deeplearning4j_tpu import faults
    from deeplearning4j_tpu.serving.overload import run_overload_ramp

    # slow_decode at prob 1.0: a DETERMINISTIC 50ms service floor on both
    # legs (probabilistic injection gave each leg a different slow-step
    # pattern and made the single-trial goodput comparison flaky).
    # burst_arrival only has a call site in the frontend, so it fires on
    # the ON leg — which is the point: extra injected load on top, and
    # the ON leg must still not lose
    faults.arm("slow_decode", prob=1.0, seed=2)
    faults.arm("burst_arrival", prob=1.0, after_n=3, max_fires=2)
    try:
        on = run_overload_ramp(frontend_on=True, n_requests=12,
                               gen_tokens=8, max_slots=2,
                               overload_factor=2.5)
        off = run_overload_ramp(
            frontend_on=False, n_requests=12, gen_tokens=8, max_slots=2,
            overload_factor=2.5,
            capacity_tokens_per_sec=on["capacity_tokens_per_sec"])
    finally:
        faults.reset()
    g_on = on["goodput_tokens_per_sec"]
    g_off = off["goodput_tokens_per_sec"]
    return {
        "goodput_on": g_on,
        "goodput_off": g_off,
        "beats_baseline": g_on >= g_off,
        "burst_requests": on["burst_requests"],
        "states_visited": on.get("states_visited"),
        "all_terminal": bool(on["all_terminal"] and off["all_terminal"]),
        "new_shape_events": on["new_shape_events"] + off["new_shape_events"],
        "reasons_on": on["reasons"], "reasons_off": off["reasons"],
    }


def run_prefix_chaos():
    """The prefix-cache leg (docs/SERVING.md § Radix prefix cache):
    shared-prompt traffic with ``page_oom`` routed through the PREFIX
    admission path — injected pool pressure fires mid-match, after the
    shared pages are already mapped into the slot's row. The contract:
    every request still reaches a terminal state (``oom`` is a result,
    not a hang or a leak), and BOTH the refcounted allocator and the
    radix tree hold their invariants — exact refcount accounting included
    — after the dust settles."""
    from deeplearning4j_tpu import faults, observe
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine
    from deeplearning4j_tpu.serving.scheduler import FINISH_REASONS

    cfg = GptConfig.tiny(vocab_size=256)
    model = GptModel(cfg, seed=0)
    eng = GenerativeEngine(model, max_slots=2, page_size=8,
                           max_pages_per_seq=6, max_prompt=16, seed=0,
                           prefix_pages=8, suffix_bucket=8)
    r = np.random.RandomState(3)
    sysp = r.randint(1, cfg.vocab_size, size=11).astype(np.int32)
    # warm: cache the shared prefix so later admissions go the match path
    eng.generate([np.concatenate([sysp, np.asarray([7], np.int32)])],
                 max_new_tokens=2, eos_token=-1)
    m = observe.metrics()
    oom_before = int(m.counter("dl4j_tpu_faults_injected_total",
                               point="page_oom").value)
    # every other admission sees injected pool pressure mid-match
    faults.arm("page_oom", prob=0.5, seed=5)
    reasons: dict = {}
    unresolved = 0
    try:
        for i in range(10):
            tail = r.randint(1, cfg.vocab_size,
                             size=int(r.randint(1, 4))).astype(np.int32)
            fut = eng.submit(np.concatenate([sysp, tail]),
                             max_new_tokens=2, eos_token=-1)
            while eng.scheduler.has_work():
                eng.step()
            if not fut.done():
                unresolved += 1
                continue
            res = fut.result(timeout=0)
            reasons[res.finish_reason] = reasons.get(res.finish_reason,
                                                     0) + 1
    finally:
        faults.disarm("page_oom")
    eng.check_invariants()  # allocator + tree, exact refcounts
    oom_fired = int(m.counter("dl4j_tpu_faults_injected_total",
                              point="page_oom").value) - oom_before
    hit_tokens = int(m.counter("dl4j_tpu_prefix_hit_tokens_total").value)
    bad = [k for k in reasons if k not in FINISH_REASONS]
    return {
        "submitted": 10,
        "reasons": reasons,
        "unresolved": unresolved,
        "bad_reasons": bad,
        "oom_fired_in_prefix_path": oom_fired,
        "prefix_hit_tokens": hit_tokens,
        "invariants_ok": True,  # check_invariants above would have raised
        "ok": (unresolved == 0 and not bad and oom_fired > 0
               and hit_tokens > 0),
    }


def run_spec_chaos():
    """The speculative-decoding leg (docs/SERVING.md § Speculative
    decoding): greedy traffic on a spec-enabled engine with
    ``decode_step_error`` firing INSIDE the verify step. The contract:
    the supervisor's retries keep every request terminal AND lossless
    (token-for-token equal to an undisturbed spec-off engine), the
    draft/target length invariant holds after recovery, and zero
    ``new_shape`` ledger events were paid — the compiled draft/verify
    functions survive the restart like the target's."""
    import jax.numpy as jnp

    from deeplearning4j_tpu import faults, observe
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel, gpt_prefill
    from deeplearning4j_tpu.serving import GenerativeEngine
    from deeplearning4j_tpu.serving.scheduler import FINISH_REASONS
    from deeplearning4j_tpu.serving.speculative import perturbed_draft

    cfg = GptConfig.tiny(vocab_size=256)
    model = GptModel(cfg, seed=0)
    # a PARTIALLY-agreeing draft: tiny random GPTs fall into constant
    # attractors, so even an unrelated-seed model greedily agrees with
    # the target almost everywhere — heavy perturbation (scale tuned
    # empirically, disagreement self-checked below) is what actually
    # makes rejections interleave with the injected crashes
    draft = perturbed_draft(model, scale=0.1, seed=1)
    r = np.random.RandomState(11)
    prompts = [r.randint(1, cfg.vocab_size, size=r.randint(2, 10))
               .astype(np.int32) for _ in range(6)]

    def build(spec):
        return GenerativeEngine(
            model, max_slots=2, page_size=8, max_pages_per_seq=6,
            max_prompt=16, seed=0, max_restarts=8, restart_backoff_s=0.01,
            spec_k=3 if spec else 0, draft_model=draft if spec else None)

    # the undisturbed spec-off oracle outputs
    ref = build(spec=False)
    want = [res.tokens.tolist() for res in ref.generate(
        prompts, max_new_tokens=6, eos_token=-1, max_retries=0)]

    # self-check the draft actually DISAGREES along these trajectories —
    # an accept-all draft would render the rejection×crash interaction
    # this leg exists for untested (and the leg not-ok)
    disagreements = 0
    for p, w in zip(prompts, want):
        if not w:
            continue
        seq = np.concatenate([p, np.asarray(w, np.int32)])
        logits, _ = gpt_prefill(draft.params,
                                jnp.asarray(seq[None], jnp.int32), cfg)
        pred = np.asarray(jnp.argmax(logits[0], -1))
        disagreements += int((pred[len(p) - 1:-1] != seq[len(p):]).sum())

    eng = build(spec=True)
    eng.generate([prompts[0][:2]], max_new_tokens=2, eos_token=-1)  # warm
    new_shape_before = sum(
        1 for e in observe.ledger().events()
        if e.graph == "serving" and e.cause == "new_shape")
    m = observe.metrics()
    err_before = int(m.counter("dl4j_tpu_faults_injected_total",
                               point="decode_step_error").value)
    faults.arm("decode_step_error", prob=0.6, seed=13, max_fires=4)
    eng.start()
    try:
        futs = [eng.submit(p, max_new_tokens=6, eos_token=-1,
                           max_retries=6) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
    finally:
        eng.stop()
        faults.reset()
    eng.check_invariants()  # allocator + draft/target agreement
    err_fired = int(m.counter("dl4j_tpu_faults_injected_total",
                              point="decode_step_error").value) - err_before
    reasons: dict = {}
    for res in results:
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1
    unresolved = sum(1 for f in futs if not f.done())
    bad = [k for k in reasons if k not in FINISH_REASONS]
    completed = sum(1 for res in results
                    if res.finish_reason in ("eos", "length"))
    # vacuous-truth guard: "lossless" over zero completions proves
    # nothing — the leg must show at least one request that actually
    # FINISHED through the crashes, with accepted draft tokens
    lossless = completed > 0 and all(
        res.tokens.tolist() == w
        for res, w in zip(results, want)
        if res.finish_reason in ("eos", "length"))
    accepted = sum(res.spec_accepted_tokens for res in results)
    new_shape = sum(
        1 for e in observe.ledger().events()
        if e.graph == "serving"
        and e.cause == "new_shape") - new_shape_before
    return {
        "submitted": len(futs),
        "completed": completed,
        "reasons": reasons,
        "unresolved": unresolved,
        "bad_reasons": bad,
        "restarts": eng.restarts,
        "errors_fired_in_verify": err_fired,
        "lossless": lossless,
        "spec_accepted_tokens": int(accepted),
        "draft_disagreements": int(disagreements),
        "new_shape_events": max(0, new_shape),
        "invariants_ok": True,  # check_invariants above would have raised
        "ok": (unresolved == 0 and not bad and lossless
               and accepted > 0 and disagreements > 0
               and err_fired > 0 and new_shape <= 0),
    }


def _train_net(seed=7, hidden=16, feat=2, depth=1):
    from deeplearning4j_tpu import nn

    b = (nn.builder().seed(seed).updater(nn.Adam(learning_rate=0.02))
         .weight_init("xavier").list())
    for _ in range(depth):
        b = b.layer(nn.DenseLayer(n_out=hidden, activation="tanh"))
    return nn.MultiLayerNetwork(
        b.layer(nn.OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(feat)).build()).init()


def _train_data(n=96, seed=0, feat=2):
    r = np.random.RandomState(seed)
    x = r.rand(n, feat).astype(np.float32)
    y = np.zeros((n, 2), np.float32)
    y[np.arange(n), r.randint(0, 2, n)] = 1.0
    return x, y


def run_training_chaos():
    """The preemption-proof-training leg (docs/ROBUSTNESS.md §
    Preemption-proof training): a supervised MLN fit under torn
    checkpoint writes, an async-writer worker death, and hard
    ``preemption`` kills mid-fit. The contract: the resumed loss/param
    trajectory is BIT-EXACT against the uninterrupted oracle, every
    checkpoint on disk is intact or detectably corrupt (restore's sha256
    verify), recovery pays zero ``new_shape`` recompiles, and a writer
    death costs one checkpoint, never the run."""
    from deeplearning4j_tpu import faults, observe
    from deeplearning4j_tpu.nn.listeners import CollectScoresIterationListener
    from deeplearning4j_tpu.parallel import (
        TrainingCheckpointer, TrainingSupervisor)

    x, y = _train_data()
    epochs, batch = 3, 16  # 96/16 = 6 exact batches — one jit signature

    # the uninterrupted oracle trajectory
    oracle = _train_net()
    col_o = CollectScoresIterationListener()
    oracle.set_listeners(col_o)
    oracle.fit(x, y, epochs=epochs, batch_size=batch)
    want_scores = dict(col_o.scores)
    want_params = oracle.params_flat()

    new_shape_before = sum(1 for e in observe.ledger().events()
                           if e.graph == "mln" and e.cause == "new_shape")
    m = observe.metrics()

    def fired(point):
        return int(m.counter("dl4j_tpu_faults_injected_total",
                             point=point).value)

    before = {p: fired(p) for p in
              ("preemption", "checkpoint_torn_write", "worker_death")}

    net = _train_net()
    col = CollectScoresIterationListener()
    net.set_listeners(col)
    warned = []
    with tempfile.TemporaryDirectory(prefix="chaos_train_") as d:
        ck = TrainingCheckpointer(d, keep_last=3, use_orbax=False)
        sup = TrainingSupervisor(net, ck, save_every=1, max_restarts=6,
                                 restart_backoff_s=0.01)
        # the schedule: the 2nd durable write is torn post-publish, the
        # 4th write attempt dies in the WRITER thread (surfaced on the
        # next save — the listener warns once and keeps training), and
        # two hard preemption kills land mid-fit
        faults.arm("checkpoint_torn_write", prob=1.0, after_n=1,
                   max_fires=1)
        faults.arm("worker_death", prob=1.0, after_n=3, max_fires=1)
        faults.arm("preemption", prob=1.0, after_n=4, max_fires=2)
        try:
            status = sup.fit(x, y, epochs=epochs, batch_size=batch)
        finally:
            faults.reset()
        ck.wait_until_finished(timeout=60.0)
        entries = list(ck._saved)
        intact = sum(1 for _, p, c in entries if ck._verify(p, c))
        detected = len(entries) - intact

    got_scores = dict(col.scores)
    traj_exact = (set(got_scores) == set(want_scores) and all(
        got_scores[i] == want_scores[i] for i in want_scores))
    params_exact = bool(np.array_equal(want_params, net.params_flat()))
    new_shape = sum(1 for e in observe.ledger().events()
                    if e.graph == "mln"
                    and e.cause == "new_shape") - new_shape_before
    fires = {p: fired(p) - before[p] for p in before}
    resumes = sup.restarts
    corrupt_seen = int(m.counter("dl4j_tpu_checkpoint_corrupt_total").value)
    return {
        "status": status,
        "steps": len(got_scores),
        "resumes": resumes,
        "fired": fires,
        "trajectory_bit_exact": traj_exact,
        "params_bit_exact": params_exact,
        "new_shape_events": new_shape,
        "checkpoints_on_disk": len(entries),
        "checkpoints_intact": intact,
        "checkpoints_detected_corrupt": detected,
        "corrupt_total_seen": corrupt_seen,
        # every surviving checkpoint is intact or DETECTABLY corrupt by
        # construction (intact + detected == on-disk); the load-bearing
        # claims are bit-exactness, >=1 restorable checkpoint, and that
        # all three fault classes actually fired
        "ok": (status == "completed" and traj_exact and params_exact
               and new_shape == 0 and resumes >= 1
               and fires["preemption"] >= 1
               and fires["checkpoint_torn_write"] >= 1
               and fires["worker_death"] >= 1
               and intact >= 1),
    }


def run_training_overhead(steps=16, repeats=3, hidden=384, batch=64,
                          _retries=1):
    """The async-checkpoint cost story: per-step overhead of every-step
    ASYNC checkpointing must be < 10% of every-step SYNCHRONOUS saving on
    the same workload — the training thread pays one device_get, not the
    fsync dance. The step must carry real XLA compute (hidden=384,
    batch=64, two dense layers): a microscopic GIL-bound step would bill
    writer-thread CPU contention — cost the accelerator never sees — to
    the async path. Per-step medians within a trial, best-of-N across
    paired trials, one retry round on a miss (timing gates on shared CI
    hosts need the same noise armor the other paired-trial stages have);
    an absolute sub-millisecond floor absorbs fast-disk noise on the
    sync baseline."""
    from deeplearning4j_tpu.parallel import (
        CheckpointTrainingListener, TrainingCheckpointer)

    x, y = _train_data(n=steps * batch, seed=1, feat=16)

    class _StepTimer:
        """Per-step host sync + per-step timing in EVERY leg: fit
        pipelines its dispatches, but a checkpoint snapshot forces the
        step to complete — without the sync the base leg would get the
        wait for free and the comparison would bill compute time to the
        checkpoint path. Recording PER-STEP durations (instead of one
        epoch mean) lets the median discard GC pauses and ambient-load
        spikes."""

        def __init__(self):
            self.durations = []
            self._prev = None

        def iteration_done(self, model, iteration, epoch, score):
            float(score)
            now = time.perf_counter()
            if self._prev is not None:
                self.durations.append(now - self._prev)
            self._prev = now

        def on_epoch_start(self, model):
            self._prev = None

        def on_epoch_end(self, model):
            pass

    def timed_epoch(listener):
        net = _train_net(hidden=hidden, feat=16, depth=2)
        timer = _StepTimer()
        listeners = [timer]
        if listener is not None:
            listeners.append(listener)
        net.set_listeners(*listeners)
        net.fit(x, y, epochs=1,
                batch_size=batch)  # warm: compile + first saves
        timer.durations = []
        net.fit(x, y, epochs=1, batch_size=batch)
        d = sorted(timer.durations)
        return d[len(d) // 2]  # median step time within the trial

    base_s, sync_s, async_s = [], [], []
    for _ in range(repeats):
        with tempfile.TemporaryDirectory(prefix="chaos_ovh_") as d:
            base_s.append(timed_epoch(None))
            ck_sync = TrainingCheckpointer(
                os.path.join(d, "sync"), use_orbax=False)
            sync_s.append(timed_epoch(CheckpointTrainingListener(
                ck_sync, every_n_iterations=1, asynchronous=False)))
            ck_async = TrainingCheckpointer(
                os.path.join(d, "async"), use_orbax=False)
            async_s.append(timed_epoch(CheckpointTrainingListener(
                ck_async, every_n_iterations=1, asynchronous=True)))
            ck_async.close(timeout=60.0)  # no leaked writer per trial
    # best-of-N across trials: the least-contended trial is the closest
    # estimate of the true cost — ambient load only ever inflates
    base = min(base_s)
    sync = min(sync_s)
    asy = min(async_s)
    ovh_sync = max(0.0, sync - base)
    ovh_async = max(0.0, asy - base)
    ratio = (ovh_async / ovh_sync) if ovh_sync > 0 else None
    ok = ovh_async < 0.10 * ovh_sync or ovh_async < 5e-4
    if not ok and _retries > 0:
        again = run_training_overhead(steps=steps, repeats=repeats,
                                      hidden=hidden, batch=batch,
                                      _retries=_retries - 1)
        if again["ok"]:
            again["retried"] = True
            return again
    return {
        "steps_per_trial": steps, "trials": repeats,
        "base_step_ms": round(base * 1e3, 3),
        "sync_step_ms": round(sync * 1e3, 3),
        "async_step_ms": round(asy * 1e3, 3),
        "sync_overhead_ms": round(ovh_sync * 1e3, 3),
        "async_overhead_ms": round(ovh_async * 1e3, 3),
        "overhead_ratio": None if ratio is None else round(ratio, 4),
        "ok": bool(ok),
    }


def run_checkpoint_chaos():
    """The durability leg: three saves, the newest torn; restore must fall
    back to the last intact checkpoint with the right parameters."""
    from deeplearning4j_tpu import faults
    from deeplearning4j_tpu.parallel.checkpoint import TrainingCheckpointer

    with tempfile.TemporaryDirectory(prefix="chaos_ckpt_") as d:
        ck = TrainingCheckpointer(d, keep_last=3, use_orbax=False)
        ck.save(1, _fake_net(1.0))
        ck.save(2, _fake_net(2.0))
        faults.arm("checkpoint_torn_write", max_fires=1)
        try:
            ck.save(3, _fake_net(3.0))
        finally:
            faults.reset()
        net = _fake_net(0.0)
        restored = ck.restore(net)
    return {
        "saves": 3,
        "torn_step": 3,
        "restored_step": restored,
        "restored_value": float(net.params["W"][0, 0]),
        "fallback_ok": restored == 2 and float(net.params["W"][0, 0]) == 2.0,
    }


def run_cluster_chaos(n_engines=3, n_requests=18, gen_tokens=8):
    """The cluster leg (docs/ROBUSTNESS.md § Cluster failure domains):
    ``n_engines`` engines behind a ClusterRouter under a past-capacity
    burst with a deterministic slow-decode service floor, one engine
    hard-killed mid-flight by the ``engine_death`` fault. ok iff every
    request reaches a terminal state, at least one in-flight request
    migrates, every finished greedy output is token-for-token identical
    to the single-engine oracle (migrated ones included), goodput
    degrades no worse than proportionally to the capacity lost (with a
    CI-noise margin), and survivors show zero ``new_shape`` events."""
    from deeplearning4j_tpu import faults, observe
    from deeplearning4j_tpu.models.gpt import (
        GptConfig, GptModel, reference_generate)
    from deeplearning4j_tpu.serving import ClusterRouter, GenerativeEngine
    from deeplearning4j_tpu.serving.scheduler import FINISH_REASONS

    cfg = GptConfig.tiny(vocab_size=256)
    model = GptModel(cfg, seed=0)
    r = np.random.RandomState(0)
    prompts = [r.randint(1, cfg.vocab_size, size=r.randint(2, 10))
               .astype(np.int32) for _ in range(n_requests)]
    oracle = [np.asarray(reference_generate(model.params, cfg, p,
                                            gen_tokens))
              for p in prompts]

    def serving_new_shape():
        return sum(1 for e in observe.ledger().events()
                   if e.graph == "serving" and e.cause == "new_shape")

    def run_leg(kill: bool):
        from deeplearning4j_tpu.testing.lifetrace import ResourceTracer

        engines = [GenerativeEngine(
            model, max_slots=2, page_size=8, max_pages_per_seq=6,
            max_prompt=16, seed=0, default_deadline_s=300.0,
            max_restarts=3, restart_backoff_s=0.01)
            for _ in range(n_engines)]
        router = ClusterRouter(engines)
        for e in engines:  # compile BEFORE the clock (and the kill) start
            e.generate([prompts[0][:2]], max_new_tokens=2, eos_token=-1)
        # lifecycle tracer per leg (docs/LINT.md § graftlife): rc-clean
        # exit + exactly-once terminals across death and migration too
        tracer = ResourceTracer()
        for e in engines:
            tracer.attach_engine(e)
        new_shape0 = serving_new_shape()
        # slow_decode at prob 1.0: a deterministic 50ms service floor on
        # both legs, so the single-trial goodput comparison is stable
        faults.arm("slow_decode", prob=1.0, seed=2)
        if kill:
            # fires on the (3*n_engines+1)-th busy loop iteration across
            # the cluster — mid-flight, while slots are held
            faults.arm("engine_death", prob=1.0, after_n=3 * n_engines,
                       max_fires=1)
        router.start()
        t0 = time.perf_counter()
        futs = [router.submit(p, max_new_tokens=gen_tokens, eos_token=-1,
                              max_retries=4) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        live_after = len(router.live_engines())  # before stop() flags them
        router.stop()
        faults.reset()
        reasons: dict = {}
        for res in results:
            reasons[res.finish_reason] = reasons.get(res.finish_reason,
                                                     0) + 1
        done_tokens = sum(len(res.tokens) for res in results
                          if res.finish_reason in ("eos", "length"))
        bit_exact = all(
            res.finish_reason not in ("eos", "length")
            or np.array_equal(res.tokens, oracle[i][:len(res.tokens)])
            for i, res in enumerate(results))
        router.check_invariants()
        lifetrace = tracer.check(REPO, build_inventory=False)
        return {
            "submitted": len(futs),
            "terminal": len(results),
            "unresolved": sum(1 for f in futs if not f.done()),
            "reasons": reasons,
            "bad_reasons": [k for k in reasons if k not in FINISH_REASONS],
            "deaths": router.deaths,
            "migrations": router.migrations,
            "live_engines": live_after,
            "bit_exact": bool(bit_exact),
            "goodput_tokens_per_sec": round(done_tokens / max(wall, 1e-9),
                                            2),
            "new_shape_events": serving_new_shape() - new_shape0,
            "lifetrace": lifetrace,
        }

    full = run_leg(kill=False)
    killed = run_leg(kill=True)
    share_left = (n_engines - 1) / n_engines
    margin = 0.7  # CI-noise allowance under the proportionality bound
    goodput_ok = (killed["goodput_tokens_per_sec"]
                  >= share_left * margin * full["goodput_tokens_per_sec"])
    ok = (full["unresolved"] == 0 and killed["unresolved"] == 0
          and not full["bad_reasons"] and not killed["bad_reasons"]
          and full["lifetrace"]["ok"] and killed["lifetrace"]["ok"]
          and full["deaths"] == 0
          and killed["deaths"] == 1
          and killed["migrations"] >= 1
          and killed["live_engines"] == n_engines - 1
          and full["bit_exact"] and killed["bit_exact"]
          and full["new_shape_events"] == 0
          and killed["new_shape_events"] == 0
          and goodput_ok)
    return {
        "ok": bool(ok),
        "n_engines": n_engines,
        "full": full,
        "killed": killed,
        "share_left": round(share_left, 3),
        "goodput_margin": margin,
        "goodput_proportional_ok": bool(goodput_ok),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: exactly one JSON line on stdout")
    ap.add_argument("--requests", type=int, default=10)
    ap.add_argument("--tokens", type=int, default=8)
    ap.add_argument("--leg", choices=("all", "training", "cluster"),
                    default="all",
                    help="'training' runs ONLY the preemption-proof "
                         "training leg and emits a \"tool\": "
                         "\"trainchaos\" line (the trainchaos gate stage); "
                         "'cluster' runs ONLY the multi-engine "
                         "kill-one-engine leg and emits a \"tool\": "
                         "\"cluster\" line (the cluster gate stage)")
    args = ap.parse_args()

    from deeplearning4j_tpu import faults, observe

    t0 = time.perf_counter()
    if args.leg == "training":
        training = run_training_chaos()
        overhead = run_training_overhead()
        ok = bool(training["ok"] and overhead["ok"])
        rec = {
            "tool": "trainchaos", "ok": ok,
            "training": training, "overhead": overhead,
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
        print(json.dumps(rec), flush=True)
        if not args.json:
            print(f"trainchaos: {'OK' if ok else 'FAIL'} — "
                  f"{training['steps']} steps, {training['resumes']} "
                  f"resumes, fired {training['fired']}, bit-exact "
                  f"{training['trajectory_bit_exact']}, async overhead "
                  f"{overhead['async_overhead_ms']}ms vs sync "
                  f"{overhead['sync_overhead_ms']}ms "
                  f"(ratio {overhead['overhead_ratio']})",
                  file=sys.stderr)
        return 0 if ok else 1

    if args.leg == "cluster":
        cluster = run_cluster_chaos()
        ok = bool(cluster["ok"])
        rec = {
            "tool": "cluster", "ok": ok, "cluster": cluster,
            "elapsed_s": round(time.perf_counter() - t0, 2),
        }
        print(json.dumps(rec), flush=True)
        if not args.json:
            k = cluster["killed"]
            print(f"cluster: {'OK' if ok else 'FAIL'} — "
                  f"{k['submitted']} submitted, {k['deaths']} death, "
                  f"{k['migrations']} migrated, bit-exact "
                  f"{k['bit_exact']}, goodput "
                  f"{k['goodput_tokens_per_sec']} vs full "
                  f"{cluster['full']['goodput_tokens_per_sec']} tok/s "
                  f"(proportional ok {cluster['goodput_proportional_ok']}"
                  f"), new_shape {k['new_shape_events']}",
                  file=sys.stderr)
        return 0 if ok else 1

    serving = run_serving_chaos(args.requests, args.tokens)
    ckpt = run_checkpoint_chaos()
    frontend = run_frontend_chaos()
    prefix = run_prefix_chaos()
    spec = run_spec_chaos()
    training = run_training_chaos()
    m = observe.metrics()
    faults_total = int(m.family_total("dl4j_tpu_faults_injected_total"))
    by_point = {}
    for inst in m.instruments():
        if inst.name == "dl4j_tpu_faults_injected_total" and inst.labels:
            by_point[dict(inst.labels).get("point")] = int(inst.value)
    # the acceptance-criterion points must all have actually fired — a
    # chaos run that never hit the pool, the decode step, the checkpoint,
    # the frontend's burst hook AND the training preemption proved nothing
    required = ("page_oom", "decode_step_error", "checkpoint_torn_write",
                "burst_arrival", "preemption")
    missing = [p for p in required if not by_point.get(p)]

    ok = (serving["unresolved"] == 0
          and not serving["bad_reasons"]
          and serving["terminal"] == serving["submitted"]
          and serving["restarts"] <= serving["max_restarts"]
          and serving["new_shape_events"] == 0
          and serving["stopped_cleanly"]
          and serving["lifetrace"]["ok"]
          and ckpt["fallback_ok"]
          and frontend["beats_baseline"]
          and frontend["all_terminal"]
          and frontend["new_shape_events"] == 0
          and prefix["ok"]
          and spec["ok"]
          and training["ok"]
          and faults_total > 0
          and not missing)

    rec = {
        "tool": "chaos", "ok": ok,
        "faults_injected_total": faults_total,
        "fired_by_point": by_point,
        "required_points_missing": missing,
        "serving": serving,
        "checkpoint": ckpt,
        "frontend": frontend,
        "prefix": prefix,
        "spec": spec,
        "training": training,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(rec), flush=True)
    if not args.json:
        print(f"chaos: {'OK' if ok else 'FAIL'} — "
              f"{serving['submitted']} submitted, reasons "
              f"{serving['reasons']}, {serving['restarts']} restarts, "
              f"{faults_total} faults injected, checkpoint fallback "
              f"{'ok' if ckpt['fallback_ok'] else 'FAILED'}, frontend "
              f"goodput {frontend['goodput_on']}/{frontend['goodput_off']} "
              f"(burst {frontend['burst_requests']})",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
