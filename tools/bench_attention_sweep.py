"""Flash-attention honesty sweep (round-4 verdict item 6).

Benchmarks the Pallas flash kernel against BOTH competitors across
T x {causal, full}, fwd+bwd in bf16:
  * jax.nn.dot_product_attention (implementation='xla') — the fused XLA
    path and the honest competitor,
  * our own generic composition (_reference_attention) — the historical
    baseline the 1.95x claim was measured against.

Records the full table in BENCH_HISTORY.json under 'attention_sweep',
prints one row per shape, and emits a TUNING-TABLE FRAGMENT (the
ops/tuning.py dl4j_tpu_tuning_v1 schema) with the measured flash-vs-XLA
crossover for this device kind. Fragments are NOT loaded automatically:
merge one into the committed default table
(deeplearning4j_tpu/ops/tuning_tables/<kind>.json) or into the cache
table the loader actually reads (<cache dir>/<device_kind>.json) via
``TuningTable.merge`` — docs/KERNELS.md § Re-tuning. DL4J_TPU_FLASH_MIN_T
still overrides everything. Fragment path: SWEEP_TABLE_OUT env, default
<cache dir>/fragment_attention_<device_kind>.json.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_shape(t: int, causal: bool, iters: int = None):
    if iters is None:
        iters = int(os.environ.get("SWEEP_ITERS", "50"))
    bench_shape.last_iters = iters  # recorded into the history rows
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas_attention import (
        flash_attention, _reference_attention)

    bh, d = 8, 64
    b, h = 2, 4  # bh = b*h for the jax.nn API's (B, T, N, H) layout
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)
    k = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)
    v = jnp.asarray(r.randn(bh, t, d).astype(np.float32)).astype(jnp.bfloat16)
    scale = d ** -0.5

    def timed(loss_fn, *args, reps: int = 3):
        """DCE/hoist-proof device loop (round-5 verdict item 6: the old
        harness multiplied grads by a LITERAL zero, which XLA folds, and
        left the loop body loop-invariant, which XLA hoists — the
        non-monotonic competitor numbers were measurement artifacts).
        The carry threads through grad(carry, ...) with a RUNTIME-zero eps,
        and timing is fenced by materializing a host scalar
        (block_until_ready is a no-op on the axon plugin). Returns
        (min_ms, mean_ms, std_ms) over ``reps`` timed runs."""
        grad = jax.grad(loss_fn, argnums=tuple(range(len(args))))

        @jax.jit
        def run(eps, *a):
            def body(carry, _):
                g = grad(carry, *a[1:])
                acc = carry + (eps * g[0].astype(jnp.float32)
                               ).astype(carry.dtype)
                tail = sum(jnp.sum(gi.astype(jnp.float32)) for gi in g[1:])
                acc = acc + (eps * tail).astype(carry.dtype)
                return acc, ()

            qf, _ = jax.lax.scan(body, a[0], None, length=iters)
            return jnp.sum(qf.astype(jnp.float32))

        zero = jnp.float32(0.0)
        float(run(zero, *args))  # compile + warm
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(zero, *args))
            times.append((time.perf_counter() - t0) / iters * 1e3)
        return (min(times), float(np.mean(times)), float(np.std(times)))

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, None, scale, causal,
                                       None, None, None, 0.0)
                       .astype(jnp.float32) ** 2)

    def gen_loss(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, scale=scale,
                                            causal=causal)
                       .astype(jnp.float32) ** 2)

    # jax.nn.dot_product_attention wants (B, T, N, H)
    q4 = q.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    k4 = k.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    v4 = v.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    def xla_loss(q4, k4, v4):
        out = jax.nn.dot_product_attention(q4, k4, v4, scale=scale,
                                           is_causal=causal,
                                           implementation="xla")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    t_flash = timed(flash_loss, q, k, v)
    t_gen = timed(gen_loss, q, k, v)
    t_xla = timed(xla_loss, q4, k4, v4)
    return t_flash, t_xla, t_gen


def main() -> None:
    import jax

    seqs = [int(s) for s in os.environ.get(
        "SWEEP_T", "1024,2048,4096,8192,16384").split(",")]
    rows = []
    print(f"device: {jax.devices()[0].device_kind}  (bh=8, d=64, bf16, "
          f"fwd+bwd, ms per call)")
    print(f"{'T':>6} {'causal':>6} {'flash':>9} {'xla':>9} {'generic':>9} "
          f"{'flash/xla':>9}")
    for t in seqs:
        for causal in (True, False):
            (f_min, f_mean, f_std), (x_min, x_mean, x_std), \
                (g_min, g_mean, g_std) = bench_shape(t, causal)
            rows.append({"t": t, "causal": causal, "bh": 8, "d": 64,
                         "iters": bench_shape.last_iters,
                         "flash_ms": round(f_min, 3),
                         "flash_ms_std": round(f_std, 3),
                         "xla_ms": round(x_min, 3),
                         "xla_ms_std": round(x_std, 3),
                         "generic_ms": round(g_min, 3),
                         "generic_ms_std": round(g_std, 3),
                         "speedup_vs_xla": round(x_min / f_min, 3)})
            print(f"{t:>6} {str(causal):>6} {f_min:>9.3f} {x_min:>9.3f} "
                  f"{g_min:>9.3f} {x_min / f_min:>9.2f}x  "
                  f"(std f={f_std:.3f} x={x_std:.3f})")

    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", "BENCH_HISTORY.json")
    hist_path = os.path.abspath(hist_path)
    hist = {}
    if os.path.exists(hist_path):
        hist = json.load(open(hist_path))
    hist["attention_sweep"] = {
        "device": jax.devices()[0].device_kind,
        "rows": rows}
    json.dump(hist, open(hist_path, "w"), indent=1)
    print(f"recorded {len(rows)} rows to {hist_path}")

    # tuning-table fragment (ops/tuning.py schema): the measured crossover
    # is the smallest swept T where flash beats XLA in BOTH causal modes;
    # if flash never wins, 2x the largest point (pessimistic, re-measurable)
    from deeplearning4j_tpu.ops import tuning

    # justified: runs after the whole sweep already exercised the backend
    kind = tuning.normalize_device_kind(jax.devices()[0].device_kind)  # graftlint: disable=GL002
    frag = tuning.TuningTable(device_kind=kind)
    wins = {}
    for row in rows:
        wins.setdefault(row["t"], True)
        wins[row["t"]] &= row["speedup_vs_xla"] >= 1.0
    crossover = next((t for t in sorted(wins) if wins[t]), 2 * max(seqs))
    frag.set("dot_product_attention", "flash_min_t", int(crossover))
    out_path = os.environ.get(
        "SWEEP_TABLE_OUT",
        os.path.join(tuning.tuning_dir(), f"fragment_attention_{kind}.json"))
    frag.save(out_path)
    print(f"tuning fragment (flash_min_t={crossover}) -> {out_path}")


if __name__ == "__main__":
    main()
