"""Measure the chip's ACTUAL deliverable HBM bandwidth (round-5 bound
proof): a donated read+write streaming pass (c = c + eps under lax.scan)
at several sizes, fenced by host materialization (block_until_ready is a
no-op on the axon plugin).

Why it matters: every roofline in docs/PERF_ANALYSIS.md previously used
the v5e spec sheet's 819 GB/s. The measured sustained number on this chip
is ~380-414 GB/s — half the spec — which moves the ResNet-50 memory
roofline onto the measured step time exactly (the step is
bandwidth-saturated; the ~17% MFU is the bandwidth ceiling, not a
software gap).

Usage: python tools/bench_hbm.py
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    rows = []
    for label, dtype, shape, iters in [
        ("128MB_bf16", jnp.bfloat16, (64, 1024, 1024), 100),
        ("512MB_bf16", jnp.bfloat16, (256, 1024, 1024), 50),
        ("1GB_bf16", jnp.bfloat16, (512, 1024, 1024), 50),
        ("2GB_bf16", jnp.bfloat16, (1024, 1024, 1024), 30),
        ("512MB_f32", jnp.float32, (128, 1024, 1024), 50),
    ]:
        x = jnp.zeros(shape, dtype)

        @jax.jit
        def run(eps, x, iters=iters):
            def body(c, _):
                return c + eps, ()

            c, _ = jax.lax.scan(body, x, None, length=iters)
            return jnp.sum(c[:1, :1, :8].astype(jnp.float32))

        z = jnp.asarray(0.0, dtype)
        float(run(z, x))
        float(run(z, x))
        t0 = time.perf_counter()
        float(run(z, x))
        per = (time.perf_counter() - t0) / iters
        bw = x.nbytes * 2 / per / 1e9  # read + write
        rows.append({"case": label, "ms_per_pass": round(per * 1e3, 3),
                     "gb_per_s": round(bw, 1)})
        print(json.dumps(rows[-1]))
    peak = max(r["gb_per_s"] for r in rows)
    print(json.dumps({"measured_peak_stream_gb_s": peak,
                      "device": jax.devices()[0].device_kind,
                      "spec_sheet_gb_s": 819}))


if __name__ == "__main__":
    main()
