#!/usr/bin/env python
"""Lifetrace smoke — runtime cross-validation of the graftlife static
resource-lifecycle analyzer (docs/LINT.md § graftlife, docs/ROBUSTNESS.md
§ Ownership rules).

Wraps the REAL paged-KV allocators of a live cluster in
``testing/lifetrace.py`` recording proxies, drives a faults-armed
workload, and checks the lifecycle honesty contract:

  * rc-clean pages: every page ends free XOR tree-held, the observed
    acquire/release ledger exactly balances the live refcount mass, and
    the allocator invariants (exact per-page accounting against the
    prefix tree) hold;
  * exactly-once terminals: every submitted request future is done and
    the ``dl4j_tpu_serving_evicted_total`` family grew by exactly one
    count per request — through oom unwinds, decode crashes, and
    whole-engine death;
  * no leaked threads;
  * every observed acquire/release callsite lies inside the static
    ownership inventory (``lint/rules_lifecycle.
    static_ownership_inventory``) — an unknown callsite is a graftlife
    blind spot to fix in the analyzer, not to baseline;
  * zero ``new_shape`` recompiles across all the injected recoveries.

Two legs, one shared tracer:

  serving    3 engines with radix prefix caches behind a ClusterRouter;
             shared-prefix traffic under page_oom (fires through prefix
             admission, shared pages already mapped), decode_step_error
             (supervised restarts), and one engine_death (cluster
             migration + pin re-warm)
  training   async TrainingCheckpointer with a worker_death fired
             MID-WRITE — the failure surfaces on the next save, the
             orphaned ``*.npz.tmp`` is swept by wait_until_finished,
             and a compensating sync save restores durability

Contract (same as lint/check/chaos): ONE JSON summary line on stdout
with ``"tool": "lifetrace"``; exit 0 iff ``ok``. ``make lifetrace-smoke``
pins JAX_PLATFORMS=cpu; ``tools/gate.py``'s ``lifetrace`` stage
enforces it.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
import tempfile
import time
import types

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fake_net(value: float, seed: int = 0):
    r = np.random.RandomState(seed)
    net = types.SimpleNamespace()
    net.params = {"W": (r.randn(8, 8) * 0 + value).astype(np.float32)}
    net.opt_state = {"W": np.zeros((8, 8), np.float32)}
    net.net_state = {}
    net.iteration_count = int(value)
    net.epoch_count = 0
    return net


def leg_serving(tracer, n_requests: int, gen_tokens: int) -> dict:
    """Prefix-enabled cluster under the full fault triple. The tracer
    sees every alloc/retain/release/cow/map_shared/free_slot on all
    three caches, and every future the router hands out (pin re-warm
    submissions included — they route through the wrapped
    ``submit_request``)."""
    from deeplearning4j_tpu import faults, observe
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import ClusterRouter, GenerativeEngine
    from deeplearning4j_tpu.serving.scheduler import FINISH_REASONS

    n_engines = 3
    cfg = GptConfig.tiny(vocab_size=256)
    model = GptModel(cfg, seed=0)
    engines = [GenerativeEngine(
        model, max_slots=2, page_size=8, max_pages_per_seq=6,
        max_prompt=16, seed=0, default_deadline_s=300.0, max_restarts=6,
        restart_backoff_s=0.01, prefix_pages=8, suffix_bucket=8)
        for _ in range(n_engines)]
    r = np.random.RandomState(3)
    sysp = r.randint(1, cfg.vocab_size, size=11).astype(np.int32)
    for e in engines:  # compile + seed the shared prefix BEFORE the clock
        e.generate([np.concatenate([sysp, np.asarray([7], np.int32)])],
                   max_new_tokens=2, eos_token=-1)

    def serving_new_shape():
        return sum(1 for e in observe.ledger().events()
                   if e.graph == "serving" and e.cause == "new_shape")

    new_shape0 = serving_new_shape()
    m = observe.metrics()

    def fired(point):
        return int(m.counter("dl4j_tpu_faults_injected_total",
                             point=point).value)

    before = {p: fired(p)
              for p in ("page_oom", "decode_step_error", "engine_death")}
    # the warm-up generates above completed (and counted) 3 untracked
    # requests — re-baseline so the exactly-once ledger starts at zero
    tracer.begin()
    for i, e in enumerate(engines):
        tracer.attach_engine(e, name=f"engine{i}")
    router = ClusterRouter(engines)

    # the schedule: injected pool pressure lands mid-prefix-admission
    # (shared pages already mapped — the GR001 unwind under test), decode
    # crashes burn supervised restarts, and one whole engine dies
    # mid-flight forcing migration + pin re-warm
    faults.arm("page_oom", prob=1.0, after_n=2, max_fires=2)
    faults.arm("decode_step_error", prob=1.0, after_n=4, max_fires=2)
    faults.arm("engine_death", prob=1.0, after_n=3 * n_engines,
               max_fires=1)
    router.start()
    try:
        futs = []
        for _ in range(n_requests):
            tail = r.randint(1, cfg.vocab_size,
                             size=int(r.randint(1, 4))).astype(np.int32)
            futs.append(router.submit(np.concatenate([sysp, tail]),
                                      max_new_tokens=gen_tokens,
                                      eos_token=-1, max_retries=4))
        results = [f.result(timeout=600) for f in futs]
    finally:
        router.stop()
        faults.reset()
    reasons: dict = {}
    for res in results:
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1
    fires = {p: fired(p) - before[p] for p in before}
    return {
        "submitted": len(futs),
        "unresolved": sum(1 for f in futs if not f.done()),
        "reasons": reasons,
        "bad_reasons": [k for k in reasons if k not in FINISH_REASONS],
        "deaths": router.deaths,
        "migrations": router.migrations,
        "fired": fires,
        "new_shape_events": serving_new_shape() - new_shape0,
        "ok": (sum(1 for f in futs if not f.done()) == 0
               and not [k for k in reasons if k not in FINISH_REASONS]
               and router.deaths == 1
               and all(v >= 1 for v in fires.values())
               and serving_new_shape() - new_shape0 == 0),
    }


def leg_training(n_saves: int) -> dict:
    """Async checkpointing with a worker death fired MID-WRITE: the tmp
    is orphaned, the failure surfaces on the next save, the
    ``wait_until_finished`` sweep removes the orphan, and a compensating
    sync save leaves a restorable newest checkpoint."""
    from deeplearning4j_tpu import faults
    from deeplearning4j_tpu.parallel.checkpoint import TrainingCheckpointer

    with tempfile.TemporaryDirectory(prefix="lifetrace_train_") as d:
        ck = TrainingCheckpointer(d, keep_last=None, use_orbax=False,
                                  max_queue=2, overflow="block")
        # the 2nd async write dies between fsync and the publishing
        # rename — exactly the orphaned-tmp window
        faults.arm("worker_death", prob=1.0, after_n=1, max_fires=1)
        try:
            for step in range(n_saves):
                ck.save_async(step, _fake_net(float(step)))
            drained = ck.wait_until_finished(timeout=120)
        finally:
            faults.reset()
        failures = ck.drain_failures()
        orphans = _glob.glob(os.path.join(d, "step_*.npz.tmp"))
        # compensating sync save: durability restored after the death
        ck.save(n_saves, _fake_net(float(n_saves)))
        net = _fake_net(-1.0)
        restored = ck.restore(net)
        ck.close()
        return {
            "saves": n_saves,
            "drained": bool(drained),
            "writer_deaths": len(failures),
            "orphan_tmps_after_drain": len(orphans),
            "restored_step": restored,
            "ok": (bool(drained) and len(failures) == 1
                   and len(orphans) == 0 and restored == n_saves),
        }


def run(n_requests: int, gen_tokens: int, n_saves: int) -> dict:
    from deeplearning4j_tpu.testing.lifetrace import ResourceTracer

    tracer = ResourceTracer()
    legs = {
        "serving": leg_serving(tracer, n_requests, gen_tokens),
        "training": leg_training(n_saves),
    }
    report = tracer.check(repo_root=REPO)
    return {
        "tool": "lifetrace",
        "ok": bool(report["ok"] and legs["serving"]["ok"]
                   and legs["training"]["ok"]
                   and report["callsites"]["observed"] > 0),
        "pages": report["pages"],
        "terminals": report["terminals"],
        "threads": report["threads"],
        "callsites": report["callsites"],
        "new_shape_events": legs["serving"]["new_shape_events"],
        "legs": legs,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=12,
                    help="requests through the cluster leg")
    ap.add_argument("--tokens", type=int, default=6,
                    help="max new tokens per request")
    ap.add_argument("--saves", type=int, default=5,
                    help="async checkpoint saves in the training leg")
    args = ap.parse_args()
    t0 = time.perf_counter()
    summary = run(args.requests, args.tokens, args.saves)
    summary["elapsed_s"] = round(time.perf_counter() - t0, 2)
    print(json.dumps(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
