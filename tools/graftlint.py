#!/usr/bin/env python
"""Thin wrapper: ``python tools/graftlint.py`` == ``python -m
deeplearning4j_tpu.lint``. Exists so the gate and Makefile have a stable
entry point that works from the repo root without -m plumbing."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the consistency rules import the package; never let that probe a TPU
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from deeplearning4j_tpu.lint.cli import run  # noqa: E402

if __name__ == "__main__":
    sys.exit(run())
