#!/usr/bin/env python
"""Prefix-cache smoke — shared-prompt replay, cache on vs off.

The ROADMAP 2(a) gate stage (docs/SERVING.md § Radix prefix cache): run
the shared-prompt replay harness (``serving/replay.py``) twice — once
with the radix prefix cache, once without, IDENTICAL request plan — and
assert the cache earns its place instead of trusting it:

  * prefix **hit tokens > 0** (a replay that never hit proved nothing);
  * **TTFT p50 improves >= 30%** vs cache-off (median of paired trials —
    host-load spikes hit single trials);
  * greedy outputs **bit-identical** on both legs — suffix prefill
    against cached pages must reproduce the full prefill token-for-token;
  * ZERO ``new_shape`` RecompileLedger serving events on either leg —
    prefix hits ride a fourth compiled function, they never recompile;
  * allocator + tree invariants hold after every leg (checked inside the
    harness) and every request retires complete.

Contract (same as lint/check/obs/tune/chaos/slo): ONE JSON summary line
on stdout with ``"tool": "prefix"``; exit 0 iff ``ok``. ``make
prefix-smoke`` pins JAX_PLATFORMS=cpu; ``tools/gate.py``'s ``prefix``
stage parses the line.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: The acceptance bar: cache-on TTFT p50 must be <= 70% of cache-off.
MIN_IMPROVEMENT = 0.30


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: exactly one JSON line on stdout")
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--prefixes", type=int, default=3)
    ap.add_argument("--sys-len", type=int, default=88)
    ap.add_argument("--tokens", type=int, default=4)
    ap.add_argument("--trials", type=int, default=3,
                    help="paired on/off trials; MEDIAN TTFT p50s are "
                         "compared (host-load spikes hit single trials)")
    args = ap.parse_args()

    from deeplearning4j_tpu.serving.replay import run_prefix_replay

    t0 = time.perf_counter()
    ons, offs = [], []
    for trial in range(max(1, args.trials)):
        ons.append(run_prefix_replay(
            prefix_on=True, n_requests=args.requests,
            n_prefixes=args.prefixes, sys_len=args.sys_len,
            gen_tokens=args.tokens, seed=trial))
        offs.append(run_prefix_replay(
            prefix_on=False, n_requests=args.requests,
            n_prefixes=args.prefixes, sys_len=args.sys_len,
            gen_tokens=args.tokens, seed=trial))

    p50_on = statistics.median(r["ttft_p50_ms"] for r in ons)
    p50_off = statistics.median(r["ttft_p50_ms"] for r in offs)
    speedup = p50_off / p50_on if p50_on else 0.0
    improvement = 1.0 - (p50_on / p50_off) if p50_off else 0.0
    hit_tokens = sum(r["prefix_hit_tokens"] for r in ons)
    identical = all(a["outputs"] == b["outputs"]
                    for a, b in zip(ons, offs))
    all_terminal = all(r["all_terminal"] for r in ons + offs)
    new_shape = sum(r["new_shape_events"] for r in ons + offs)

    ok = (hit_tokens > 0
          and identical
          and all_terminal
          and improvement >= MIN_IMPROVEMENT
          and new_shape == 0)

    on, off = ons[-1], offs[-1]  # full detail from the last pair
    rec = {
        "tool": "prefix", "ok": ok,
        "ttft_p50_ms_on": p50_on, "ttft_p50_ms_off": p50_off,
        "ttft_speedup": round(speedup, 3),
        "ttft_improvement_pct": round(100.0 * improvement, 1),
        "min_improvement_pct": round(100.0 * MIN_IMPROVEMENT, 1),
        "prefix_hit_tokens": hit_tokens,
        "hit_requests": sum(r["hit_requests"] for r in ons),
        "requests_per_leg": args.requests,
        "trials": len(ons),
        "p50_on_trials": [r["ttft_p50_ms"] for r in ons],
        "p50_off_trials": [r["ttft_p50_ms"] for r in offs],
        "outputs_identical": identical,
        "all_terminal": all_terminal,
        "new_shape_events": new_shape,
        "tree_pages": on.get("tree_pages"),
        "reasons_on": on["reasons"], "reasons_off": off["reasons"],
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    print(json.dumps(rec), flush=True)
    if not args.json:
        print(f"prefix: {'OK' if ok else 'FAIL'} — TTFT p50 "
              f"{p50_on}/{p50_off} ms on/off (x{rec['ttft_speedup']}, "
              f"{rec['ttft_improvement_pct']}% better), {hit_tokens} hit "
              f"tokens, identical={identical}, new_shape={new_shape}",
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
