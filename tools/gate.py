#!/usr/bin/env python
"""Pre-snapshot gate — the CI role (SURVEY §3.4).

Round-2 shipped a red snapshot because nothing stood between `git commit`
and a failing gradcheck; this gate is that something. Run before ANY
snapshot/round-end commit:

    python tools/gate.py            # full: pytest + consistency + bench smoke
    python tools/gate.py --fast     # pytest only (pre-commit speed)

Stages:
  1. native: cmake build + ctest, then an ASAN(-DSANITIZE=ON) build + ctest
     (the libnd4j tests_cpu CI stage — SURVEY §5.3, §6.2)
  2. full pytest suite on the 8-device CPU harness with
     DL4J_TPU_REQUIRE_NATIVE=1 (a missing .so fails ctypes tests loudly)
  3. CPU-vs-TPU consistency suite on the real chip (skipped with a WARNING
     if no TPU is reachable — never silently)
  4. bench smoke: LeNet BENCH_ITERS=3 must print one JSON line with a
     finite value (catches "the benchmark itself is broken" regressions)
  5. multichip dryrun (virtual 8-device CPU mesh via __graft_entry__;
     backend/environment failures report an explicit skipped JSON line)
  6. obs smoke: tools/obsreport.py --json must report nonzero train steps,
     recompile-ledger events, and serving p50/p99 (docs/OBSERVABILITY.md)
  7. serve smoke: BENCH_MODEL=generate continuous-batching generation must
     produce tokens with a finite decode p99 (docs/SERVING.md)
  8. tune smoke: tiny-shape autotune into a throwaway cache dir must
     produce a loadable tuning table and prove measured dispatch via the
     helper-dispatch counters (docs/KERNELS.md)
  9. chaos smoke: tools/chaos.py under an injected fault schedule — every
     request must reach a terminal finish reason, the supervisor must
     restart within its cap with zero new_shape ledger events, and
     restore() must fall back past a torn checkpoint (docs/ROBUSTNESS.md)
 10. slo smoke: tools/slo.py goodput-under-overload ramp — frontend-on
     goodput must be >= frontend-off under an identical past-capacity
     schedule, with every request terminal and zero new_shape events
     (docs/SERVING.md § SLO admission frontend)
 11. prefix smoke: tools/prefix.py shared-prompt replay — prefix hit
     tokens > 0, TTFT p50 >= 30% better than cache-off, greedy outputs
     bit-identical both legs, zero new_shape events
     (docs/SERVING.md § Radix prefix cache)
 12. spec smoke: tools/spec.py speculative-decoding replay — accepted
     draft tokens > 0, tokens/sec >= spec-off, greedy outputs
     bit-identical both legs, exactly the expected first_compile events
     and zero new_shape (docs/SERVING.md § Speculative decoding)
 13. trainchaos smoke: tools/chaos.py --leg training — training killed
     mid-fit by injected faults must resume BIT-EXACT vs the
     uninterrupted oracle with zero new_shape, and async checkpointing's
     per-step overhead must be < 10% of the synchronous-save baseline
     (docs/ROBUSTNESS.md § Preemption-proof training)
 14. locktrace smoke: tools/locktrace.py shadow-lock cross-validation —
     the graftlock static lock-order graph must be acyclic, every
     lock-order edge observed under the threaded serving + checkpoint
     workload must lie inside its transitive closure, and the combined
     graph must stay acyclic (docs/LINT.md § graftlock)
 15. shapetrace smoke: tools/shapetrace.py recompile-ledger
     cross-validation — every CompileEvent recorded under the
     randomized-shape serving replay + checkpoint-resumed training
     workload must attribute to a statically known registration span,
     every new_shape must land in a statically flagged hazard module,
     and both legs must themselves observe zero new_shape
     (docs/LINT.md § graftshape)
 16. lifetrace smoke: tools/lifetrace.py runtime resource-lifecycle
     cross-validation — the faults-armed prefix cluster + async
     checkpoint workload must end with rc-clean pages, exactly one
     terminal count per request, zero leaked threads, every observed
     acquire/release callsite inside graftlife's static ownership
     inventory, and zero new_shape (docs/LINT.md § graftlife)
 17. aot smoke: tools/aot.py cold-restart warm boot — a fresh process
     restoring from the persistent export cache must pay zero serving
     first_compile events (cache_hit only), emit outputs bit-identical
     to the cache-off leg, and keep cold-start TTFT within 2x
     (docs/SERVING.md § AOT warm boot)

Exit code 0 = snapshot allowed; anything else = fix first.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(name: str, cmd, env=None, timeout=3600) -> bool:
    print(f"== gate: {name} ==", flush=True)
    e = dict(os.environ)
    if env:
        e.update(env)
    try:
        proc = subprocess.run(cmd, cwd=REPO, env=e, timeout=timeout,
                              capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        print(f"   FAIL ({name}: timeout after {timeout}s)")
        return False
    if proc.returncode != 0:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-25:])
        print(f"   FAIL ({name}, exit {proc.returncode})\n{tail}")
        return False
    print(f"   ok ({name})")
    return True


def has_tpu() -> bool:
    probe = ("import jax\n"
             "print(any(d.platform == 'tpu' for d in jax.devices()))")
    try:
        out = subprocess.run([sys.executable, "-c", probe], cwd=REPO,
                             capture_output=True, text=True, timeout=180)
        return "True" in out.stdout
    except Exception:
        return False


def bench_smoke() -> bool:
    print("== gate: bench smoke (lenet, 3 iters) ==", flush=True)
    # BENCH_RECORD=0: a 3-iter smoke is a liveness probe, not a measurement —
    # it must not touch the BENCH_HISTORY ratchet series
    env = dict(os.environ, BENCH_MODEL="lenet", BENCH_ITERS="3",
               BENCH_BATCH="64", BENCH_RECORD="0")
    try:
        proc = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                              capture_output=True, text=True, timeout=1800)
    except subprocess.TimeoutExpired:
        print("   FAIL (bench smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and "metric" in l), None)
    if proc.returncode != 0 or line is None:
        print(f"   FAIL (bench exit {proc.returncode}; no JSON line)")
        print("\n".join((proc.stdout + proc.stderr).splitlines()[-10:]))
        return False
    rec = json.loads(line)
    ok = rec.get("value", 0) > 0
    print(f"   {'ok' if ok else 'FAIL'} ({rec['metric']} = {rec['value']})")
    return ok


def native_stage() -> bool:
    """Build the native lib + run ctest, then an ASAN build + ctest
    (SURVEY §5.3/§6.2 — the libnd4j tests_cpu CI stage)."""
    steps = [
        ("cmake configure", ["cmake", "-S", "native", "-B", "native/build"]),
        ("cmake build", ["cmake", "--build", "native/build", "-j"]),
        ("ctest", ["ctest", "--test-dir", "native/build",
                   "--output-on-failure"]),
        ("cmake configure (ASAN)",
         ["cmake", "-S", "native", "-B", "native/build-asan",
          "-DSANITIZE=ON"]),
        ("cmake build (ASAN)", ["cmake", "--build", "native/build-asan",
                                "-j"]),
        ("ctest (ASAN)", ["ctest", "--test-dir", "native/build-asan",
                          "--output-on-failure"]),
    ]
    for name, cmd in steps:
        if not run(f"native: {name}", cmd, timeout=600):
            return False
    return True


def _baselined_tool_stage(tool: str, script: str, label: str) -> bool:
    """Shared stage driver for the baselined static-analysis tools
    (graftlint / graftcheck): run the script with --json, echo its ONE
    JSON summary line into the gate log so driver artifacts stay
    diagnosable, fail on any finding beyond the tool's shrink-only
    baseline."""
    print(f"== gate: {tool} ({label}) ==", flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, script, "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        print(f"   FAIL ({tool} timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL ({tool} exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    print(f"   ok ({tool}: {rec['total']} findings, "
          f"{rec['baselined']} grandfathered, {rec['new']} new)")
    return True


def lint_stage() -> bool:
    """graftlint over the whole repo (docs/LINT.md), vs
    lint_baseline.json."""
    return _baselined_tool_stage("graftlint", "tools/graftlint.py",
                                 "static analysis")


def check_stage() -> bool:
    """graftcheck over the fixture zoo (docs/ANALYSIS.md), vs
    check_baseline.json."""
    return _baselined_tool_stage("graftcheck", "tools/graftcheck.py",
                                 "graph shape/dtype verification")


def obs_stage() -> bool:
    """observability smoke (docs/OBSERVABILITY.md): the obsreport demo
    workload on CPU must report nonzero train steps, recompile-ledger
    events, and serving latency percentiles — one JSON line, like
    lint/check."""
    print("== gate: obs-smoke (obsreport demo workload) ==", flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "tools/obsreport.py", "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        print("   FAIL (obs-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (obs-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    ok = bool(rec.get("ok"))
    print(f"   {'ok' if ok else 'FAIL'} (obs-smoke: "
          f"{rec.get('train_steps')} steps, {rec.get('recompiles')} "
          f"recompiles, serving p99 {rec.get('serving_p99_ms')} ms)")
    return ok


def serve_stage() -> bool:
    """Generative-serving smoke (docs/SERVING.md): BENCH_MODEL=generate
    against the continuous-batching engine must emit ONE JSON line with
    generated tokens > 0 and a finite decode p99 — the bench.py subprocess
    backend probe gives it the CPU fallback, so this passes on CPU-only
    hosts. Like lint/check/obs: one machine-parsable line in the log."""
    print("== gate: serve-smoke (generate, open-loop) ==", flush=True)
    env = dict(os.environ, BENCH_MODEL="generate", BENCH_RECORD="0",
               BENCH_QPS="5", BENCH_REQUESTS="8", BENCH_GEN_TOKENS="8",
               BENCH_SLOTS="4", BENCH_GPT="tiny")
    try:
        proc = subprocess.run([sys.executable, "bench.py"], cwd=REPO, env=env,
                              capture_output=True, text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        print("   FAIL (serve-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and "metric" in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (serve-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    gen = rec.get("observe", {}).get("generate", {})
    p99 = gen.get("decode_p99_ms")
    ok = ((rec.get("value") or 0) > 0
          and (rec.get("generated_tokens") or 0) > 0
          and isinstance(p99, (int, float)) and p99 == p99)
    print(f"   {'ok' if ok else 'FAIL'} (serve-smoke: "
          f"{rec.get('generated_tokens')} tokens at "
          f"{rec.get('value')} tok/s, decode p99 {p99} ms)")
    return ok


def tune_stage() -> bool:
    """Autotuner smoke (docs/KERNELS.md): tiny-shape tune into a THROWAWAY
    cache dir must produce a loadable table and prove — via the
    dl4j_tpu_helper_dispatch_total counters — that small-shape attention
    dispatches to the XLA generic below the tuned threshold and to the
    Pallas helper above it. One JSON line, like lint/check/obs."""
    import tempfile

    print("== gate: tune-smoke (autotuner + measured dispatch) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_TPU_TUNING_DIR=tempfile.mkdtemp(prefix="gate_tune_"))
    env.pop("DL4J_TPU_FLASH_MIN_T", None)  # env override would mask the
    try:                                   # tuned-table dispatch proof
        proc = subprocess.run(
            [sys.executable, "tools/tune.py", "--smoke", "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (tune-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (tune-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    verify = rec.get("verify") or {}
    ok = (bool(rec.get("ok")) and rec.get("table_path")
          and verify.get("below_dispatch") == "xla"
          and verify.get("above_dispatch") == "pallas")
    print(f"   {'ok' if ok else 'FAIL'} (tune-smoke: "
          f"{rec.get('measurements')} candidates, flash_min_t="
          f"{verify.get('flash_min_t')}, below->{verify.get('below_dispatch')}"
          f", above->{verify.get('above_dispatch')})")
    return bool(ok)


def chaos_stage() -> bool:
    """Robustness smoke (docs/ROBUSTNESS.md): the chaos harness must
    report ok — faults fired > 0 (all required points), unresolved
    requests == 0, restarts within cap, zero new_shape events, checkpoint
    fallback intact. One JSON line, like lint/check/obs.

    The full composite deliberately includes the (cheap, ~10s) training
    leg even though trainchaos_stage re-runs it: `make chaos-smoke` must
    stay the one-command proof of the WHOLE failure surface in one
    process, and the trainchaos stage owns the (expensive) overhead
    measurement the composite skips."""
    print("== gate: chaos-smoke (fault injection + supervised recovery) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)  # an ambient schedule would double-
    try:                              # inject on top of the harness's own
        proc = subprocess.run(
            [sys.executable, "tools/chaos.py", "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (chaos-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (chaos-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    srv = rec.get("serving") or {}
    ok = (bool(rec.get("ok"))
          and (rec.get("faults_injected_total") or 0) > 0
          and srv.get("unresolved") == 0)
    print(f"   {'ok' if ok else 'FAIL'} (chaos-smoke: "
          f"{rec.get('faults_injected_total')} faults, "
          f"{srv.get('submitted')} submitted -> reasons {srv.get('reasons')}"
          f", {srv.get('restarts')} restarts, checkpoint fallback "
          f"{(rec.get('checkpoint') or {}).get('fallback_ok')})")
    return bool(ok)


def slo_stage() -> bool:
    """Goodput smoke (docs/SERVING.md § SLO admission frontend): the
    overload ramp must report frontend-on goodput >= frontend-off with
    every request terminal on both legs, the ladder engaged, and zero
    new_shape events. One JSON line, like lint/check/obs/chaos."""
    print("== gate: slo-smoke (goodput under overload, frontend on/off) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)  # an ambient schedule would distort
    try:                              # the measured legs
        proc = subprocess.run(
            [sys.executable, "tools/slo.py", "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (slo-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (slo-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    on = rec.get("frontend_on") or {}
    off = rec.get("frontend_off") or {}
    ok = (bool(rec.get("ok"))
          and (rec.get("goodput_on") or 0) >= (rec.get("goodput_off") or 0)
          and on.get("all_terminal") and off.get("all_terminal"))
    print(f"   {'ok' if ok else 'FAIL'} (slo-smoke: goodput on/off "
          f"{rec.get('goodput_on')}/{rec.get('goodput_off')} tok/s "
          f"(x{rec.get('goodput_ratio')}), states "
          f"{on.get('states_visited')}, reasons on={on.get('reasons')} "
          f"off={off.get('reasons')})")
    return bool(ok)


def prefix_stage() -> bool:
    """Prefix-cache smoke (docs/SERVING.md § Radix prefix cache): the
    shared-prompt replay must report ok — prefix hit tokens > 0, TTFT p50
    >= 30% better than cache-off (median of paired trials), greedy
    outputs bit-identical on both legs, zero new_shape events. One JSON
    line, like lint/check/obs/chaos/slo."""
    print("== gate: prefix-smoke (shared-prompt replay, cache on/off) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)  # an ambient schedule would distort
    try:                              # the paired TTFT comparison
        proc = subprocess.run(
            [sys.executable, "tools/prefix.py", "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (prefix-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (prefix-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    ok = (bool(rec.get("ok"))
          and (rec.get("prefix_hit_tokens") or 0) > 0
          and rec.get("outputs_identical")
          and rec.get("new_shape_events") == 0)
    print(f"   {'ok' if ok else 'FAIL'} (prefix-smoke: TTFT p50 "
          f"{rec.get('ttft_p50_ms_on')}/{rec.get('ttft_p50_ms_off')} ms "
          f"on/off (x{rec.get('ttft_speedup')}), "
          f"{rec.get('prefix_hit_tokens')} hit tokens, identical="
          f"{rec.get('outputs_identical')})")
    return bool(ok)


def spec_stage() -> bool:
    """Speculative-decoding smoke (docs/SERVING.md § Speculative
    decoding): the greedy replay must report ok — accepted draft tokens
    > 0, tokens/sec >= spec-off (median of paired trials), greedy
    outputs bit-identical on both legs, exactly the expected
    first_compile ledger events, zero new_shape. One JSON line, like
    lint/check/obs/chaos/slo/prefix."""
    print("== gate: spec-smoke (speculative replay, spec on/off) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)  # an ambient schedule would distort
    try:                              # the paired throughput comparison
        proc = subprocess.run(
            [sys.executable, "tools/spec.py", "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (spec-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (spec-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    ok = (bool(rec.get("ok"))
          and (rec.get("accepted_tokens") or 0) > 0
          and rec.get("outputs_identical")
          and rec.get("new_shape_events") == 0
          and rec.get("first_compiles_ok"))
    print(f"   {'ok' if ok else 'FAIL'} (spec-smoke: "
          f"{rec.get('tokens_per_sec_on')}/{rec.get('tokens_per_sec_off')} "
          f"tok/s on/off (x{rec.get('speedup')}), "
          f"{rec.get('accepted_tokens')}/{rec.get('proposed_tokens')} "
          f"accepted, identical={rec.get('outputs_identical')})")
    return bool(ok)


def aot_stage() -> bool:
    """AOT warm-boot smoke (docs/SERVING.md § AOT warm boot): three
    fresh processes replay the identical randomized-shape request mix —
    compile cache off, populating, and warm. The warm restart must pay
    ZERO serving first_compile ledger events (everything it dispatches
    arrives as cache_hit), produce outputs bit-identical to the
    cache-off leg, observe zero new_shape, and keep cold-start TTFT
    (process boot + first token) within 2x the cache-off leg. One JSON
    line, like lint/check/obs/chaos/slo/prefix/spec."""
    print("== gate: aot-smoke (cold-restart warm boot, cache off/on) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)   # ambient faults would distort the
    env.pop("DL4J_TPU_COMPILE_CACHE", None)  # paired TTFT legs / cache state
    try:
        proc = subprocess.run(
            [sys.executable, "tools/aot.py", "--json"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (aot-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (aot-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    ok = (bool(rec.get("ok"))
          and rec.get("warm_first_compile_keys") == []
          and len(rec.get("warm_cache_hit_keys") or []) > 0
          and rec.get("outputs_identical")
          and rec.get("new_shape_events") == 0)
    print(f"   {'ok' if ok else 'FAIL'} (aot-smoke: warm first_compiles="
          f"{rec.get('warm_first_compile_keys')}, cache_hits="
          f"{rec.get('warm_cache_hit_keys')}, ttft cold/warm="
          f"{rec.get('ttft_cold_off_ms')}/{rec.get('ttft_warm_ms')}ms "
          f"(x{rec.get('cold_restart_ttft_ratio')}), "
          f"identical={rec.get('outputs_identical')})")
    return bool(ok)


def trainchaos_stage() -> bool:
    """Preemption-proof-training smoke (docs/ROBUSTNESS.md §
    Preemption-proof training): training killed mid-fit by injected
    faults (torn checkpoint write + async-writer death + hard
    preemption) must resume to a BIT-EXACT loss/param trajectory vs the
    uninterrupted oracle with zero new_shape recompiles, every on-disk
    checkpoint intact or detectably corrupt, and every-step async
    checkpointing's per-step overhead < 10% of the synchronous-save
    baseline. One JSON line, like lint/check/obs/chaos."""
    print("== gate: train-chaos-smoke (preemption-proof training) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)  # an ambient schedule would double-
    try:                              # inject on top of the harness's own
        proc = subprocess.run(
            [sys.executable, "tools/chaos.py", "--json", "--leg",
             "training"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (train-chaos-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (train-chaos-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    tr = rec.get("training") or {}
    ovh = rec.get("overhead") or {}
    ok = (bool(rec.get("ok"))
          and tr.get("trajectory_bit_exact")
          and tr.get("params_bit_exact")
          and tr.get("new_shape_events") == 0
          and (tr.get("resumes") or 0) >= 1
          and bool(ovh.get("ok")))
    print(f"   {'ok' if ok else 'FAIL'} (train-chaos-smoke: "
          f"{tr.get('steps')} steps, {tr.get('resumes')} resumes, fired "
          f"{tr.get('fired')}, bit-exact={tr.get('trajectory_bit_exact')}"
          f", async overhead {ovh.get('async_overhead_ms')}ms vs sync "
          f"{ovh.get('sync_overhead_ms')}ms "
          f"(ratio {ovh.get('overhead_ratio')}))")
    return bool(ok)


def cluster_stage() -> bool:
    """Cluster-failure-domain smoke (docs/ROBUSTNESS.md § Cluster
    failure domains): three engines behind the ClusterRouter under a
    past-capacity burst, one hard-killed mid-flight by ``engine_death``
    — fails unless every request reaches a terminal state on both legs,
    at least one in-flight request migrates with its greedy output
    token-for-token identical to the single-engine oracle, goodput
    degrades no worse than proportionally to the capacity lost, and
    survivors show zero ``new_shape`` ledger events. One JSON line,
    like lint/check/obs/chaos."""
    print("== gate: cluster-chaos-smoke (kill one engine, migrate) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)  # an ambient schedule would double-
    try:                              # inject on top of the harness's own
        proc = subprocess.run(
            [sys.executable, "tools/chaos.py", "--json", "--leg",
             "cluster"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print("   FAIL (cluster-chaos-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (cluster-chaos-smoke exit {proc.returncode})\n"
              f"{tail}")
        return False
    rec = json.loads(line)
    cl = rec.get("cluster") or {}
    kd = cl.get("killed") or {}
    ok = (bool(rec.get("ok"))
          and kd.get("deaths") == 1
          and (kd.get("migrations") or 0) >= 1
          and kd.get("bit_exact")
          and kd.get("unresolved") == 0
          and kd.get("new_shape_events") == 0
          and cl.get("goodput_proportional_ok"))
    full = cl.get("full") or {}
    print(f"   {'ok' if ok else 'FAIL'} (cluster-chaos-smoke: "
          f"{kd.get('submitted')} submitted, {kd.get('deaths')} death, "
          f"{kd.get('migrations')} migrated, bit-exact="
          f"{kd.get('bit_exact')}, goodput "
          f"{kd.get('goodput_tokens_per_sec')} vs full "
          f"{full.get('goodput_tokens_per_sec')} tok/s, new_shape "
          f"{kd.get('new_shape_events')})")
    return bool(ok)


def locktrace_stage() -> bool:
    """Locktrace smoke (docs/LINT.md § graftlock): runtime shadow-lock
    cross-validation of the static lock-order graph — fails if the
    static graph has a cycle, any observed runtime edge falls outside
    its transitive closure (an analyzer blind spot), the combined graph
    is cyclic, or the threaded workload leaves unresolved work. One
    JSON line, like lint/check/obs/chaos."""
    print("== gate: locktrace-smoke (shadow-lock vs static order) ==",
          flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "tools/locktrace.py"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        print("   FAIL (locktrace-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (locktrace-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    ok = (bool(rec.get("ok"))
          and rec.get("static_acyclic")
          and not rec.get("unknown_edges")
          and rec.get("combined_cycle") is None
          and len(rec.get("observed_edges") or []) > 0)
    print(f"   {'ok' if ok else 'FAIL'} (locktrace-smoke: "
          f"{rec.get('static_edges')} static edges, "
          f"{len(rec.get('observed_edges') or [])} observed, "
          f"{len(rec.get('unknown_edges') or [])} outside closure, "
          f"combined cycle {rec.get('combined_cycle')})")
    return bool(ok)


def shapetrace_stage() -> bool:
    """Shapetrace smoke (docs/LINT.md § graftshape): runtime
    recompile-ledger cross-validation of the static jit-boundary
    inventory — fails if any ledger event recorded under the
    randomized-shape serving + resumed-training workload is
    unattributed (callsite outside every statically known registration
    span), any new_shape lands in a statically clean module, either leg
    itself pays a new_shape, or the window saw no ledger traffic at
    all. One JSON line, like lint/check/obs/chaos/locktrace."""
    print("== gate: shapetrace-smoke (recompile ledger vs static "
          "jit inventory) ==", flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "tools/shapetrace.py"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        print("   FAIL (shapetrace-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (shapetrace-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    ok = (bool(rec.get("ok"))
          and not rec.get("unattributed")
          and not rec.get("new_shape_unexplained")
          and (rec.get("events") or 0) > 0)
    print(f"   {'ok' if ok else 'FAIL'} (shapetrace-smoke: "
          f"{rec.get('events')} ledger events, "
          f"{len(rec.get('unattributed') or [])} unattributed, "
          f"{rec.get('new_shape_total')} new_shape / "
          f"{len(rec.get('new_shape_unexplained') or [])} unexplained)")
    return bool(ok)


def lifetrace_stage() -> bool:
    """Lifetrace smoke (docs/LINT.md § graftlife): runtime
    resource-lifecycle cross-validation of the static ownership
    inventory — fails unless the faults-armed cluster + checkpoint
    workload ends rc-clean (observed acquires - releases == live
    refcount mass, allocator invariants hold), every tracked request
    terminal is counted exactly once, no thread leaks, every observed
    acquire/release callsite lies inside a static inventory span, and
    the recoveries paid zero new_shape. One JSON line, like
    lint/check/obs/chaos/locktrace/shapetrace."""
    print("== gate: lifetrace-smoke (resource tracer vs static "
          "ownership inventory) ==", flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "tools/lifetrace.py"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        print("   FAIL (lifetrace-smoke timeout)")
        return False
    line = next((l for l in proc.stdout.splitlines()
                 if l.startswith("{") and '"tool"' in l), None)
    if line:
        print(f"   {line}")
    if proc.returncode != 0 or line is None:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-15:])
        print(f"   FAIL (lifetrace-smoke exit {proc.returncode})\n{tail}")
        return False
    rec = json.loads(line)
    pages = rec.get("pages") or {}
    terms = rec.get("terminals") or {}
    ok = (bool(rec.get("ok"))
          and pages.get("rc_balanced")
          and not pages.get("invariant_errors")
          and terms.get("exactly_once")
          and not (rec.get("threads") or {}).get("leaked")
          and not (rec.get("callsites") or {}).get("unknown")
          and (rec.get("new_shape_events") or 0) == 0)
    print(f"   {'ok' if ok else 'FAIL'} (lifetrace-smoke: "
          f"{pages.get('acquires')} acquires / {pages.get('releases')} "
          f"releases, live {pages.get('live_refs')}, terminals "
          f"{terms.get('counted')}/{terms.get('tracked')}, "
          f"{len((rec.get('callsites') or {}).get('unknown') or [])} "
          f"unknown callsites, new_shape {rec.get('new_shape_events')})")
    return bool(ok)


def multichip_stage() -> bool:
    """Multichip dryrun with explicit skipped-status passthrough: the
    hardened __graft_entry__.dryrun_multichip prints ONE JSON line with
    "skipped": true on backend/environment failures — surface it in the
    gate log instead of a silent ok."""
    print("== gate: multichip dryrun (8 virtual CPU devices) ==", flush=True)
    try:
        # outer timeout must exceed dryrun's own probe (240s) + the THREE
        # per-stage worker watchdogs (3 × 600s default) so even the
        # every-stage-hung case reaches its skipped lines instead of being
        # killed from outside just before reporting them
        proc = subprocess.run(
            [sys.executable, "-c",
             "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"],
            cwd=REPO, env=dict(os.environ), capture_output=True, text=True,
            timeout=2100)
    except subprocess.TimeoutExpired:
        print("   FAIL (multichip timeout)")
        return False
    skips = [l for l in proc.stdout.splitlines()
             if l.startswith("{") and '"skipped": true' in l]
    if proc.returncode != 0:
        tail = "\n".join((proc.stdout + proc.stderr).splitlines()[-25:])
        print(f"   FAIL (multichip exit {proc.returncode})\n{tail}")
        return False
    if skips:
        for line in skips:  # per-stage watchdog markers — each is signal
            print(f"   SKIPPED (environment): {line}")
        return True
    print("   ok (multichip)")
    return True


def main() -> int:
    fast = "--fast" in sys.argv
    results = {}

    # static analysis runs in BOTH modes: it is the cheapest stage and the
    # one that catches the hang class before anything can hang
    results["lint"] = lint_stage()
    # graph verification also runs in BOTH modes: build-only (no jit), so
    # it is nearly free and catches importer/optimizer shape regressions
    # before the pytest stage spends minutes compiling them
    results["check"] = check_stage()

    if not fast:  # --fast stays "pytest only" (pre-commit speed)
        results["native"] = native_stage()

    # DL4J_TPU_REQUIRE_NATIVE: under the gate, a missing .so FAILS the
    # ctypes tests instead of silently exercising the numpy fallback
    results["pytest"] = run(
        "pytest (CPU harness)",
        [sys.executable, "-m", "pytest", "tests/", "-q", "-x"],
        env={"DL4J_TPU_REQUIRE_NATIVE": "1"},
        timeout=2400)

    if not fast:
        if has_tpu():
            results["consistency"] = run(
                "CPU-vs-TPU consistency (real chip)",
                [sys.executable, "-m", "deeplearning4j_tpu.testing.consistency"],
                timeout=1800)
            results["bench"] = bench_smoke()
        else:
            print("== gate: WARNING — no TPU reachable; consistency + bench "
                  "smoke SKIPPED (do not snapshot a chip-affecting change "
                  "from this state) ==")
        results["obs"] = obs_stage()
        results["serve"] = serve_stage()
        results["tune"] = tune_stage()
        results["chaos"] = chaos_stage()
        results["trainchaos"] = trainchaos_stage()
        results["cluster"] = cluster_stage()
        results["locktrace"] = locktrace_stage()
        results["shapetrace"] = shapetrace_stage()
        results["lifetrace"] = lifetrace_stage()
        results["slo"] = slo_stage()
        results["prefix"] = prefix_stage()
        results["spec"] = spec_stage()
        results["aot"] = aot_stage()
        results["multichip"] = multichip_stage()

    failed = [k for k, v in results.items() if not v]
    if failed:
        print(f"\nGATE RED: {failed} — fix before snapshotting")
        return 1
    print("\nGATE GREEN: snapshot allowed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
