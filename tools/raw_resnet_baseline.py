"""Hand-rolled raw-JAX ResNet-50 train step — the control experiment for
docs/PERF_ANALYSIS.md: if this runs at the same speed as the framework's
ComputationGraph step, the framework adds no overhead and the remaining
bound is XLA's own fusion structure, not our graph machinery."""

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bn_train(x, gamma, beta, eps=1e-5):
    f32 = jnp.float32
    axes = (0, 1, 2)
    xf = x.astype(f32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.mean(jnp.square(xf - mean), axis=axes)
    inv = lax.rsqrt(var + eps)
    scale = (inv * gamma).astype(x.dtype)
    shift = (beta - mean * inv * gamma).astype(x.dtype)
    return x * scale + shift


def bottleneck(params, x, stride, project):
    s = x
    y = conv(x, params["w1"], stride)
    y = jax.nn.relu(bn_train(y, params["g1"], params["b1"]))
    y = conv(y, params["w2"])
    y = jax.nn.relu(bn_train(y, params["g2"], params["b2"]))
    y = conv(y, params["w3"])
    y = bn_train(y, params["g3"], params["b3"])
    if project:
        s = conv(x, params["ws"], stride)
        s = bn_train(s, params["gs"], params["bs"])
    return jax.nn.relu(y + s)


STAGES = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]


def init_params(key, dtype=jnp.float32):
    r = np.random.RandomState(0)

    def w(shape):
        fan_in = np.prod(shape[:-1])
        return jnp.asarray((r.randn(*shape) * np.sqrt(2.0 / fan_in))
                           .astype(np.float32), dtype)

    params = {"conv1": w((7, 7, 3, 64)),
              "g0": jnp.ones((64,), dtype), "b0": jnp.zeros((64,), dtype)}
    c_in = 64
    for si, (f, blocks, stride) in enumerate(STAGES):
        for bi in range(blocks):
            p = {}
            p["w1"] = w((1, 1, c_in, f))
            p["g1"], p["b1"] = jnp.ones((f,), dtype), jnp.zeros((f,), dtype)
            p["w2"] = w((3, 3, f, f))
            p["g2"], p["b2"] = jnp.ones((f,), dtype), jnp.zeros((f,), dtype)
            p["w3"] = w((1, 1, f, 4 * f))
            p["g3"], p["b3"] = jnp.ones((4 * f,), dtype), jnp.zeros((4 * f,), dtype)
            if bi == 0:
                p["ws"] = w((1, 1, c_in, 4 * f))
                p["gs"], p["bs"] = jnp.ones((4 * f,), dtype), jnp.zeros((4 * f,), dtype)
            params[f"s{si}b{bi}"] = p
            c_in = 4 * f
    params["fc_w"] = w((2048, 1000))
    params["fc_b"] = jnp.zeros((1000,), dtype)
    return params


def forward(params, x):
    y = conv(x, params["conv1"], 2)
    y = jax.nn.relu(bn_train(y, params["g0"], params["b0"]))
    y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    for si, (f, blocks, stride) in enumerate(STAGES):
        for bi in range(blocks):
            y = bottleneck(params[f"s{si}b{bi}"], y,
                           stride if bi == 0 else 1, bi == 0)
    y = jnp.mean(y, axis=(1, 2))
    return y.astype(jnp.float32) @ params["fc_w"].astype(jnp.float32) + \
        params["fc_b"]


def main():
    batch = 128
    r = np.random.RandomState(0)
    x = jnp.asarray(r.rand(batch, 224, 224, 3).astype(np.float32))
    labels = jnp.asarray(r.randint(0, 1000, batch))
    params = init_params(jax.random.key(0))

    def loss_fn(p, xb):
        xb = xb.astype(jnp.bfloat16)
        pb = jax.tree.map(lambda a: a.astype(jnp.bfloat16)
                          if a.dtype == jnp.float32 else a, p)
        logits = forward(pb, xb)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(logp[jnp.arange(batch), labels])

    @jax.jit
    def step(p, xb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb)
        p = jax.tree.map(lambda a, d: a - 0.1 * d.astype(a.dtype), p, g)
        return p, loss

    params, loss = step(params, x)
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        params, loss = step(params, x)
    jax.block_until_ready(params)
    dt = (time.perf_counter() - t0) / n
    print(f"raw jax resnet50: {dt * 1e3:.2f} ms/step  {batch / dt:.1f} img/s")


if __name__ == "__main__":
    main()
