#!/usr/bin/env python
"""Thin wrapper: `python tools/graftcheck.py` ==
`python -m deeplearning4j_tpu.analysis` (graftcheck — docs/ANALYSIS.md).
Kept in tools/ so the gate and humans share one entry point layout with
tools/graftlint.py."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    main()
