"""Round-5 product-surface demo: an MoE network trained dp×ep through the
standard ParallelWrapper.fit(), and a config-built pipeline-parallel
trainer with the stock updaters/listeners — no hand-written shard_map.

Run: python examples/moe_pipeline_parallel.py
(forces an 8-device virtual CPU mesh so it runs anywhere; on a real pod
the same code spans the chips)"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from deeplearning4j_tpu import nn  # noqa: E402
from deeplearning4j_tpu.datasets.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.nn.listeners import ScoreIterationListener  # noqa: E402
from deeplearning4j_tpu.parallel.mesh import (  # noqa: E402
    ParallelWrapper, moe_ep_rules)
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: E402
    PipelineParallelTrainer)


def moe_dp_ep():
    """A Mixture-of-Experts FFN declared like any other layer; the mesh's
    'expert' axis + moe_ep_rules shard the experts, GSPMD inserts the
    dispatch collectives."""
    b = (nn.builder().seed(0).updater(nn.Adam(learning_rate=5e-3)).list()
         .layer(nn.DenseLayer(n_out=32, activation="relu"))
         .layer(nn.MoELayer(d_hidden=64, n_experts=4, top_k=2,
                            activation="relu"))
         .layer(nn.OutputLayer(n_out=5, activation="softmax", loss="mcxent")))
    net = nn.MultiLayerNetwork(
        b.set_input_type(nn.InputType.feed_forward(32)).build()).init()

    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "expert"))
    pw = ParallelWrapper(net, mesh=mesh, tp_rules=moe_ep_rules("expert"))
    r = np.random.RandomState(0)
    x = r.randn(256, 32).astype(np.float32)
    y = np.eye(5)[r.randint(0, 5, 256)].astype(np.float32)
    net.listeners = [ScoreIterationListener(5)]
    pw.fit(DataSet(x, y), epochs=6, batch_size=64)
    print(f"MoE dp×ep: final score {net.score():.4f}, "
          f"dropped assignments {float(net.net_state[1]['_dropped_frac']):.1%}")


def pipeline_dp_pp():
    """A transformer-ish block declared as layer configs, trained GPipe-
    style over a data×pipe mesh with Adam + listeners + the standard
    checkpointing hooks."""
    d = 16
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "pipe"))
    r = np.random.RandomState(1)
    head = {"W": jnp.asarray(r.randn(d, 3).astype(np.float32) * 0.3)}

    def head_fn(hp, feats, y):
        logp = jax.nn.log_softmax(feats @ hp["W"])
        return -jnp.mean(jnp.sum(y * logp, axis=-1))

    trainer = PipelineParallelTrainer.from_confs(
        [nn.DenseLayer(n_out=d, activation="tanh")],
        head_fn, d, mesh, num_microbatches=4,
        updater=nn.Adam(learning_rate=0.01), head_params=head)
    x = jnp.asarray(r.randn(32, d).astype(np.float32))
    y = jnp.asarray(np.eye(3)[r.randint(0, 3, 32)].astype(np.float32))
    losses = trainer.fit(x, y, steps=40)
    print(f"pipeline dp×pp: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"over {len(losses)} steps on mesh {dict(mesh.shape)}")


if __name__ == "__main__":
    moe_dp_ep()
    pipeline_dp_pp()
