"""Import a TF SavedModel with its trained weights and fine-tune it
(TFGraphMapper checkpoint-restore role): the imported variables are
trainable SDVariables, so a TrainingConfig fit starts from the pretrained
point rather than random init."""

import os
import tempfile

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import tensorflow as tf

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.imports.tf_import import import_saved_model


def main():
    rng = np.random.RandomState(0)

    # --- "pretrained" TF model (stands in for a downloaded SavedModel) ---
    class M(tf.Module):
        def __init__(self):
            super().__init__()
            self.w = tf.Variable(rng.randn(8, 3).astype(np.float32) * 0.5,
                                 name="w")
            self.b = tf.Variable(np.zeros(3, np.float32), name="b")

        @tf.function(input_signature=[tf.TensorSpec([None, 8], tf.float32)])
        def __call__(self, x):
            return tf.nn.softmax(x @ self.w + self.b)

    m = M()
    path = os.path.join(tempfile.mkdtemp(), "saved_model")
    tf.saved_model.save(m, path)

    # --- import: weights land as VARIABLE-role SDVariables ---
    sd = import_saved_model(path)
    x = rng.randn(5, 8).astype(np.float32)
    got = sd.output({sd.graph_inputs[0]: x},
                    sd.graph_outputs[0])[sd.graph_outputs[0]]
    np.testing.assert_allclose(got, m(tf.constant(x)).numpy(),
                               rtol=1e-4, atol=1e-5)
    print("imported outputs match TF: True")

    # --- fine-tune on new labels ---
    steps = int(os.environ.get("EXAMPLE_MAX_BATCHES", "20"))
    xs = rng.randn(256, 8).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 256)]
    labels = sd.placeholder("labels", shape=(None, 3))
    out_var = sd._vars[sd.graph_outputs[0]]
    sd.loss.mean_squared_error(out_var, labels).rename("ft_loss")
    sd.set_training_config(TrainingConfig(
        updater=nn.Adam(learning_rate=0.05),
        data_set_feature_mapping=[sd.graph_inputs[0]],
        data_set_label_mapping=["labels"],
        loss_variables=["ft_loss"]))
    w_name = next(n for n, v in sd._vars.items() if v.vtype == "VARIABLE"
                  and np.asarray(sd.get_arr(n)).shape == (8, 3))
    before = np.asarray(sd.get_arr(w_name)).copy()
    hist = sd.fit(ListDataSetIterator(DataSet(xs, ys), batch_size=64),
                  epochs=max(steps // 4, 1))
    after = np.asarray(sd.get_arr(w_name))
    print(f"fine-tune loss: {hist[0]:.4f} -> {hist[-1]:.4f}")
    print("weights moved from the pretrained point:",
          bool(not np.allclose(before, after)))


if __name__ == "__main__":
    main()
