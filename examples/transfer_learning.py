"""Transfer learning — freeze a pretrained-style backbone, graft a new
head (dl4j-examples TransferLearning role).

Run: python examples/transfer_learning.py"""

import numpy as np

from deeplearning4j_tpu import models, nn
from deeplearning4j_tpu.nn.transfer import (FineTuneConfiguration,
                                            TransferLearning)


def main():
    # "pretrained" backbone (here: freshly initialized SimpleCNN; swap in a
    # restored zip via nn.restore_model for a real workflow)
    base = models.SimpleCNN(num_classes=10, input_shape=(32, 32, 3),
                            seed=7).init()

    new_net = (TransferLearning.builder(base)
               .fine_tune_configuration(
                   FineTuneConfiguration(updater=nn.Adam(learning_rate=5e-4)))
               .set_feature_extractor(3)      # freeze layers 0..3
               .remove_output_layer()
               .add_layer(nn.OutputLayer(n_out=5, activation="softmax",
                                         loss="mcxent"))
               .build())

    r = np.random.RandomState(0)
    x = r.rand(64, 32, 32, 3).astype(np.float32)
    y = np.eye(5)[r.randint(0, 5, 64)].astype(np.float32)
    frozen_before = np.asarray(new_net.params[0]["W"]).copy()
    new_net.fit(x, y, epochs=2, batch_size=16)
    frozen_after = np.asarray(new_net.params[0]["W"])
    print("frozen backbone unchanged:",
          bool(np.allclose(frozen_before, frozen_after)))
    print("final score:", float(new_net.score()))


if __name__ == "__main__":
    main()
