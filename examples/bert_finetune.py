"""BERT fine-tuning on TPU — mixed bf16, fused multi-step training, and
the Pallas flash-attention platform helper (the SameDiff-BERT example
role at example scale).

Run: python examples/bert_finetune.py  (tiny config so it runs anywhere;
scale cfg/seq/batch up on a real chip)"""

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.models.bert import BertConfig, BertModel


def main():
    cfg = BertConfig(vocab_size=1000, hidden=64, layers=2, heads=4,
                     intermediate=128, max_position=64)
    model = BertModel(cfg, seed=0, dtype=jnp.bfloat16)

    r = np.random.RandomState(0)
    batch, seq = 8, 32
    data = {
        "ids": r.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32),
        "segments": np.zeros((batch, seq), np.int32),
        "mask": np.ones((batch, seq), np.int32),
        "mlm_labels": r.randint(0, cfg.vocab_size,
                                (batch, seq)).astype(np.int32),
        "mlm_mask": (r.rand(batch, seq) < 0.15).astype(np.float32),
    }
    losses = model.fit_mlm_scanned(data, 30)  # 30 steps in ONE device call
    print(f"MLM loss: {float(losses[0]):.3f} -> {float(losses[-1]):.3f}")


if __name__ == "__main__":
    main()
