"""Data-parallel training over a device mesh — the ParallelWrapper /
SharedTrainingMaster role, the TPU way: shard the batch over a mesh axis
and let XLA insert the gradient all-reduce over ICI.

Run: python examples/distributed_data_parallel.py
(forces an 8-device virtual CPU mesh so it runs anywhere; on a real pod,
drop the env lines and the same code spans the chips)"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu import nn  # noqa: E402
from deeplearning4j_tpu.datasets.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh  # noqa: E402


def main():
    conf = (nn.builder()
            .seed(7)
            .updater(nn.Nesterovs(learning_rate=0.05, momentum=0.9))
            .list()
            .layer(nn.DenseLayer(n_out=64, activation="relu"))
            .layer(nn.OutputLayer(n_out=10, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.feed_forward(32))
            .build())
    net = nn.MultiLayerNetwork(conf).init()

    mesh = make_mesh({"data": len(jax.devices())})
    pw = ParallelWrapper(net, mesh=mesh)
    r = np.random.RandomState(0)
    x = r.randn(512, 32).astype(np.float32)
    y = np.eye(10)[r.randint(0, 10, 512)].astype(np.float32)
    pw.fit(DataSet(x, y), epochs=3, batch_size=256)
    print(f"trained over {len(jax.devices())} devices; "
          f"final score {float(net.score()):.4f}")


if __name__ == "__main__":
    main()
