"""Multi-host data-parallel fit worker — run under the launcher:

    python -m deeplearning4j_tpu.parallel.launch --nprocs 2 --restarts 1 \
        -- examples/distributed_fit.py --steps 12 --checkpoint-dir /tmp/ck

Each process forms one rank of a jax.distributed cluster
(SharedTrainingMaster worker role), feeds ITS shard of every global batch,
and the jitted step's gradient all-reduce rides XLA collectives. Process 0
persists the replicated training state every --checkpoint-every steps; on
relaunch every rank restores the latest checkpoint and continues from the
NEXT step, which is what makes `launch --restarts N` an elastic
checkpoint-restart story (SURVEY §4.4, §6.3, §6.4).

--crash-at K + --crash-marker PATH inject a one-shot failure: rank 0 dies
hard at global step K on the first attempt only (the marker file makes the
relaunch skip the crash) — the fault-injection hook the recovery test uses.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

import numpy as np


def make_step_batch(step: int, global_batch: int, n_in: int, n_out: int):
    """Deterministic global batch for a step — every rank derives the SAME
    global data and slices its own contiguous shard."""
    r = np.random.RandomState(1000 + step)
    x = r.randn(global_batch, n_in).astype(np.float32)
    w_true = np.linspace(-1, 1, n_in * n_out).reshape(n_in, n_out)
    logits = x @ w_true
    y = (logits == logits.max(axis=1, keepdims=True)).astype(np.float32)
    return x, y


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--global-batch", type=int, default=16)
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--checkpoint-every", type=int, default=4)
    ap.add_argument("--out", default=None,
                    help="process 0 writes final losses+param digest here")
    ap.add_argument("--crash-at", type=int, default=0)
    ap.add_argument("--crash-marker", default=None)
    ns = ap.parse_args()

    import jax

    # honor an explicit JAX_PLATFORMS=cpu via jax.config: a sitecustomize
    # that pins another platform wins over the env var alone, and this
    # multi-process demo must not have N workers fight over one real chip
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # cluster formation MUST precede any backend-initializing jax call, and
    # importing the framework creates RNG keys — so initialize first
    from deeplearning4j_tpu.parallel.launch import initialize_distributed

    initialize_distributed()

    from deeplearning4j_tpu import nn
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
    from deeplearning4j_tpu.parallel.checkpoint import TrainingCheckpointer
    from deeplearning4j_tpu.nn.listeners import TrainingListener
    pid, nproc = jax.process_index(), jax.process_count()
    n_in, n_out = 8, 4

    net = nn.MultiLayerNetwork(
        nn.builder().seed(7).updater(nn.Sgd(learning_rate=0.1)).list()
        .layer(nn.DenseLayer(n_out=16, activation="tanh"))
        .layer(nn.OutputLayer(n_out=n_out, activation="softmax", loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(n_in)).build()).init()

    ck = TrainingCheckpointer(ns.checkpoint_dir, use_orbax=False)
    restored = ck.restore(net)
    start = net.iteration_count if restored is not None else 0
    if restored is not None:
        print(f"[rank {pid}] resumed from step {start}", flush=True)

    local = ns.global_batch // nproc
    batches = []
    for step in range(start, ns.steps):
        x, y = make_step_batch(step, ns.global_batch, n_in, n_out)
        batches.append(DataSet(x[pid * local:(pid + 1) * local],
                               y[pid * local:(pid + 1) * local]))

    losses = []

    class Recorder(TrainingListener):
        def iteration_done(self, model, iteration, epoch, loss):
            losses.append(float(loss))
            if (ns.crash_at and iteration == ns.crash_at and pid == 0
                    and ns.crash_marker and not os.path.exists(ns.crash_marker)):
                open(ns.crash_marker, "w").write("crashed")
                print(f"[rank 0] injected crash at step {iteration}",
                      flush=True)
                os._exit(17)

    net.set_listeners(Recorder())
    pw = ParallelWrapper(net, mesh=make_mesh({"data": len(jax.devices())}))
    pw.fit(batches, epochs=1, checkpointer=ck,
           checkpoint_every=ns.checkpoint_every)

    if pid == 0 and ns.out:
        digest = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(net.params):
            digest.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        json.dump({"first_step": start, "losses": losses,
                   "param_sha256": digest.hexdigest(),
                   "final_iteration": net.iteration_count},
                  open(ns.out, "w"))
    print(f"[rank {pid}] done at step {net.iteration_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
