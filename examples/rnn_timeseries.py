"""Recurrent nets: LSTM sequence classification with truncated BPTT and
streaming inference (dl4j-examples UCISequenceClassification role).

Run: python examples/rnn_timeseries.py"""

import numpy as np

from deeplearning4j_tpu import nn


def make_data(n=128, t=24, f=3, seed=0):
    """Toy task: classify whether the first feature's mean is positive."""
    r = np.random.RandomState(seed)
    x = r.randn(n, t, f).astype(np.float32)
    x[:, :, 0] += np.where(r.rand(n) > 0.5, 0.8, -0.8)[:, None]
    y = np.eye(2)[(x[:, :, 0].mean(1) > 0).astype(int)].astype(np.float32)
    # per-timestep labels for the RnnOutputLayer
    return x, np.repeat(y[:, None, :], t, axis=1)


def main():
    conf = (nn.builder()
            .seed(42)
            .updater(nn.Adam(learning_rate=5e-3))
            .list()
            .layer(nn.LSTM(n_out=16, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=2, activation="softmax",
                                     loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(3, 24))
            .tbptt(8, 8)  # truncated BPTT, 8-step segments
            .build())
    net = nn.MultiLayerNetwork(conf).init()

    x, y = make_data()
    net.fit(x, y, epochs=3, batch_size=32)
    print("training score:", float(net.score()))

    # streaming inference: feed one step at a time, state carries over
    net.rnn_clear_previous_state()
    stream = [net.rnn_time_step(x[:4, i]) for i in range(6)]
    print("streamed 6 steps; last-step output shape:", stream[-1].shape)


if __name__ == "__main__":
    main()
