"""Hyperparameter search with the arbiter module (arbiter-core role):
random search over learning rate + width for a small classifier, grid
refinement around the winner."""

import os

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace, DiscreteParameterSpace,
    GridSearchCandidateGenerator, IntegerParameterSpace,
    LocalOptimizationRunner, RandomSearchGenerator)
from deeplearning4j_tpu.arbiter import test_set_loss_score as loss_score
from deeplearning4j_tpu.datasets.dataset import DataSet


def make_data(seed, n=256):
    r = np.random.RandomState(seed)
    x = r.randn(n, 8).astype(np.float32)
    w = r.randn(8, 3).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[(x @ w).argmax(axis=1)]
    return [DataSet(x, y)]


def build(params):
    return nn.MultiLayerNetwork(
        nn.builder().seed(7)
        .updater(nn.Adam(learning_rate=params["lr"])).list()
        .layer(nn.DenseLayer(n_out=params["width"], activation="relu"))
        .layer(nn.OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(nn.InputType.feed_forward(8)).build()).init()


def main():
    budget = int(os.environ.get("EXAMPLE_MAX_BATCHES", "6"))
    train, heldout = make_data(0), make_data(1)

    # stage 1: random exploration
    explore = LocalOptimizationRunner(
        build,
        RandomSearchGenerator({"lr": ContinuousParameterSpace(1e-4, 0.3,
                                                              log=True),
                               "width": IntegerParameterSpace(4, 64)},
                              seed=0),
        train_data=train, score_data=heldout, score_fn=loss_score,
        epochs=10, max_candidates=budget)
    best = explore.execute()
    print(f"random search best: lr={best.parameters['lr']:.4g} "
          f"width={best.parameters['width']} loss={best.score:.4f}")

    # stage 2: grid around the winner's learning rate
    lo, hi = best.parameters["lr"] / 3, best.parameters["lr"] * 3
    refine = LocalOptimizationRunner(
        build,
        GridSearchCandidateGenerator(
            {"lr": ContinuousParameterSpace(lo, hi, log=True),
             "width": best.parameters["width"]}, discretization=3),
        train_data=train, score_data=heldout, score_fn=loss_score,
        epochs=10, max_candidates=3)
    refined = refine.execute()
    print(f"grid refinement best: lr={refined.parameters['lr']:.4g} "
          f"loss={refined.score:.4f}")
    print(f"search ok: {len(explore.results) + len(refine.results)} trials")


if __name__ == "__main__":
    main()
