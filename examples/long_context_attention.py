"""Long-context attention: sequence parallelism over a device mesh.

The framework ships BOTH first-class strategies (the capability the
reference's truncated-BPTT never had):
  * ring attention  — K/V shards rotate via ppermute, online-softmax
                      accumulation; any head count, N hops
  * Ulysses         — two all-to-alls re-shard sequence → heads → sequence;
                      one pass of dense attention per device

Run: python examples/long_context_attention.py
(8 virtual CPU devices so it runs anywhere; the same code spans real
chips over ICI)"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.parallel import ring_attention, ulysses_attention  # noqa: E402


def main():
    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("seq",))
    b, h, t, d = 1, 8, 64 * n, 32  # sequence N× one device's share
    r = np.random.RandomState(0)
    q = r.randn(b, h, t, d).astype(np.float32)
    k = r.randn(b, h, t, d).astype(np.float32)
    v = r.randn(b, h, t, d).astype(np.float32)

    spec4 = NamedSharding(mesh, P(None, None, "seq", None))
    uly = np.asarray(ulysses_attention(
        jax.device_put(jnp.asarray(q), spec4),
        jax.device_put(jnp.asarray(k), spec4),
        jax.device_put(jnp.asarray(v), spec4), mesh=mesh, causal=True))

    spec3 = NamedSharding(mesh, P(None, "seq", None))
    ring = np.asarray(ring_attention(
        jax.device_put(jnp.asarray(q.reshape(b * h, t, d)), spec3),
        jax.device_put(jnp.asarray(k.reshape(b * h, t, d)), spec3),
        jax.device_put(jnp.asarray(v.reshape(b * h, t, d)), spec3),
        mesh=mesh, causal=True)).reshape(b, h, t, d)

    diff = float(np.abs(uly - ring).max())
    print(f"sequence length {t} sharded over {n} devices")
    if diff >= 1e-3:
        raise SystemExit(
            f"ulysses vs ring max|Δ| = {diff:.2e} — strategies DISAGREE")
    print(f"ulysses vs ring max|Δ| = {diff:.2e}  (strategies agree)")


if __name__ == "__main__":
    main()
