"""SameDiff: define-then-run graph with autodiff training — the
SameDiff MNIST-MLP example role (quickstart for the sd API)."""

import numpy as np

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.autodiff.samediff import SameDiff, TrainingConfig
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator


def main():
    r = np.random.RandomState(0)
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 8))
    labels = sd.placeholder("labels", shape=(None, 3))
    w0 = sd.var("w0", r.randn(8, 16).astype(np.float32) * 0.2)
    b0 = sd.var("b0", np.zeros(16, np.float32))
    w1 = sd.var("w1", r.randn(16, 3).astype(np.float32) * 0.2)
    h = sd.nn.relu(x @ w0 + b0)
    logits = h @ w1
    loss = sd.loss.softmax_cross_entropy(logits, labels)

    sd.set_training_config(TrainingConfig(
        updater=nn.Adam(learning_rate=1e-2),
        data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"],
        loss_variables=[loss.name]))

    xs = r.randn(256, 8).astype(np.float32)
    ys = np.eye(3)[(xs[:, 0] > 0).astype(int)
                   + (xs[:, 1] > 0)].astype(np.float32)
    hist = sd.fit(ListDataSetIterator(DataSet(xs, ys), batch_size=64),
                  epochs=20)
    print("loss first -> last:", round(hist[0], 4), "->", round(hist[-1], 4))

    out = sd.output({"x": xs[:4]}, logits.name)[logits.name]
    print("logits shape:", out.shape)


if __name__ == "__main__":
    main()
