"""Model import: Keras h5, TF frozen graph, ONNX — and running a foreign
graph directly with GraphRunner (modelimport examples role).

Run: python examples/model_import.py  (builds tiny source models in-env
with tf.keras; no downloads)"""

import numpy as np


def main():
    import tensorflow as tf

    from deeplearning4j_tpu.imports import GraphRunner, import_keras_model

    # --- Keras Sequential → MultiLayerNetwork -----------------------------
    model = tf.keras.Sequential([
        tf.keras.layers.Input((8,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.Dense(4, activation="softmax"),
    ])
    net = import_keras_model(model)
    x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    ours, theirs = net.output(x), model(x, training=False).numpy()
    print("keras import max|Δ|:", float(np.abs(ours - theirs).max()))

    # --- frozen TF GraphDef → GraphRunner ---------------------------------
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)

    cf = tf.function(lambda t: model(t)).get_concrete_function(
        tf.TensorSpec([None, 8], tf.float32))
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    runner = GraphRunner(gd.SerializeToString())  # format sniffed
    feed_name = frozen.inputs[0].name.split(":")[0]
    res = runner.run({feed_name: x})
    print("GraphRunner outputs:", {k: v.shape for k, v in res.items()})


if __name__ == "__main__":
    main()
