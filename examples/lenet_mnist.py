"""LeNet-5 on MNIST — the canonical first example (dl4j-examples
MnistClassifier role): build a conf, fit, evaluate.

Run: python examples/lenet_mnist.py  (uses the local MNIST files when
present, else a deterministic synthetic fallback — no downloads)."""

import itertools
import os

import numpy as np

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.eval import Evaluation


def main():
    batch = 128
    train_iter = MnistDataSetIterator(batch, train=True)
    test_iter = MnistDataSetIterator(batch, train=False)
    # EXAMPLE_MAX_BATCHES caps the run for smoke tests/CI; unset = full epoch
    cap = int(os.environ.get("EXAMPLE_MAX_BATCHES", "0"))
    if cap:
        train_iter = list(itertools.islice(iter(train_iter), cap))
        test_iter = list(itertools.islice(iter(test_iter), cap))

    conf = (nn.builder()
            .seed(123)
            .updater(nn.Adam(learning_rate=1e-3))
            # "mixed": bf16 compute / f32 master params — the TPU-native
            # policy; it also keeps CPU smoke runs fast (f32 policy forces
            # multi-pass matmul emulation whose conv compiles take minutes)
            .dtype("mixed")
            .list()
            .layer(nn.ConvolutionLayer(n_out=20, kernel=(5, 5),
                                       activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.ConvolutionLayer(n_out=50, kernel=(5, 5),
                                       activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.DenseLayer(n_out=500, activation="relu"))
            .layer(nn.OutputLayer(n_out=10, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.convolutional_flat(28, 28, 1))
            .build())
    net = nn.MultiLayerNetwork(conf).init()
    net.set_listeners(nn.ScoreIterationListener(50))

    net.fit(train_iter, epochs=1)

    ev = Evaluation()
    for ds in test_iter:
        ev.eval(ds.labels, net.output(ds.features))
    print(ev.stats())


if __name__ == "__main__":
    main()
