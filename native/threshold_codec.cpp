// Threshold gradient codec — native core of the DCN gradient compressor.
//
// Reference parity: libnd4j's threshold encoding ops
// (ops/declarable/generic/compression/threshold.cpp and the bitmap variant),
// used by EncodedGradientsAccumulator for Strom-2015-style sparse gradient
// exchange. On-pod ICI all-reduce needs no compression (SURVEY §6.8); this
// codec is the optional DCN-crossing compressor, and doing it in native code
// keeps the host-side encode off the Python critical path.
//
// Format (matches deeplearning4j_tpu/ops/compression.py):
//   encode: indices[int32] of |g| > threshold (capacity-bounded), values
//           replaced by ±threshold sign; residual = g - decoded.
//   bitmap: 2-bit stream: 00 skip, 01 +threshold, 10 -threshold.
//
// Build: cmake -S native -B native/build && cmake --build native/build
// Exposed C ABI (ctypes-consumed, see deeplearning4j_tpu/native_ops/).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Encode: writes up to `capacity` indices of |g|>threshold into out_idx,
// subtracts ±threshold from residual (callers pass residual=copy of g).
// Returns the number of encoded entries.
int64_t threshold_encode(const float* grad, int64_t n, float threshold,
                         int32_t* out_idx, int64_t capacity, float* residual) {
  // single serial pass: first-N capacity semantics match the reference's
  // encoder; everything not encoded (incl. past-capacity entries) stays in
  // the residual unchanged.
  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    float g = grad[i];
    if (count < capacity && g > threshold) {
      out_idx[count++] = static_cast<int32_t>(i + 1);  // +1: sign carries direction
      residual[i] = g - threshold;
    } else if (count < capacity && g < -threshold) {
      out_idx[count++] = static_cast<int32_t>(-(i + 1));
      residual[i] = g + threshold;
    } else {
      residual[i] = g;
    }
  }
  return count;
}

// Decode: adds ±threshold at the encoded indices into `out` (size n).
void threshold_decode(const int32_t* idx, int64_t count, float threshold,
                      float* out, int64_t n) {
#if defined(_OPENMP)
#pragma omp parallel for
#endif
  for (int64_t k = 0; k < count; ++k) {
    int32_t v = idx[k];
    int64_t i = (v > 0 ? v : -v) - 1;
    if (i >= 0 && i < n) {
      out[i] += (v > 0 ? threshold : -threshold);
    }
  }
}

// Bitmap encode: 2 bits per element packed into uint8 (4 elements/byte).
// Returns number of non-zero entries encoded.
int64_t bitmap_encode(const float* grad, int64_t n, float threshold,
                      uint8_t* out_bits, float* residual) {
  std::atomic<int64_t> nz{0};
#if defined(_OPENMP)
#pragma omp parallel for
#endif
  for (int64_t b = 0; b < (n + 3) / 4; ++b) {
    uint8_t byte = 0;
    for (int64_t j = 0; j < 4; ++j) {
      int64_t i = b * 4 + j;
      if (i >= n) break;
      float g = grad[i];
      uint8_t code = 0;
      if (g > threshold) {
        code = 1;
        residual[i] = g - threshold;
        nz.fetch_add(1, std::memory_order_relaxed);
      } else if (g < -threshold) {
        code = 2;
        residual[i] = g + threshold;
        nz.fetch_add(1, std::memory_order_relaxed);
      } else {
        residual[i] = g;
      }
      byte |= (code << (2 * j));
    }
    out_bits[b] = byte;
  }
  return nz.load();
}

void bitmap_decode(const uint8_t* bits, int64_t n, float threshold, float* out) {
#if defined(_OPENMP)
#pragma omp parallel for
#endif
  for (int64_t b = 0; b < (n + 3) / 4; ++b) {
    uint8_t byte = bits[b];
    for (int64_t j = 0; j < 4; ++j) {
      int64_t i = b * 4 + j;
      if (i >= n) break;
      uint8_t code = (byte >> (2 * j)) & 0x3;
      if (code == 1) out[i] += threshold;
      else if (code == 2) out[i] -= threshold;
    }
  }
}

// Version/capability probe for the binding layer.
int32_t codec_abi_version() { return 1; }

}  // extern "C"
