// Native record loader — the datavec native-loader role.
//
// Reference parity: the reference's record readers bottom out in native
// code (JavaCPP-wrapped loaders; libnd4j NativeOps I/O helpers) so Java
// never parses bytes on the training path. Here the hot loaders are:
//
//   * csv_parse_floats: one-pass CSV → float32 matrix (delimiter
//     configurable, quoted fields skipped as NaN), replacing Python
//     csv.reader + float() per cell for numeric tables.
//   * idx_parse: IDX (MNIST/EMNIST container) → float32 [0,1] array.
//
// Consumed via ctypes (deeplearning4j_tpu/native_ops/record_loader.py);
// the Python CSVRecordReader keeps its general typed path and delegates
// all-numeric schemas here.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>

extern "C" {

// Parse CSV text into out[rows*cols] (caller-allocated, row-major).
// Returns the number of rows parsed, or -1 if a row has != cols fields.
// Empty/unparseable fields become NaN (quality analysis counts them).
long long csv_parse_floats(const char* text, long long len, char delim,
                           long long skip_rows, long long cols,
                           long long max_rows, float* out) {
    const char* p = text;
    const char* end = text + len;
    long long row = 0;
    // skip header rows
    for (long long s = 0; s < skip_rows && p < end; ++s) {
        while (p < end && *p != '\n') ++p;
        if (p < end) ++p;
    }
    while (p < end && row < max_rows) {
        // skip blank lines (including whitespace-only ones)
        if (*p == '\n' || *p == '\r') { ++p; continue; }
        {
            const char* scan = p;
            while (scan < end && (*scan == ' ' || *scan == '\t')) ++scan;
            if (scan == end) break;
            if (*scan == '\n' || *scan == '\r') { p = scan + 1; continue; }
        }
        long long col = 0;
        while (p <= end) {
            const char* field = p;
            while (p < end && *p != delim && *p != '\n' && *p != '\r') ++p;
            if (col >= cols) return -1;
            char* parse_end = nullptr;
            double v = strtod(field, &parse_end);
            bool ok = parse_end > field;
            // match the Python fallback's accepted syntax: plain
            // decimal/scientific only (strtod would accept 0x hex)
            for (const char* h = field; ok && h < parse_end; ++h)
                if (*h == 'x' || *h == 'X') ok = false;
            // strtod must have consumed up to the delimiter (trailing
            // spaces allowed); otherwise the field is non-numeric
            if (ok) {
                const char* q = parse_end;
                while (q < p && (*q == ' ' || *q == '\t')) ++q;
                ok = (q == p);
            }
            out[row * cols + col] = ok ? (float)v : NAN;
            ++col;
            if (p >= end || *p == '\n' || *p == '\r') break;
            ++p;  // skip delimiter
        }
        if (col != cols) return -1;
        ++row;
        while (p < end && (*p == '\r')) ++p;
        if (p < end && *p == '\n') ++p;
    }
    return row;
}

// Parse an IDX buffer (big-endian header: magic, dims...) of unsigned
// bytes into out (scaled to [0,1] when scale != 0). Returns element count
// or -1 on malformed input. shape_out receives up to 4 dims; ndim_out the
// dimension count.
long long idx_parse(const unsigned char* buf, long long len, int scale,
                    float* out, long long out_capacity,
                    long long* shape_out, int* ndim_out) {
    if (len < 4) return -1;
    if (buf[0] != 0 || buf[1] != 0) return -1;
    int dtype = buf[2];
    int ndim = buf[3];
    if (dtype != 0x08 || ndim < 1 || ndim > 4) return -1;  // ubyte only
    if (len < 4 + 4 * ndim) return -1;
    long long total = 1;
    for (int d = 0; d < ndim; ++d) {
        const unsigned char* q = buf + 4 + 4 * d;
        long long dim = ((long long)q[0] << 24) | ((long long)q[1] << 16) |
                        ((long long)q[2] << 8) | (long long)q[3];
        shape_out[d] = dim;
        total *= dim;
    }
    *ndim_out = ndim;
    if (total > out_capacity || len < 4 + 4 * ndim + total) return -1;
    const unsigned char* data = buf + 4 + 4 * ndim;
    const float k = scale ? (1.0f / 255.0f) : 1.0f;
    for (long long i = 0; i < total; ++i) out[i] = data[i] * k;
    return total;
}

}  // extern "C"
