// Native tests for the threshold codec (the reference's native-test role,
// SURVEY §5.3 — layers_tests/*.cpp pattern, assert-based).
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <vector>

extern "C" {
int64_t threshold_encode(const float*, int64_t, float, int32_t*, int64_t, float*);
void threshold_decode(const int32_t*, int64_t, float, float*, int64_t);
int64_t bitmap_encode(const float*, int64_t, float, uint8_t*, float*);
void bitmap_decode(const uint8_t*, int64_t, float, float*);
int32_t codec_abi_version();
void u8_normalize(const uint8_t*, long long, float, float, float*);
void u8_standardize(const uint8_t*, long long, long long, const float*,
                    const float*, float*);
uint32_t murmur3_32(const uint8_t*, long long, uint32_t);
}

static bool feq(float a, float b) { return std::fabs(a - b) < 1e-6f; }

int main() {
  assert(codec_abi_version() == 1);

  // encode/decode round trip: decoded + residual == original
  std::vector<float> g = {0.5f, -0.2f, 1.5f, -2.0f, 0.0f, 0.9f};
  std::vector<int32_t> idx(16);
  std::vector<float> residual(g.size());
  int64_t n = threshold_encode(g.data(), g.size(), 1.0f, idx.data(), 16,
                               residual.data());
  assert(n == 2);  // 1.5 and -2.0
  assert(idx[0] == 3 && idx[1] == -4);
  std::vector<float> decoded(g.size(), 0.0f);
  threshold_decode(idx.data(), n, 1.0f, decoded.data(), g.size());
  for (size_t i = 0; i < g.size(); ++i) {
    assert(feq(decoded[i] + residual[i], g[i]));
  }

  // capacity bound: first-N kept, rest left in residual
  std::vector<float> big(100, 2.0f);
  std::vector<int32_t> idx2(10);
  std::vector<float> res2(big.size());
  int64_t n2 = threshold_encode(big.data(), big.size(), 1.0f, idx2.data(), 10,
                                res2.data());
  assert(n2 == 10);
  assert(feq(res2[0], 1.0f));   // encoded: residual reduced
  assert(feq(res2[50], 2.0f));  // past capacity: untouched

  // bitmap round trip
  std::vector<uint8_t> bits((g.size() + 3) / 4, 0);
  std::vector<float> res3(g.size());
  int64_t nz = bitmap_encode(g.data(), g.size(), 1.0f, bits.data(), res3.data());
  assert(nz == 2);
  std::vector<float> dec3(g.size(), 0.0f);
  bitmap_decode(bits.data(), g.size(), 1.0f, dec3.data());
  for (size_t i = 0; i < g.size(); ++i) {
    assert(feq(dec3[i] + res3[i], g[i]));
  }

  // u8_normalize: [0,255] -> [0,1] scaler semantics
  std::vector<uint8_t> px = {0, 128, 255};
  std::vector<float> out(px.size());
  u8_normalize(px.data(), px.size(), 1.0f / 255.0f, 0.0f, out.data());
  assert(feq(out[0], 0.0f) && feq(out[2], 1.0f));
  assert(std::fabs(out[1] - 128.0f / 255.0f) < 1e-6f);

  // u8_standardize: channel-last z-score
  std::vector<uint8_t> img = {10, 20, 30, 40};  // 2 px, c=2
  float mean[2] = {20.0f, 30.0f};
  float inv_std[2] = {0.5f, 0.25f};
  std::vector<float> st(4);
  u8_standardize(img.data(), 4, 2, mean, inv_std, st.data());
  assert(feq(st[0], -5.0f) && feq(st[1], -2.5f));
  assert(feq(st[2], 5.0f) && feq(st[3], 2.5f));

  // murmur3 x86-32 known vectors
  assert(murmur3_32((const uint8_t*)"", 0, 0) == 0u);
  assert(murmur3_32((const uint8_t*)"abc", 3, 0) == 0xB3DD93FAu);
  assert(murmur3_32((const uint8_t*)"hello", 5, 0) == 0x248BFA47u);
  assert(murmur3_32((const uint8_t*)"", 0, 1) == 0x514E28B7u);

  std::printf("codec_test OK\n");
  return 0;
}
