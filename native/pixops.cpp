// Native pixel/normalization kernels + string hashing.
//
// Reference parity:
//   * ImagePreProcessingScaler / NormalizerStandardize bottom out in native
//     elementwise loops in the reference (libnd4j legacy transform kernels);
//     on the TPU build the DEVICE side is XLA, but the HOST-side input
//     pipeline (uint8 images → normalized f32 batches, before device_put)
//     is exactly the loop below — keeping byte-wrangling off Python.
//   * murmur3_32: nd4j-common HashUtil role (stable string/bytes hashing
//     for vocab bucketing and shard assignment).

#include <cstdint>
#include <cstring>

extern "C" {

// out[i] = in[i] * scale + shift  (ImagePreProcessingScaler hot path)
void u8_normalize(const uint8_t* in, long long n, float scale, float shift,
                  float* out) {
#pragma omp parallel for schedule(static)
    for (long long i = 0; i < n; ++i) {
        out[i] = (float)in[i] * scale + shift;
    }
}

// Channel-last standardize: out[i] = (in[i] - mean[i % c]) * inv_std[i % c]
// (NormalizerStandardize on NHWC uint8 images; c = trailing channel count)
void u8_standardize(const uint8_t* in, long long n, long long c,
                    const float* mean, const float* inv_std, float* out) {
#pragma omp parallel for schedule(static)
    for (long long i = 0; i < n; ++i) {
        long long ch = i % c;
        out[i] = ((float)in[i] - mean[ch]) * inv_std[ch];
    }
}

// MurmurHash3 x86 32-bit (public domain reference algorithm, Austin Appleby)
uint32_t murmur3_32(const uint8_t* data, long long len, uint32_t seed) {
    const uint32_t c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
    uint32_t h = seed;
    const long long nblocks = len / 4;
    for (long long i = 0; i < nblocks; ++i) {
        uint32_t k;
        std::memcpy(&k, data + i * 4, 4);
        k *= c1;
        k = (k << 15) | (k >> 17);
        k *= c2;
        h ^= k;
        h = (h << 13) | (h >> 19);
        h = h * 5 + 0xe6546b64u;
    }
    uint32_t k = 0;
    const uint8_t* tail = data + nblocks * 4;
    switch (len & 3) {
        case 3: k ^= (uint32_t)tail[2] << 16; [[fallthrough]];
        case 2: k ^= (uint32_t)tail[1] << 8; [[fallthrough]];
        case 1:
            k ^= tail[0];
            k *= c1;
            k = (k << 15) | (k >> 17);
            k *= c2;
            h ^= k;
    }
    h ^= (uint32_t)len;
    h ^= h >> 16;
    h *= 0x85ebca6bu;
    h ^= h >> 13;
    h *= 0xc2b2ae35u;
    h ^= h >> 16;
    return h;
}

}  // extern "C"
