"""SameDiff — the define-then-run autodiff graph engine, TPU-native.

Reference parity:
  * org/nd4j/autodiff/samediff/SameDiff.java (~12k lines — graph build,
    variables, createGradFunction, fit, FlatBuffers serde) and SDVariable.java.
  * org/nd4j/autodiff/samediff/internal/{AbstractSession, InferenceSession,
    TrainingSession}.java — the dependency-tracked op-by-op interpreter.
  * op factories: ops/SDMath.java, SDNN.java, SDCNN.java, SDRNN.java,
    SDLoss.java, SDImage.java (code-generated in the reference).

TPU-native realization (SURVEY §4.3 mapping): the user still builds a graph
of named variables and recorded ops (API parity), but execution TRACES the
whole graph into one function that jit-compiles to a single XLA computation —
the reference's per-node interpreter (one JNI crossing per op per step)
disappears. Autodiff is jax.grad over that traced function, replacing ~500
hand-written ``doDiff`` rules. ``createGradFunction`` exists for API parity
and simply marks gradients as requested outputs.

Serde: JSON graph-def + npz arrays (the FlatBuffers-file analog), plus
StableHLO text export of the compiled computation (`as_stablehlo`).
"""

from __future__ import annotations

import json
import time
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import registry as op_registry
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops import losses as loss_lib


class SDVariable:
    """SDVariable.java analog: a named symbolic tensor in one SameDiff graph.

    variable_type: PLACEHOLDER | VARIABLE (trainable) | CONSTANT | ARRAY
    (op output) — mirrors org.nd4j.autodiff.samediff.VariableType.
    """

    def __init__(self, sd: "SameDiff", name: str, vtype: str,
                 shape: Optional[Tuple[int, ...]] = None, dtype=jnp.float32):
        self.sd = sd
        self.name = name
        self.vtype = vtype
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    # ---- python operator sugar (SDVariable.add/mul/... in the reference) --
    def _bin(self, op: str, other) -> "SDVariable":
        other = self.sd._lift(other)
        return self.sd._record(op, [self, other])

    def __add__(self, o):
        return self._bin("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin("sub", o)

    def __rsub__(self, o):
        return self.sd._lift(o)._bin("sub", self)

    def __mul__(self, o):
        return self._bin("mul", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin("div", o)

    def __rtruediv__(self, o):
        return self.sd._lift(o)._bin("div", self)

    def __pow__(self, o):
        return self._bin("pow", o)

    def __neg__(self):
        return self.sd._record("neg", [self])

    def __matmul__(self, o):
        return self._bin("mmul", o)

    # ---- common methods ---------------------------------------------------
    def add(self, o):
        return self.__add__(o)

    def sub(self, o):
        return self.__sub__(o)

    def mul(self, o):
        return self.__mul__(o)

    def div(self, o):
        return self.__truediv__(o)

    def mmul(self, o):
        return self.__matmul__(o)

    def reshape(self, *shape):
        return self.sd._record("reshape", [self], {"shape": tuple(int(s) for s in shape)})

    def transpose(self, *axes):
        return self.sd._record("transpose", [self], {"axes": axes or None})

    def sum(self, *axes, keepdims=False):
        return self.sd._record("reduce_sum", [self], {"axes": axes or None, "keepdims": keepdims})

    def mean(self, *axes, keepdims=False):
        return self.sd._record("reduce_mean", [self], {"axes": axes or None, "keepdims": keepdims})

    def max(self, *axes, keepdims=False):
        return self.sd._record("reduce_max", [self], {"axes": axes or None, "keepdims": keepdims})

    def min(self, *axes, keepdims=False):
        return self.sd._record("reduce_min", [self], {"axes": axes or None, "keepdims": keepdims})

    def std(self, *axes, keepdims=False):
        return self.sd._record("reduce_std", [self], {"axes": axes or None, "keepdims": keepdims})

    def argmax(self, axis=-1):
        return self.sd._record("argmax", [self], {"axis": axis})

    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self.name, new_name)
        return self

    def eval(self, feeds: Optional[Dict[str, Any]] = None):
        """Evaluate just this variable (SDVariable.eval)."""
        return self.sd.output(feeds or {}, [self.name])[self.name]

    def __repr__(self):
        return f"SDVariable(name={self.name!r}, type={self.vtype}, shape={self.shape})"


class _Node:
    """One recorded op application (the reference's SameDiffOp entry)."""

    __slots__ = ("op", "inputs", "kwargs", "outputs")

    def __init__(self, op: str, inputs: List[str], kwargs: Dict[str, Any], outputs: List[str]):
        self.op = op
        self.inputs = inputs
        self.kwargs = kwargs
        self.outputs = outputs


# ---------------------------------------------------------------------------
# Op implementations available to graphs. Each entry: name -> callable taking
# (*input_arrays, **kwargs). Drawn from jnp/lax plus the declarable-op
# registry (conv2d etc.), mirroring the reference op catalog naming.
# ---------------------------------------------------------------------------


def _reduce(fn):
    def wrap(x, *, axes=None, keepdims=False):
        ax = None if not axes else tuple(a for a in axes)
        return fn(x, axis=ax, keepdims=keepdims)

    return wrap


GRAPH_OPS: Dict[str, Callable[..., Any]] = {
    # elementwise binary
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "pow": lambda a, b: a**b,
    "floormod": lambda a, b: jnp.mod(a, b),
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "squared_difference": lambda a, b: (a - b) ** 2,
    # comparisons
    "gt": lambda a, b: (a > b).astype(jnp.float32),
    "lt": lambda a, b: (a < b).astype(jnp.float32),
    "gte": lambda a, b: (a >= b).astype(jnp.float32),
    "lte": lambda a, b: (a <= b).astype(jnp.float32),
    "eq": lambda a, b: (a == b).astype(jnp.float32),
    "neq": lambda a, b: (a != b).astype(jnp.float32),
    # elementwise unary
    "neg": lambda a: -a,
    "abs": jnp.abs,
    "exp": jnp.exp,
    "log": jnp.log,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda a: jax.lax.rsqrt(a),
    "square": jnp.square,
    "reciprocal": lambda a: 1.0 / a,
    "sign": jnp.sign,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "erf": jax.lax.erf,
    "clip_by_value_graph": lambda a, *, min_value, max_value: jnp.clip(a, min_value, max_value),
    "cast": lambda a, *, dtype: a.astype(jnp.dtype(dtype)),
    # activations
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leakyrelu": lambda a, *, alpha=0.01: jax.nn.leaky_relu(a, alpha),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "swish": jax.nn.swish,
    "mish": jax.nn.mish,
    "hardsigmoid": jax.nn.hard_sigmoid,
    "hardtanh": jax.nn.hard_tanh,
    "softmax": lambda a, *, axis=-1: jax.nn.softmax(a, axis=axis),
    "log_softmax": lambda a, *, axis=-1: jax.nn.log_softmax(a, axis=axis),
    # linalg / shape
    "mmul": lambda a, b, *, transpose_a=False, transpose_b=False: jnp.matmul(
        jnp.swapaxes(a, -1, -2) if transpose_a else a,
        jnp.swapaxes(b, -1, -2) if transpose_b else b),
    "tensordot": lambda a, b, *, axes: jnp.tensordot(a, b, axes=axes),
    "reshape": lambda a, *, shape: jnp.reshape(a, shape),
    "transpose": lambda a, *, axes=None: jnp.transpose(a, axes),
    "permute": lambda a, *, axes: jnp.transpose(a, axes),
    "expand_dims": lambda a, *, axis: jnp.expand_dims(a, axis),
    "squeeze": lambda a, *, axis=None: jnp.squeeze(a, axis),
    "concat": lambda *xs, axis=0: jnp.concatenate(xs, axis=axis),
    # "stack" intentionally NOT here: the registry impl preserves numpy
    # for un-traced shape chains (tf.shape→Pack→Reshape imports)
    "unstack_first": lambda x: x[0],
    "slice": lambda a, *, begin, size: jax.lax.dynamic_slice(a, begin, size),
    "strided_slice": lambda a, *, begin, end, strides=None: a[
        tuple(slice(b, e, s) for b, e, s in zip(begin, end, strides or [1] * len(begin)))],
    "gather": lambda params, indices, *, axis=0: jnp.take(params, indices.astype(jnp.int32), axis=axis),
    "tile": lambda a, *, reps: jnp.tile(a, reps),
    "pad": lambda a, *, paddings, value=0.0: jnp.pad(a, paddings, constant_values=value),
    # "shape_of" intentionally NOT here: the registry impl returns numpy
    # (shapes are static; keeps shape arithmetic trace-time concrete)
    "size": lambda a: jnp.asarray(a.size, jnp.int32),
    "one_hot_graph": lambda a, *, depth: jax.nn.one_hot(a.astype(jnp.int32), depth),
    "where": jnp.where,
    "select": jnp.where,
    # reductions
    "reduce_sum": _reduce(jnp.sum),
    "reduce_mean": _reduce(jnp.mean),
    "reduce_max": _reduce(jnp.max),
    "reduce_min": _reduce(jnp.min),
    "reduce_prod": _reduce(jnp.prod),
    "reduce_std": _reduce(jnp.std),
    "reduce_var": _reduce(jnp.var),
    "argmax": lambda a, *, axis=-1: jnp.argmax(a, axis=axis),
    "argmin": lambda a, *, axis=-1: jnp.argmin(a, axis=axis),
    "cumsum": lambda a, *, axis=0, exclusive=False, reverse=False:
        _cumsum_flags(a, axis, exclusive, reverse),
    "zeros_like": jnp.zeros_like,
    "ones_like": jnp.ones_like,
    "norm2": lambda a, *, axes=None: jnp.sqrt(jnp.sum(a**2, axis=None if not axes else tuple(axes))),
    # nn composites
    "linear": lambda x, w, b=None: (x @ w + b) if b is not None else x @ w,
    "layer_norm_graph": lambda x, gain, bias=None, *, axis=-1, eps=1e-5: _layer_norm(x, gain, bias, axis, eps),
    "batch_norm_graph": lambda x, mean, var, gamma, beta, *, eps=1e-5: (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta,
    "dropout_graph": lambda x, *, rate, seed=0: x,  # inference identity; training uses rng plumbing
    # losses (feed probabilities/logits per name, as the reference does)
    "softmax_cross_entropy": lambda logits, labels: loss_lib.softmax_cross_entropy_with_logits(logits, labels),
    "sparse_softmax_cross_entropy": lambda logits, ids: loss_lib.sparse_mcxent(logits, ids),
    "sigmoid_cross_entropy": lambda logits, labels: loss_lib.sigmoid_cross_entropy_with_logits(logits, labels),
    "mean_squared_error": lambda pred, labels: loss_lib.mse(pred, labels),
    "absolute_difference": lambda pred, labels: loss_lib.mae(pred, labels),
    "log_loss": lambda probs, labels: loss_lib.binary_xent(probs, labels),
    "huber_loss": lambda pred, labels, *, delta=1.0: _huber(pred, labels, delta),
    "cosine_distance": lambda a, b: loss_lib.cosine_proximity(a, b),
}


def _cumsum_flags(a, axis, exclusive, reverse):
    if reverse:
        a = jnp.flip(a, axis=axis)
    out = jnp.cumsum(a, axis=axis)
    if exclusive:
        out = out - a
    if reverse:
        out = jnp.flip(out, axis=axis)
    return out


def _layer_norm(x, gain, bias, axis, eps):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps) * gain
    return out + bias if bias is not None else out


def _huber(pred, labels, delta):
    err = jnp.abs(pred - labels)
    quad = jnp.minimum(err, delta)
    return jnp.mean(0.5 * quad**2 + delta * (err - quad))


# ---------------------------------------------------------------------------
# Registry-shadowing whitelist (round-5 verdict item 4).
#
# Resolution order is local -> GRAPH_OPS -> registry, so any GRAPH_OPS key
# that also names a declarable op SILENTLY wins over the registry impl. That
# bit the build twice: `where` (an older registry signature lost to
# jnp.where) and `shape_of`/`stack` (whose registry impls deliberately stay
# in NUMPY for un-traced shape chains — a GRAPH_OPS duplicate would have
# devicified them). Every intentional shadow must be listed here WITH its
# justification; graftlint rule GL006 fails the suite on any unlisted
# shadow AND on any stale whitelist entry, so this set is exact, not
# advisory. `shape_of`, `stack`, and `unstack` are intentionally ABSENT
# from GRAPH_OPS so their numpy-preserving registry impls win (regression-
# tested in tests/test_graph_ops_shadowing.py).
# ---------------------------------------------------------------------------

REGISTRY_SHADOW_WHITELIST = frozenset(
    # Elementwise unary/binary + activations: the GRAPH_OPS lambda is
    # mathematically identical to the registry impl; kept inline so graph
    # execution never pays a registry lookup + platform-helper resolve on
    # the trace hot path.
    ["add", "abs", "acos", "asin", "atan", "ceil",
     "cos", "cosh", "erf", "exp", "floor", "floormod", "log", "log1p",
     "maximum", "minimum", "neg", "pow", "reciprocal", "round", "rsqrt",
     "sign", "sin", "sinh", "sqrt", "square", "tan", "tanh",
     "elu", "gelu", "mish", "relu6", "selu", "sigmoid", "softplus",
     "softsign", "swish"]
    # Reductions: GRAPH_OPS carries the serde kwarg convention
    # (axes=list, keepdims) that imported graphs record; the registry
    # flavor takes axis tuples.
    + ["reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
       "reduce_prod", "argmax", "argmin", "cumsum"]
    # Shape/indexing: GRAPH_OPS uses the importer calling convention
    # (kwargs like begin/size/paddings); registry twins are the
    # positional DynamicCustomOp surface.
    + ["concat", "expand_dims", "gather", "pad", "permute", "reshape",
       "size", "slice", "squeeze", "strided_slice", "tile", "transpose",
       "zeros_like", "ones_like"]
    # `where`/`select`: jnp.where 3-arg broadcast semantics are the
    # documented winner over the registry's legacy signature (the round-3
    # collision this whitelist exists for).
    + ["where", "select"]
    # `identity`: registered into GRAPH_OPS by the ONNX importer for
    # no-op nodes; the registry `identity` is equivalent.
    + ["identity"]
)


def resolve_graph_op(name: str, local_ops: Optional[Dict[str, Callable]] = None
                     ) -> Callable[..., Any]:
    """Resolve an op name: instance-local control-flow impls first (so two
    SameDiff instances with the same counter names never collide), then the
    global catalog, then the declarable-op registry. A GRAPH_OPS key that
    duplicates a registry op must be on REGISTRY_SHADOW_WHITELIST (enforced
    by graftlint GL006).

    Registry ops WITH platform helpers resolve to the descriptor itself, so
    graph execution dispatches through ``OpDescriptor.resolve`` per call —
    this is how a fused ``dot_product_attention`` node lands on the Pallas
    flash kernel on TPU (the whole point of the optimizer's fusion tier,
    docs/OPTIMIZER.md). Helper-less ops return the raw impl: no resolve
    cost on the trace hot path, and host-static numpy impls
    (``shape_of``/``stack``) stay exactly the functions the shape-chain
    contract documents."""
    if local_ops and name in local_ops:
        return local_ops[name]
    if name in GRAPH_OPS:
        return GRAPH_OPS[name]
    reg = op_registry()
    if name in reg:
        desc = reg.get(name)
        return desc if desc.platform_impls else desc.fn
    raise KeyError(f"unknown graph op '{name}'")


# ---------------------------------------------------------------------------
# Namespaced op factories (SDMath/SDNN/SDCNN/SDRNN/SDLoss analogs)
# ---------------------------------------------------------------------------


class _Namespace:
    def __init__(self, sd: "SameDiff"):
        self._sd = sd


class SDMath(_Namespace):
    def _u(self, op, x, **kw):
        return self._sd._record(op, [self._sd._lift(x)], kw)

    def abs(self, x):
        return self._u("abs", x)

    def exp(self, x):
        return self._u("exp", x)

    def log(self, x):
        return self._u("log", x)

    def sqrt(self, x):
        return self._u("sqrt", x)

    def square(self, x):
        return self._u("square", x)

    def sin(self, x):
        return self._u("sin", x)

    def cos(self, x):
        return self._u("cos", x)

    def tanh(self, x):
        return self._u("tanh", x)

    def erf(self, x):
        return self._u("erf", x)

    def sign(self, x):
        return self._u("sign", x)

    def floor(self, x):
        return self._u("floor", x)

    def neg(self, x):
        return self._u("neg", x)

    def max(self, a, b):
        return self._sd._record("maximum", [self._sd._lift(a), self._sd._lift(b)])

    def min(self, a, b):
        return self._sd._record("minimum", [self._sd._lift(a), self._sd._lift(b)])

    def clip_by_value(self, x, lo, hi):
        return self._sd._record("clip_by_value_graph", [self._sd._lift(x)],
                                {"min_value": lo, "max_value": hi})

    def cast(self, x, dtype):
        return self._sd._record("cast", [self._sd._lift(x)], {"dtype": str(np.dtype(dtype))})


class SDNN(_Namespace):
    def relu(self, x):
        return self._sd._record("relu", [x])

    def relu6(self, x):
        return self._sd._record("relu6", [x])

    def gelu(self, x):
        return self._sd._record("gelu", [x])

    def elu(self, x):
        return self._sd._record("elu", [x])

    def selu(self, x):
        return self._sd._record("selu", [x])

    def swish(self, x):
        return self._sd._record("swish", [x])

    def sigmoid(self, x):
        return self._sd._record("sigmoid", [x])

    def softplus(self, x):
        return self._sd._record("softplus", [x])

    def leaky_relu(self, x, alpha=0.01):
        return self._sd._record("leakyrelu", [x], {"alpha": alpha})

    def softmax(self, x, axis=-1):
        return self._sd._record("softmax", [x], {"axis": axis})

    def log_softmax(self, x, axis=-1):
        return self._sd._record("log_softmax", [x], {"axis": axis})

    def linear(self, x, w, b=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._sd._record("linear", ins)

    def layer_norm(self, x, gain, bias=None, axis=-1, eps=1e-5):
        ins = [x, gain] + ([bias] if bias is not None else [])
        return self._sd._record("layer_norm_graph", ins, {"axis": axis, "eps": eps})

    def batch_norm(self, x, mean, var, gamma, beta, eps=1e-5):
        return self._sd._record("batch_norm_graph", [x, mean, var, gamma, beta], {"eps": eps})

    def dropout(self, x, rate):
        return self._sd._record("dropout_graph", [x], {"rate": rate})

    def multi_head_dot_product_attention(self, q, k, v, wq, wk, wv, wo, num_heads):
        return self._sd._record(
            "multi_head_dot_product_attention", [q, k, v, wq, wk, wv, wo],
            {"num_heads": num_heads})

    def dot_product_attention(self, q, k, v):
        return self._sd._record("dot_product_attention", [q, k, v])


class SDCNN(_Namespace):
    def conv2d(self, x, w, b=None, *, stride=1, padding="same", dilation=1):
        ins = [x, w] + ([b] if b is not None else [])
        return self._sd._record("conv2d", ins, {"stride": stride, "padding": padding,
                                                "dilation": dilation})

    def max_pooling2d(self, x, *, kernel, stride=None, padding="valid"):
        return self._sd._record("maxpool2d", [x], {"kernel": kernel, "stride": stride,
                                                   "padding": padding})

    def avg_pooling2d(self, x, *, kernel, stride=None, padding="valid"):
        return self._sd._record("avgpool2d", [x], {"kernel": kernel, "stride": stride,
                                                   "padding": padding})

    def upsampling2d(self, x, *, size=2):
        return self._sd._record("upsampling2d", [x], {"size": size})


class SDRNN(_Namespace):
    def lstm_cell(self, x, h, c, w_ih, w_hh, b):
        return self._sd._record("lstm_cell", [x, h, c, w_ih, w_hh, b], n_out=2)

    def gru_cell(self, x, h, w_ih, w_hh, b_ih, b_hh):
        return self._sd._record("gru_cell", [x, h, w_ih, w_hh, b_ih, b_hh])


class SDLoss(_Namespace):
    def softmax_cross_entropy(self, logits, labels):
        return self._sd._record("softmax_cross_entropy", [logits, labels])

    def sparse_softmax_cross_entropy(self, logits, ids):
        return self._sd._record("sparse_softmax_cross_entropy", [logits, ids])

    def sigmoid_cross_entropy(self, logits, labels):
        return self._sd._record("sigmoid_cross_entropy", [logits, labels])

    def mean_squared_error(self, pred, labels):
        return self._sd._record("mean_squared_error", [pred, labels])

    def absolute_difference(self, pred, labels):
        return self._sd._record("absolute_difference", [pred, labels])

    def log_loss(self, probs, labels):
        return self._sd._record("log_loss", [probs, labels])

    def huber_loss(self, pred, labels, delta=1.0):
        return self._sd._record("huber_loss", [pred, labels], {"delta": delta})


class SDImage(_Namespace):
    """sd.image() — SDImage.java op factory over the catalog's image family."""

    def resize_bilinear(self, x, height, width):
        return self._sd.op("resize_bilinear", x, size=(height, width))

    def resize_nearest_neighbor(self, x, height, width):
        return self._sd.op("resize_nearest_neighbor", x, size=(height, width))

    def resize_bicubic(self, x, height, width):
        return self._sd.op("resize_bicubic", x, size=(height, width))

    def crop_and_resize(self, image, boxes, box_indices, crop_size):
        return self._sd.op("crop_and_resize", image, boxes, box_indices,
                           crop_size=tuple(crop_size))

    def non_max_suppression(self, boxes, scores, max_out_size,
                            iou_threshold=0.5, score_threshold=float("-inf")):
        """Returns (indices, valid_mask) — the op is two-output."""
        return self._sd.op("non_max_suppression", boxes, scores,
                           max_output_size=max_out_size,
                           iou_threshold=iou_threshold,
                           score_threshold=score_threshold, n_out=2)

    def adjust_contrast(self, x, factor):
        return self._sd.op("adjust_contrast", x, factor=factor)

    def adjust_hue(self, x, delta):
        return self._sd.op("adjust_hue", x, delta=delta)

    def adjust_saturation(self, x, factor):
        return self._sd.op("adjust_saturation", x, factor=factor)

    def rgb_to_hsv(self, x):
        return self._sd.op("rgb_to_hsv", x)

    def hsv_to_rgb(self, x):
        return self._sd.op("hsv_to_rgb", x)


class SDLinalg(_Namespace):
    """sd.linalg() — SDLinalg.java op factory."""

    def cholesky(self, x):
        return self._sd.op("cholesky", x)

    def qr(self, x, full_matrices=False):
        return self._sd.op("qr", x, full_matrices=full_matrices, n_out=2)

    def svd(self, x, full_uv=False, compute_uv=True):
        return self._sd.op("svd", x, full_matrices=full_uv,
                           compute_uv=compute_uv, n_out=3 if compute_uv else 1)

    def solve(self, a, b):
        return self._sd.op("solve", a, b)

    def triangular_solve(self, a, b, lower=True, adjoint=False):
        return self._sd.op("triangular_solve", a, b, lower=lower,
                           adjoint=adjoint)

    def lu(self, x):
        return self._sd.op("lu", x, n_out=2)

    def matrix_determinant(self, x):
        return self._sd.op("matrix_determinant", x)

    def matrix_inverse(self, x):
        return self._sd.op("matrix_inverse", x)

    def matrix_band_part(self, x, lower, upper):
        return self._sd.op("matrix_band_part", x, num_lower=lower,
                           num_upper=upper)

    def diag(self, x):
        return self._sd.op("matrix_diag", x)


class SDBitwise(_Namespace):
    """sd.bitwise() — SDBitwise.java op factory."""

    def and_(self, a, b):
        return self._sd.op("bitwise_and", a, b)

    def or_(self, a, b):
        return self._sd.op("bitwise_or", a, b)

    def xor(self, a, b):
        return self._sd.op("bitwise_xor", a, b)

    def left_shift(self, x, n):
        return self._sd.op("shift_bits", x, shift=int(n))

    def right_shift(self, x, n):
        return self._sd.op("rshift_bits", x, shift=int(n))

    def left_shift_cyclic(self, x, n):
        return self._sd.op("cyclic_shift_bits", x, shift=int(n))

    def right_shift_cyclic(self, x, n):
        return self._sd.op("cyclic_rshift_bits", x, shift=int(n))

    def toggle_bits(self, x):
        return self._sd.op("toggle_bits", x)

    def bits_hamming_distance(self, a, b):
        return self._sd.op("bits_hamming_distance", a, b)


class SDRandom(_Namespace):
    """sd.random() — SDRandom.java op factory. Every draw takes an explicit
    ``seed`` that becomes a functional PRNG key constant (jax discipline:
    same seed → same stream, across backends)."""

    def _key(self, seed):
        import jax as _jax

        return self._sd.constant(self._sd._fresh("rng_key"),
                                 _jax.random.PRNGKey(seed))

    def uniform(self, lo, hi, shape, seed=0):
        return self._sd.op("random_uniform", self._key(seed),
                           shape=tuple(shape), minval=lo, maxval=hi)

    def normal(self, mean, stddev, shape, seed=0):
        return self._sd.op("random_normal", self._key(seed),
                           shape=tuple(shape), mean=mean, stddev=stddev)

    def truncated_normal(self, mean, stddev, shape, seed=0):
        return self._sd.op("random_truncated_normal", self._key(seed),
                           shape=tuple(shape), mean=mean, stddev=stddev)

    def bernoulli(self, p, shape, seed=0):
        return self._sd.op("random_bernoulli", self._key(seed),
                           shape=tuple(shape), prob=p)

    def exponential(self, rate, shape, seed=0):
        return self._sd.op("random_exponential", self._key(seed),
                           shape=tuple(shape), rate=rate)

    def gamma(self, alpha, shape, seed=0, beta=1.0):
        return self._sd.op("random_gamma", self._key(seed),
                           shape=tuple(shape), alpha=alpha, beta=beta)


# ---------------------------------------------------------------------------
# TrainingConfig (org/nd4j/autodiff/samediff/TrainingConfig.java)
# ---------------------------------------------------------------------------


class TrainingConfig:
    def __init__(self, updater=None, l1: float = 0.0, l2: float = 0.0,
                 weight_decay: float = 0.0,
                 data_set_feature_mapping: Optional[Sequence[str]] = None,
                 data_set_label_mapping: Optional[Sequence[str]] = None,
                 loss_variables: Optional[Sequence[str]] = None):
        from deeplearning4j_tpu.nn.updater import Adam, get_updater

        self.updater = get_updater(updater) if updater is not None else Adam()
        self.l1 = l1
        self.l2 = l2
        self.weight_decay = weight_decay
        self.feature_mapping = list(data_set_feature_mapping or [])
        self.label_mapping = list(data_set_label_mapping or [])
        self.loss_variables = list(loss_variables or [])


class SameDiff:
    """The graph container + execution facade.

    ``optimize``: run the pre-trace graph optimizer (autodiff/optimize.py —
    DCE, constant folding, CSE, algebraic identity cleanup) before every
    compilation. ``optimize_passes``: subset of
    ``optimize.PASS_ORDER`` to enable (None = all; per-pass opt-out).
    ``last_compile_stats``: OptimizeStats for the most recent compilation
    (per-pass node deltas, trace seconds, XLA compile seconds).
    ``validate``: run graftcheck (analysis/ — the abstract shape/dtype
    interpreter, docs/ANALYSIS.md) before every compilation and raise
    :class:`~deeplearning4j_tpu.analysis.GraphCheckError` on provable
    shape/dtype errors, with node provenance — instead of the XLA tracer
    error deep inside the trace. ``check()`` runs it on demand either way.
    """

    def __init__(self, optimize: bool = True,
                 optimize_passes: Optional[Sequence[str]] = None,
                 validate: bool = False) -> None:
        self._vars: Dict[str, SDVariable] = {}
        self._arrays: Dict[str, jnp.ndarray] = {}  # VARIABLE + CONSTANT values
        self._nodes: List[_Node] = []
        # instance-local op impls (control-flow closures from scan/while/cond);
        # kept off the module-global GRAPH_OPS so instances cannot collide
        self._local_ops: Dict[str, Callable[..., Any]] = {}
        self._name_counter = 0
        self.math = SDMath(self)
        self.nn = SDNN(self)
        self.cnn = SDCNN(self)
        self.image = SDImage(self)
        self.linalg = SDLinalg(self)
        self.bitwise = SDBitwise(self)
        self.random = SDRandom(self)
        self.rnn = SDRNN(self)
        self.loss = SDLoss(self)
        self.training_config: Optional[TrainingConfig] = None
        self._updater_state: Optional[Dict[str, Any]] = None
        self._step = 0
        # exact-resume bookkeeping (docs/ROBUSTNESS.md): epochs completed
        # across fit() calls + completed batches in the current epoch
        self.epoch_count = 0
        self.batch_in_epoch = 0
        self._jit_cache: Dict[Any, Any] = {}
        self._grad_requested = False
        # graph IO signature, populated by the import layer (imports/ir.py)
        self.graph_inputs: List[str] = []
        self.graph_outputs: List[str] = []
        # pre-trace optimizer wiring (autodiff/optimize.py)
        self.optimize = optimize
        self.optimize_passes = (tuple(optimize_passes)
                                if optimize_passes is not None else None)
        self.last_compile_stats = None
        # graftcheck wiring (analysis/ — docs/ANALYSIS.md)
        self.validate = validate
        self.last_check_report = None
        # recompile-ledger wiring (observe/ — docs/OBSERVABILITY.md): the
        # cause of the most recent cache invalidation, applied by
        # _note_compile to EVERY previously-compiled key rebuilt after it
        # (keys never compiled before stay "first_compile")
        self._pending_invalidate: Optional[str] = None
        self._ever_compiled: set = set()

    # ------------------------------------------------------------- factories
    @staticmethod
    def create(optimize: bool = True,
               optimize_passes: Optional[Sequence[str]] = None,
               validate: bool = False) -> "SameDiff":
        return SameDiff(optimize=optimize, optimize_passes=optimize_passes,
                        validate=validate)

    def _fresh(self, prefix: str) -> str:
        self._name_counter += 1
        return f"{prefix}_{self._name_counter}"

    def placeholder(self, name: str, shape: Sequence[Optional[int]] = None,
                    dtype=jnp.float32) -> SDVariable:
        v = SDVariable(self, name, "PLACEHOLDER",
                       None if shape is None else tuple(-1 if s is None else s for s in shape),
                       dtype)
        self._vars[name] = v
        return v

    # reference alias
    place_holder = placeholder

    def var(self, name: str, array=None, shape: Sequence[int] = None,
            dtype=jnp.float32, initializer: str = "xavier", key=None) -> SDVariable:
        """Trainable variable — from array or (shape, weight-init scheme)."""
        if array is None:
            from deeplearning4j_tpu.ops.weight_init import init_weights

            if shape is None:
                raise ValueError("var() needs an array or a shape")
            key = key if key is not None else jax.random.key(len(self._vars))
            array = init_weights(key, tuple(shape), initializer, dtype=dtype)
        arr = jnp.asarray(array)
        v = SDVariable(self, name, "VARIABLE", arr.shape, arr.dtype)
        self._vars[name] = v
        self._arrays[name] = arr
        return v

    def constant(self, name_or_value, value=None) -> SDVariable:
        if value is None:
            name, value = self._fresh("const"), name_or_value
        else:
            name = name_or_value
        arr = jnp.asarray(value)
        v = SDVariable(self, name, "CONSTANT", arr.shape, arr.dtype)
        self._vars[name] = v
        self._arrays[name] = arr
        return v

    def op(self, name: str, *inputs, **kwargs) -> SDVariable:
        """Record ANY catalog op by name — the Nd4j.exec(DynamicCustomOp)
        parity surface: every declarable-op-registry name (README carries
        the lint-checked count) plus the graph-op table is recordable
        without a dedicated namespace method.

            vals, idx = sd.op("top_k", x, k=5, n_out=2)

        Multi-output ops take ``n_out`` (the DynamicCustomOp numOutputs
        role) and return a list. Unknown names raise at graph build, not at
        execution."""
        n_out = int(kwargs.pop("n_out", 1))
        resolve_graph_op(name, self._local_ops)  # existence check
        ins = [self._lift(x) for x in inputs]
        return self._record(name, ins, kwargs or None, n_out=n_out)

    def _lift(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            return x
        return self.constant(x)

    def _rename(self, old: str, new: str) -> None:
        if new in self._vars:
            raise ValueError(f"variable '{new}' already exists")
        v = self._vars.pop(old)
        v.name = new
        self._vars[new] = v
        if old in self._arrays:
            self._arrays[new] = self._arrays.pop(old)
        for n in self._nodes:
            n.inputs = [new if i == old else i for i in n.inputs]
            n.outputs = [new if o == old else o for o in n.outputs]
        # renaming is a graph mutation: cached optimizer plans hold frozen
        # node-name snapshots and compiled traces key envs by name
        self._invalidate("graph_mutation")

    def _invalidate(self, cause: str) -> None:
        """Clear the jit cache, remembering WHY — the recompile ledger tags
        rebuilt keys with this cause. A clear while the cache is empty AND
        no cause is pending (graph still being built, nothing ever
        compiled) is not an invalidation; an empty cache WITH a pending
        cause means we are between invalidation and recompile, where a
        second invalidation (e.g. rebind then mutate) updates the cause to
        the latest one instead of silently keeping the first."""
        if self._jit_cache or self._pending_invalidate is not None:
            self._pending_invalidate = cause
        self._jit_cache.clear()

    def _note_compile(self, fn, kind: str, signature: str,
                      stable_key: Any = None) -> None:
        """Report a compile to the recompile ledger iff this (fn, input
        signature) pair has not run before (observe.note_jit_signature: the
        seen-signature set lives ON the cached function, so every
        `_jit_cache` invalidation path drops the history with it).

        Cause resolution: ``stable_key`` mirrors the `_jit_cache` key and
        survives invalidation in ``_ever_compiled`` — a key compiled before
        that shows up as a fresh fn was REBUILT, and reports the pending
        invalidation cause (graph_mutation / constant_rebind /
        variable_rebind) — every such key after one invalidation, not just
        the first to recompile. A key never compiled before reports
        first_compile; a cached fn seeing a new shape/dtype signature
        reports new_shape (jax retraces per shape)."""
        from deeplearning4j_tpu import observe

        ident = (kind, stable_key)
        rebuilt = ident in self._ever_compiled
        pend = (self._pending_invalidate if rebuilt else None) \
            or "first_compile"
        cause = observe.note_jit_signature(
            fn, graph="samediff", key=kind, signature=signature,
            stats=self.last_compile_stats, cause_if_new_fn=pend)
        if cause is not None:
            self._ever_compiled.add(ident)

    # -------------------------------------------------------------- recording
    def _record(self, op: str, inputs: List[SDVariable],
                kwargs: Optional[Dict[str, Any]] = None, n_out: int = 1):
        resolve_graph_op(op, self._local_ops)  # fail fast on unknown op
        out_names = [self._fresh(op) for _ in range(n_out)]
        self._nodes.append(_Node(op, [v.name for v in inputs], dict(kwargs or {}), out_names))
        outs = []
        for n in out_names:
            v = SDVariable(self, n, "ARRAY")
            self._vars[n] = v
            outs.append(v)
        self._invalidate("graph_mutation")  # graph changed; recompile
        return outs[0] if n_out == 1 else tuple(outs)

    # -------------------------------------------------------------- execution
    def _needed_nodes(self, wanted: Sequence[str]) -> List[_Node]:
        """Ancestor subgraph of the wanted outputs (the dependency tracking
        the reference's AbstractSession does per step — here once, at trace)."""
        needed: set = set(wanted)
        keep: List[_Node] = []
        for node in reversed(self._nodes):
            if any(o in needed for o in node.outputs):
                keep.append(node)
                needed.update(node.inputs)
        keep.reverse()
        return keep

    def _precision_policy(self) -> str:
        """Dtype policy the graph's float variables imply — f32 graphs get
        f32 MXU math (nn.dtype.precision_scope), same as the network
        classes' forward chokepoints. Imported f32 models (TF/ONNX golden
        parity) would otherwise silently run bf16-class matmuls on TPU."""
        for a in self._arrays.values():
            dt = getattr(a, "dtype", None)
            if dt is not None and dt in (jnp.bfloat16, jnp.float16):
                return "bfloat16"
        return "float32"

    # ------------------------------------------------------------ graftcheck
    def check(self, outputs: Optional[Sequence[str]] = None,
              name: str = "<samediff>"):
        """Statically verify the graph with the abstract shape/dtype
        interpreter (analysis/ — docs/ANALYSIS.md). Returns a CheckReport
        whose findings carry GC error codes and node provenance; also
        stored as ``last_check_report``. Does not raise — callers that
        want the hard failure use ``report.raise_on_errors()`` (what
        ``validate=True`` and the importers do)."""
        from deeplearning4j_tpu.analysis import check_samediff

        report = check_samediff(self, outputs=outputs, graph_name=name)
        self.last_check_report = report
        return report

    def _input_avals(self):
        """Declared placeholder metadata as symbolic avals — the optimizer's
        pass-invariance checker unifies named batch dims through them."""
        from deeplearning4j_tpu.analysis import AVal

        return {n: AVal.of_placeholder(n, v.shape, v.dtype)
                for n, v in self._vars.items() if v.vtype == "PLACEHOLDER"}

    def _maybe_validate(self, out_names: Tuple[str, ...]) -> None:
        """validate=True: graftcheck the subgraph about to be traced; a
        provable shape/dtype error raises GraphCheckError here — at graph
        level, with node provenance — not inside the XLA trace."""
        if not self.validate:
            return
        cache_key = ("checked", out_names)
        if cache_key in self._jit_cache:  # cleared on every graph mutation
            return
        self.check(outputs=out_names).raise_on_errors()
        self._jit_cache[cache_key] = True

    def _effective_passes(self) -> Optional[Tuple[str, ...]]:
        """The pass tuple this compile will actually run: the explicit
        ``optimize_passes`` or the env-resolved default. Cache keys use
        THIS, not the raw attribute — otherwise toggling DL4J_TPU_FUSION /
        DL4J_TPU_AUTOCAST between calls would silently serve a plan built
        under the previous setting."""
        if not self.optimize:
            return None
        if self.optimize_passes is not None:
            return self.optimize_passes
        from deeplearning4j_tpu.autodiff import optimize as _opt

        return _opt.default_passes()

    def _graph_plan(self, out_names: Tuple[str, ...]):
        """Optimized execution plan for the given outputs, or None when the
        optimizer is off. Cached in ``_jit_cache`` so the exact paths that
        invalidate compiled traces (graph mutation in ``_record``, constant
        rebind in ``set_arr``) also invalidate stale fold/CSE results."""
        if not self.optimize:
            return None
        from deeplearning4j_tpu.autodiff import optimize as _opt

        cache_key = ("plan", out_names, self._effective_passes())
        plan = self._jit_cache.get(cache_key)
        if plan is None:
            policy = self._precision_policy()
            # shape/dtype evidence for algebraic strips comes ONLY from
            # actual bound arrays (VARIABLE + CONSTANT): declared
            # PLACEHOLDER metadata is not enforced at feed time — feeds are
            # shape/dtype-polymorphic under jit — so trusting it would bake
            # a strip that is wrong for a differently-shaped/typed feed
            seed_dtypes = {n: np.dtype(a.dtype) for n, a in self._arrays.items()}
            var_shapes = {n: tuple(np.shape(a))
                          for n, a in self._arrays.items()}
            # seed with the reachable subgraph — the exact node set the
            # unoptimized trace executes — so plan execution can never run
            # (or fold) a dead node the plain path would have skipped, even
            # with the 'dce' pass opted out; pipeline 'dce' then prunes
            # nodes orphaned by folding/aliasing
            plan = _opt.optimize_graph(
                self._needed_nodes(out_names), list(out_names),
                const_env=self._const_env(),
                seed_dtypes=seed_dtypes,
                var_shapes=var_shapes,
                local_ops=self._local_ops,
                resolve_op=lambda name: resolve_graph_op(name, self._local_ops),
                passes=self._effective_passes(),
                precision_policy=policy,
                input_avals=self._input_avals())
            self._jit_cache[cache_key] = plan
        self.last_compile_stats = plan.stats
        return plan

    def _interpret(self, env: Dict[str, Any], wanted: Sequence[str],
                   plan=None) -> Dict[str, Any]:
        """Run the needed subgraph in order (pure; called under trace/jit).
        With a ``plan`` (GraphPlan), the optimized node list executes instead
        and wanted names resolve through the plan's alias map; the caller
        must have merged ``plan.extra_consts`` into ``env``."""
        from deeplearning4j_tpu.nn import dtype as DT

        nodes = plan.nodes if plan is not None else self._needed_nodes(wanted)
        with DT.precision_scope(self._precision_policy()):
            for node in nodes:
                if not all(i in env for i in node.inputs):
                    missing = [i for i in node.inputs if i not in env]
                    raise KeyError(
                        f"op '{node.op}' needs {missing}; placeholders not fed or "
                        f"graph out of order")
                fn = resolve_graph_op(node.op, self._local_ops)
                res = fn(*[env[i] for i in node.inputs], **node.kwargs)
                if len(node.outputs) == 1:
                    env[node.outputs[0]] = res
                else:
                    for o, r in zip(node.outputs, res):
                        env[o] = r
        if plan is not None:
            return {w: env[plan.resolve(w)] for w in wanted}
        return {w: env[w] for w in wanted}

    def _exec_fn(self, out_names: Tuple[str, ...]):
        """Build + cache the compiled whole-graph function for given outputs.

        CONSTANT-vtype arrays are closed over (baked into the trace as
        literals) rather than passed as jit arguments: a constant passed as
        an argument becomes a tracer, which breaks trace-time-concrete
        shape arithmetic (imported tf.shape→Pack→Reshape chains) and denies
        XLA constant folding. VARIABLEs stay arguments so training updates
        never trigger recompiles. The optimizer plan's folded constants join
        the baked set; the CompiledGraph wrapper measures trace vs compile
        seconds into ``last_compile_stats``."""
        from deeplearning4j_tpu.autodiff.optimize import CompiledGraph

        cache_key = ("exec", out_names, bool(self.optimize),
                     self._effective_passes())
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            self._maybe_validate(out_names)
            plan = self._graph_plan(out_names)
            const_env = self._const_env()
            if plan is not None:
                const_env = {**const_env, **plan.extra_consts}

            def run(var_arrays, feeds):
                env = dict(const_env)
                env.update(var_arrays)
                env.update(feeds)
                return self._interpret(env, out_names, plan)

            fn = CompiledGraph(jax.jit(run),
                               plan.stats if plan is not None else None)
            fn._const_names = frozenset(const_env)
            self._jit_cache[cache_key] = fn
        self.last_compile_stats = fn.stats
        return fn

    def _var_arrays(self, fn):
        return {k: v for k, v in self._arrays.items()
                if k not in fn._const_names}

    def _const_env(self) -> Dict[str, Any]:
        """CONSTANT-vtype arrays, for baking into traces (see _exec_fn)."""
        return {n: a for n, a in self._arrays.items()
                if self._vars[n].vtype == "CONSTANT"}

    def output(self, feeds: Dict[str, Any], outputs: Union[str, Sequence[str]]):
        """Execute the graph — ONE compiled XLA computation
        (InferenceSession.output analog, minus the interpreter)."""
        if isinstance(outputs, str):
            outputs = [outputs]
        fn = self._exec_fn(tuple(outputs))
        from deeplearning4j_tpu.observe import signature_of

        self._note_compile(fn, "exec", signature_of(**feeds),
                           stable_key=(tuple(outputs), bool(self.optimize),
                                       self.optimize_passes))
        res = fn(self._var_arrays(fn),
                 {k: jnp.asarray(v) for k, v in feeds.items()})
        return {k: np.asarray(v) for k, v in res.items()}

    exec = output  # reference SameDiff.exec alias

    # --------------------------------------------------------------- autodiff
    def create_grad_function(self) -> None:
        """API-parity marker (reference builds the grad subgraph eagerly;
        we derive gradients by jax.grad at execution time)."""
        self._grad_requested = True

    def calculate_gradients(self, feeds: Dict[str, Any], loss_name: str,
                            wrt: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        """Gradients of a scalar loss variable w.r.t. VARIABLEs
        (sd.calculateGradients analog)."""
        wrt = list(wrt) if wrt is not None else [
            n for n, v in self._vars.items() if v.vtype == "VARIABLE"]
        cache_key = ("grad", loss_name, tuple(wrt), bool(self.optimize),
                     self._effective_passes())
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            self._maybe_validate((loss_name,))
            plan = self._graph_plan((loss_name,))
            const_env = self._const_env()
            if plan is not None:
                const_env = {**const_env, **plan.extra_consts}

            def loss_of(train_vars, other_arrays, feeds_):
                env = dict(const_env)  # baked: constants stay un-traced
                env.update(other_arrays)
                env.update(train_vars)
                env.update(feeds_)
                return self._interpret(env, [loss_name], plan)[loss_name]

            fn = jax.jit(jax.grad(loss_of))
            fn._const_names = frozenset(const_env)
            self._jit_cache[cache_key] = fn
        from deeplearning4j_tpu.observe import signature_of

        self._note_compile(fn, "grad", signature_of(**feeds),
                           stable_key=cache_key)
        train_vars = {n: self._arrays[n] for n in wrt}
        other = {n: a for n, a in self._arrays.items()
                 if n not in train_vars and n not in fn._const_names}
        grads = fn(train_vars, other, {k: jnp.asarray(v) for k, v in feeds.items()})
        return {k: np.asarray(v) for k, v in grads.items()}

    # --------------------------------------------------------------- training
    def set_training_config(self, tc: TrainingConfig) -> None:
        self.training_config = tc

    def training_state(self) -> Dict[str, Any]:
        """Full training state for exact resume — the checkpointer's state
        protocol (parallel/checkpoint.py): trainable VARIABLE arrays,
        updater slots, step/epoch position and the data cursor. Initializes
        the updater state if fit has not run yet, so a restore BEFORE the
        first fit still finds a matching pytree."""
        tc = self.training_config
        trainable = [n for n, v in self._vars.items()
                     if v.vtype == "VARIABLE"]
        if self._updater_state is None and tc is not None:
            self._updater_state = {
                n: tc.updater.init_state(self._arrays[n]) for n in trainable}
        return {
            "params": {n: self._arrays[n] for n in trainable},
            "opt_state": self._updater_state
            if self._updater_state is not None else {},
            "iteration": np.asarray(self._step),
            "epoch": np.asarray(self.epoch_count),
            "data_cursor": np.asarray(self.batch_in_epoch),
        }

    def apply_training_state(self, state: Dict[str, Any]) -> None:
        """Inverse of :meth:`training_state`. Same-shape assignment — the
        cached jitted train step survives (zero ``new_shape``)."""
        for n, a in state["params"].items():
            self._arrays[n] = jnp.asarray(a)
        opt = state.get("opt_state") or {}
        if opt:
            self._updater_state = jax.tree.map(jnp.asarray, opt)
        self._step = int(state["iteration"])
        self.epoch_count = int(state["epoch"])
        self.batch_in_epoch = int(state.get("data_cursor", 0))

    def _train_step_fn(self, loss_name: str):
        tc = self.training_config
        upd = tc.updater
        self._maybe_validate((loss_name,))
        plan = self._graph_plan((loss_name,))
        const_env = self._const_env()
        if plan is not None:
            const_env = {**const_env, **plan.extra_consts}

        def step_fn(train_vars, upd_state, step, other_arrays, feeds):
            def loss_of(tv):
                env = dict(const_env)  # baked: constants stay un-traced
                env.update(other_arrays)
                env.update(tv)
                env.update(feeds)
                return self._interpret(env, [loss_name], plan)[loss_name]

            loss, grads = jax.value_and_grad(loss_of)(train_vars)
            lr = upd.lr(step)
            new_vars, new_state = {}, {}
            for n, g in grads.items():
                w = train_vars[n]
                if tc.l2:
                    g = g + tc.l2 * w
                if tc.l1:
                    g = g + tc.l1 * jnp.sign(w)
                # fused updater step (ops/pallas_updater.py): one kernel
                # pass per leaf on TPU, the identical apply() math elsewhere
                nw, s = upd.apply_fused(w, g, upd_state[n], lr, step)
                if tc.weight_decay:
                    nw = nw - lr * tc.weight_decay * w
                new_vars[n] = nw
                new_state[n] = s
            return new_vars, new_state, loss

        return jax.jit(step_fn, donate_argnums=(0, 1))

    def fit(self, iterator, epochs: int = 1, loss_name: Optional[str] = None) -> List[float]:
        """sd.fit(DataSetIterator, nEpochs) — TrainingSession analog.

        Feature/label arrays bind to placeholders via the TrainingConfig
        mappings. Returns per-epoch mean losses (History analog)."""
        tc = self.training_config
        if tc is None:
            raise ValueError("call set_training_config first")
        loss_name = loss_name or (tc.loss_variables[0] if tc.loss_variables else None)
        if loss_name is None:
            raise ValueError("no loss variable configured")
        trainable = [n for n, v in self._vars.items() if v.vtype == "VARIABLE"]
        if self._updater_state is None:
            self._updater_state = {n: tc.updater.init_state(self._arrays[n]) for n in trainable}
        step_key = ("train", loss_name, bool(self.optimize),
                    self._effective_passes())
        step_fn = self._jit_cache.get(step_key)
        if step_fn is None:
            step_fn = self._train_step_fn(loss_name)
            self._jit_cache[step_key] = step_fn

        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

        if isinstance(iterator, DataSet):
            iterator = ListDataSetIterator(iterator, batch_size=32)

        from deeplearning4j_tpu import observe
        from deeplearning4j_tpu.observe import signature_of

        _m = observe.metrics()
        _steps_c = _m.counter("dl4j_tpu_train_steps_total", model="samediff")
        _ex_c = _m.counter("dl4j_tpu_train_examples_total", model="samediff")
        _xfer_c = _m.counter("dl4j_tpu_host_to_device_transfers_total",
                             model="samediff")
        _step_h = _m.histogram("dl4j_tpu_train_step_seconds",
                               model="samediff")
        from deeplearning4j_tpu import faults
        from deeplearning4j_tpu.nn.listeners import (
            notify_fit_done, notify_preemption)

        history = []
        listeners = getattr(self, "_listeners", [])
        for ep in range(epochs):
            losses = []
            t_prev = time.perf_counter()
            # nonzero only when resuming mid-epoch from a checkpoint: the
            # first `skip` batches were already consumed by the killed run
            skip = self.batch_in_epoch
            for bi, ds in enumerate(iterator):
                if bi < skip:
                    continue
                # preemption (docs/ROBUSTNESS.md): injected fault = HARD
                # kill (supervisor restores+resumes); flag = SOFT SIGTERM
                # path (final snapshot, clean exit)
                faults.maybe_fail("preemption")
                if faults.preemption_requested():
                    notify_preemption(self, listeners)
                    return history
                feeds = {}
                feats = ds.features if isinstance(ds.features, (list, tuple)) else [ds.features]
                labs = ds.labels if isinstance(ds.labels, (list, tuple)) else [ds.labels]
                for name, arr in zip(tc.feature_mapping, feats):
                    feeds[name] = jnp.asarray(arr)
                for name, arr in zip(tc.label_mapping, labs):
                    feeds[name] = jnp.asarray(arr)
                self._note_compile(step_fn, "train", signature_of(**feeds),
                                   stable_key=step_key)
                train_vars = {n: self._arrays[n] for n in trainable}
                # constants are baked into step_fn's closure (_const_env)
                other = {n: a for n, a in self._arrays.items()
                         if n not in train_vars
                         and self._vars[n].vtype != "CONSTANT"}
                new_vars, self._updater_state, loss = step_fn(
                    train_vars, self._updater_state,
                    jnp.asarray(self._step, jnp.int32), other, feeds)
                self._arrays.update(new_vars)
                self._step += 1
                self.batch_in_epoch = bi + 1  # cursor BEFORE listeners save
                losses.append(loss)
                # inter-step latency (includes compile on the first step);
                # counters/histograms are host-side — never under the trace
                now = time.perf_counter()
                _step_h.observe(now - t_prev)
                t_prev = now
                _steps_c.inc()
                _ex_c.inc(ds.num_examples())
                _xfer_c.inc(len(feeds))
                for lst in listeners:
                    lst.iteration_done(self, self._step, ep, loss)
            self.batch_in_epoch = 0
            self.epoch_count += 1
            # log the GLOBAL post-increment epoch_count (matching MLN/CG):
            # a resumed fit's local `ep` restarts at 0 and would duplicate
            # the epoch numbers the killed run already emitted
            if losses:
                ep_loss = float(jnp.mean(jnp.stack(
                    [jnp.asarray(l) for l in losses])))
                history.append(ep_loss)
                observe.log_event("train_epoch", model="samediff",
                                  epoch=self.epoch_count,
                                  steps=len(losses), mean_loss=ep_loss)
            else:
                # a resumed epoch whose batches were all consumed before
                # the kill: nothing trained HERE — no NaN in history, no
                # NaN (spec-invalid JSON) in the event log
                observe.log_event("train_epoch", model="samediff",
                                  epoch=self.epoch_count, steps=0)
        notify_fit_done(self, listeners)
        return history

    # ---------------------------------------------------------- control flow
    def scan(self, fn, init, xs_var: "SDVariable") -> "SDVariable":
        """Recorded lax.scan over axis 0 of xs (the TF-frames / Enter-Exit
        control-flow analog — SURVEY §4.3 maps frames to lax loops).

        fn: (carry, x_slice) -> (new_carry, y_slice), built from jnp ops
        (traced at execution time, NOT recorded node-by-node)."""
        name = self._fresh("scan")

        def scan_op(xs, init_val=init):
            carry, ys = jax.lax.scan(fn, init_val, xs)
            return ys

        self._local_ops[name + "_impl"] = scan_op
        return self._record(name + "_impl", [xs_var])

    def while_loop(self, cond_fn, body_fn, init_var: "SDVariable") -> "SDVariable":
        """Recorded lax.while_loop (TF While-frame analog)."""
        name = self._fresh("while")

        def while_op(x):
            return jax.lax.while_loop(cond_fn, body_fn, x)

        self._local_ops[name + "_impl"] = while_op
        return self._record(name + "_impl", [init_var])

    def while_loop_multi(self, cond_fn, body_fn,
                         init_vars: Sequence["SDVariable"]):
        """Recorded multi-carry lax.while_loop — the TF2 While/StatelessWhile
        function-graph analog (AbstractSession loop frames, SURVEY §4.3).

        cond_fn: tuple(carry) -> scalar bool; body_fn: tuple(carry) ->
        tuple(carry). Returns one SDVariable per loop variable (the final
        carry), mirroring the TF While node's N outputs."""
        name = self._fresh("while")
        n = len(init_vars)

        def while_op(*vals):
            out = jax.lax.while_loop(cond_fn, body_fn, tuple(vals))
            # n_out=1 slots store a bare value, not lax's 1-tuple carry
            return out[0] if n == 1 else out

        self._local_ops[name + "_impl"] = while_op
        return self._record(name + "_impl", list(init_vars), n_out=n)

    def scan_multi(self, fn, init_vars: Sequence["SDVariable"],
                   xs_vars: Sequence["SDVariable"], n_ys: int,
                   length: Optional[int] = None):
        """Recorded multi-carry multi-output lax.scan — the ONNX Scan /
        Loop-with-scan-outputs analog (reference: onnx Scan/Loop op defs,
        SURVEY §3.2 samediff-import-onnx).

        fn: (tuple(carry), tuple(x_slices)) -> (tuple(carry), tuple(y_slices));
        returns [final carries…] + [stacked ys…] as SDVariables."""
        name = self._fresh("scan")
        n_state = len(init_vars)
        n_out = n_state + n_ys

        def scan_op(*vals):
            inits = tuple(vals[:n_state])
            xs = tuple(vals[n_state:])
            carry, ys = jax.lax.scan(fn, inits, xs if xs else None,
                                     length=length)
            outs = tuple(carry) + (tuple(ys) if isinstance(ys, tuple)
                                   else (ys,) if n_ys else ())
            return outs[0] if n_out == 1 else outs

        self._local_ops[name + "_impl"] = scan_op
        return self._record(name + "_impl",
                            list(init_vars) + list(xs_vars), n_out=n_out)

    def cond_multi(self, pred_var: "SDVariable", true_fn, false_fn,
                   operands: Sequence["SDVariable"], n_out: int):
        """Recorded lax.cond over N operands with M outputs — the TF2
        If/StatelessIf function-graph analog. true_fn/false_fn:
        (*operands) -> tuple of n_out values."""
        name = self._fresh("cond")

        def cond_op(pred, *vals):
            return jax.lax.cond(jnp.asarray(pred).astype(bool).reshape(()),
                                true_fn, false_fn, *vals)

        self._local_ops[name + "_impl"] = cond_op
        return self._record(name + "_impl", [pred_var] + list(operands),
                            n_out=n_out)

    def cond(self, pred_var: "SDVariable", true_fn, false_fn,
             operand: "SDVariable") -> "SDVariable":
        """Recorded lax.cond (TF Switch/Merge analog)."""
        name = self._fresh("cond")

        def cond_op(pred, x):
            return jax.lax.cond(pred.astype(bool).reshape(()), true_fn, false_fn, x)

        self._local_ops[name + "_impl"] = cond_op
        return self._record(name + "_impl", [pred_var, operand])

    # --------------------------------------------------------------- listeners
    def set_listeners(self, *listeners) -> None:
        """SameDiff listener family (autodiff/listeners/** — ScoreListener,
        HistoryListener, CheckpointListener). Listeners receive
        iteration_done(self, iteration, epoch, loss) during fit()."""
        self._listeners = list(listeners)

    # ------------------------------------------------------------------ serde
    def to_dict(self) -> Dict[str, Any]:
        return {
            "variables": [
                {"name": v.name, "vtype": v.vtype,
                 "shape": list(v.shape) if v.shape else None,
                 "dtype": str(np.dtype(v.dtype)) if v.dtype else "float32"}
                for v in self._vars.values()
            ],
            "nodes": [
                {"op": n.op, "inputs": n.inputs, "kwargs": _jsonable(n.kwargs),
                 "outputs": n.outputs}
                for n in self._nodes
            ],
            "name_counter": self._name_counter,
        }

    def save(self, path: str, save_updater_state: bool = False) -> None:
        """sd.save(file) — zip of graph JSON + variable arrays
        (FlatBuffers-file analog). Persists the training step so a resumed
        fit() keeps Adam bias-correction and LR schedules aligned (matches
        nn/serde.py's meta.json iteration_count)."""
        unsaveable = sorted({n.op for n in self._nodes if n.op in self._local_ops})
        if unsaveable:
            raise ValueError(
                "graph contains control-flow ops whose Python closures cannot "
                f"be serialized: {unsaveable}; rebuild the graph after load() "
                "or express the loop body as recorded ops")
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("graph.json", json.dumps(self.to_dict(), indent=2))
            z.writestr("meta.json", json.dumps({"step": self._step}))
            import io

            buf = io.BytesIO()
            np.savez(buf, **{k: np.asarray(v) for k, v in self._arrays.items()})
            z.writestr("arrays.npz", buf.getvalue())
            if save_updater_state and self._updater_state is not None:
                buf2 = io.BytesIO()
                flat = {}
                for n, st in self._updater_state.items():
                    for k, v in st.items():
                        flat[f"{n}::{k}"] = np.asarray(v)
                np.savez(buf2, **flat)
                z.writestr("updater.npz", buf2.getvalue())

    @staticmethod
    def load(path: str) -> "SameDiff":
        sd = SameDiff()
        with zipfile.ZipFile(path, "r") as z:
            d = json.loads(z.read("graph.json").decode())
            import io

            arrays = np.load(io.BytesIO(z.read("arrays.npz")))
            for vd in d["variables"]:
                v = SDVariable(sd, vd["name"], vd["vtype"],
                               tuple(vd["shape"]) if vd["shape"] else None,
                               jnp.dtype(vd["dtype"]))
                sd._vars[v.name] = v
                if v.name in arrays.files:
                    sd._arrays[v.name] = jnp.asarray(arrays[v.name])
            for nd in d["nodes"]:
                sd._nodes.append(_Node(nd["op"], list(nd["inputs"]),
                                       dict(nd["kwargs"]), list(nd["outputs"])))
            sd._name_counter = d.get("name_counter", len(sd._vars))
            if "meta.json" in z.namelist():
                sd._step = int(json.loads(z.read("meta.json").decode()).get("step", 0))
            if "updater.npz" in z.namelist():
                upd = np.load(io.BytesIO(z.read("updater.npz")))
                state: Dict[str, Dict[str, Any]] = {}
                for key in upd.files:
                    n, k = key.split("::", 1)
                    state.setdefault(n, {})[k] = jnp.asarray(upd[key])
                sd._updater_state = state
        return sd

    def as_stablehlo(self, feeds: Dict[str, Any], outputs: Sequence[str]) -> str:
        """StableHLO text of the whole-graph computation — the artifact the
        reference's libnd4j GraphExecutioner FlatBuffers file maps to."""
        fn = self._exec_fn(tuple(outputs))
        return fn.lower(self._var_arrays(fn),
                        {k: jnp.asarray(v) for k, v in feeds.items()}).as_text()

    # ------------------------------------------------------------------ misc
    def variables(self) -> List[str]:
        return list(self._vars)

    def get_variable(self, name: str) -> SDVariable:
        return self._vars[name]

    def get_arr(self, name: str) -> np.ndarray:
        return np.asarray(self._arrays[name])

    def set_arr(self, name: str, value) -> None:
        if name not in self._vars:
            raise KeyError(name)
        old = self._arrays.get(name)
        arr = jnp.asarray(value)
        self._arrays[name] = arr
        # keep the variable's declared metadata in sync — optimizer plans
        # and shape inference read it, and a stale declared shape would
        # survive the cache clear below
        self._vars[name].shape = tuple(arr.shape)
        self._vars[name].dtype = arr.dtype
        if self._vars[name].vtype == "CONSTANT":
            # constants are BAKED into cached traces (_exec_fn/_const_env)
            # AND into optimizer plans (fold results); changing one must
            # invalidate every cached computation and plan
            self._invalidate("constant_rebind")
        elif old is None or old.dtype != arr.dtype or old.shape != arr.shape:
            # a VARIABLE changing dtype/shape invalidates optimizer plans
            # (dtype-guarded identity strips) and forces a retrace anyway
            self._invalidate("variable_rebind")

    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} variables, {len(self._nodes)} ops"]
        for n in self._nodes:
            lines.append(f"  {','.join(n.outputs)} = {n.op}({','.join(n.inputs)})")
        return "\n".join(lines)


def _jsonable(kw: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in kw.items():
        if isinstance(v, tuple):
            v = list(v)
        out[k] = v
    return out
