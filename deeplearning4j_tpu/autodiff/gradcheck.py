"""Gradient checking — the correctness workhorse (SURVEY §5.2).

Reference parity:
  * org/deeplearning4j/gradientcheck/GradientCheckUtil.java — central finite
    differences vs analytic backprop per parameter, in DOUBLE, with
    max-relative-error thresholds.
  * org/nd4j/autodiff/validation/{OpValidation, GradCheckUtil}.java — the
    SameDiff-side equivalent + per-op validation TestCase.

These helpers check OUR whole-graph jax.grad against finite differences of
the same compiled forward. Because both run the same XLA computation, this
validates the end-to-end trace (layer math, preprocessors, loss reduction),
exactly what the reference's checkGradients validates for the hand-written
backprop stack.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


DEFAULT_EPS = 1e-6
# f64 central differences at eps=1e-6 carry ~1e-10 intrinsic error, so 1e-5
# is a real bound (the reference's DOUBLE-mode checks use the same order);
# the old 1e-3 default dated from the f32 era and hid true mismatches
DEFAULT_MAX_REL_ERROR = 1e-5
DEFAULT_MIN_ABS_ERROR = 1e-8


def _rel_error(a: float, n: float, min_abs: float) -> float:
    if abs(a - n) < min_abs:
        return 0.0
    denom = abs(a) + abs(n)
    return abs(a - n) / denom if denom > 0 else 0.0


def check_gradients_fn(loss_fn, params, *, eps: float = DEFAULT_EPS,
                       max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                       min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                       max_per_param: int = 25,
                       seed: int = 0, print_failures: bool = True) -> bool:
    """Check jax.grad(loss_fn) vs central finite differences.

    loss_fn: pytree params -> scalar. Checks up to ``max_per_param`` randomly
    chosen coordinates per leaf (the reference subsamples the same way for
    big layers). Runs under a SCOPED x64 context — GradientCheckUtil mandates
    DataType.DOUBLE, but the rest of the framework stays f32.
    """
    with jax.enable_x64():
        return _check_gradients_fn_x64(
            loss_fn, params, eps=eps, max_rel_error=max_rel_error,
            min_abs_error=min_abs_error, max_per_param=max_per_param,
            seed=seed, print_failures=print_failures)


def _check_gradients_fn_x64(loss_fn, params, *, eps, max_rel_error,
                            min_abs_error, max_per_param, seed, print_failures) -> bool:
    params = jax.tree.map(lambda a: jnp.asarray(np.asarray(a), jnp.float64), params)
    analytic = jax.grad(loss_fn)(params)
    leaves_p, treedef = jax.tree.flatten(params)
    leaves_g = treedef.flatten_up_to(analytic)
    rng = np.random.RandomState(seed)
    ok = True
    for li, (p, g) in enumerate(zip(leaves_p, leaves_g)):
        p_np = np.asarray(p, np.float64)
        g_np = np.asarray(g, np.float64)
        n = p_np.size
        idxs = range(n) if n <= max_per_param else rng.choice(n, max_per_param, replace=False)
        for i in idxs:
            orig = p_np.reshape(-1)[i]

            def loss_at(v):
                pp = p_np.copy().reshape(-1)
                pp[i] = v
                new_leaves = list(leaves_p)
                new_leaves[li] = jnp.asarray(pp.reshape(p_np.shape))
                return float(loss_fn(treedef.unflatten(new_leaves)))

            num = (loss_at(orig + eps) - loss_at(orig - eps)) / (2 * eps)
            ana = g_np.reshape(-1)[i]
            rel = _rel_error(ana, num, min_abs_error)
            if rel > max_rel_error:
                ok = False
                if print_failures:
                    print(f"GRADCHECK FAIL leaf {li} idx {i}: analytic={ana:.8g} "
                          f"numeric={num:.8g} rel={rel:.3g}")
    return ok


def check_gradients(net, features, labels, *, eps: float = DEFAULT_EPS,
                    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                    max_per_param: int = 25, seed: int = 0,
                    features_mask=None, labels_mask=None) -> bool:
    """GradientCheckUtil.checkGradients(MultiLayerNetwork, ...) analog.

    Checks the full forward+loss of a MultiLayerNetwork (train=False so
    dropout/BN-stat updates don't spoil determinism, matching the reference's
    requirement that gradient checks disable dropout).
    """
    with jax.enable_x64():
        x = jnp.asarray(np.asarray(features), jnp.float64)
        y = jnp.asarray(np.asarray(labels), jnp.float64)
        fm = None if features_mask is None else jnp.asarray(np.asarray(features_mask), jnp.float64)
        lm = None if labels_mask is None else jnp.asarray(np.asarray(labels_mask), jnp.float64)
        net_state = jax.tree.map(lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.net_state)

        def loss_fn(params):
            out, _ = net._forward(params, net_state, x, fm, train=False, rng=None)
            return net._loss_from_out(out, y, lm)

        return _check_gradients_fn_x64(
            loss_fn, net.params, eps=eps, max_rel_error=max_rel_error,
            min_abs_error=min_abs_error, max_per_param=max_per_param, seed=seed,
            print_failures=True)


def check_samediff_gradients(sd, feeds: Dict[str, np.ndarray], loss_name: str,
                             *, eps: float = DEFAULT_EPS,
                             max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                             max_per_param: int = 25, seed: int = 0) -> bool:
    """GradCheckUtil.checkGradients(SameDiff) analog."""
    trainable = [n for n, v in sd._vars.items() if v.vtype == "VARIABLE"]
    with jax.enable_x64():
        feeds64 = {k: jnp.asarray(np.asarray(v), jnp.float64) for k, v in feeds.items()}
        others = {n: jnp.asarray(np.asarray(a), jnp.float64) for n, a in sd._arrays.items()
                  if n not in trainable}

        def loss_fn(train_vars):
            env = dict(others)
            env.update(train_vars)
            env.update(feeds64)
            return sd._interpret(env, [loss_name])[loss_name]

        params = {n: jnp.asarray(np.asarray(sd._arrays[n]), jnp.float64) for n in trainable}
        return _check_gradients_fn_x64(
            loss_fn, params, eps=eps, max_rel_error=max_rel_error,
            min_abs_error=DEFAULT_MIN_ABS_ERROR, max_per_param=max_per_param,
            seed=seed, print_failures=True)
