"""SameDiff listener family additions: History + UI bridging.

Reference parity: nd4j autodiff/listeners/** —
  * records/History.java + HistoryListener: fit() produces a History of
    per-epoch loss curves and evaluation results.
  * UIListener.java: streams training stats to the UI's StatsStorage so
    the dashboard charts SameDiff runs like MultiLayerNetwork ones.

Score/Checkpoint/Profiling listeners already exist in nn/listeners.py and
work on SameDiff.fit via the shared iteration_done protocol; these two
complete the family the round-2 verdict called absent.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class History:
    """records/History.java analog: training-run record."""

    def __init__(self):
        self.loss_curve: List[float] = []        # per-iteration losses
        self.epoch_losses: List[float] = []      # per-epoch means
        self.evaluations: Dict[str, List[Any]] = {}
        self.training_time_millis: float = 0.0

    def final_train_loss(self) -> float:
        return self.loss_curve[-1] if self.loss_curve else float("nan")

    def average_loss(self, epoch: int) -> float:
        return self.epoch_losses[epoch]

    def num_epochs(self) -> int:
        return len(self.epoch_losses)


class HistoryListener:
    """HistoryListener analog: accumulates a History across fit() calls.

    Usage:
        hl = HistoryListener()
        sd.set_listeners(hl)
        sd.fit(data, epochs=3)
        hl.history.loss_curve / .epoch_losses
    """

    def __init__(self):
        self.history = History()
        self._epoch_losses: List[float] = []
        self._current_epoch: Optional[int] = None
        # monotonic clock: this anchor exists only to be subtracted — an
        # NTP step between iterations must not corrupt training_time_millis
        self._t0 = time.perf_counter()

    def iteration_done(self, model, iteration, epoch, score) -> None:
        s = float(score)
        if self._current_epoch is None:
            self._current_epoch = epoch
        if epoch != self._current_epoch:
            self._flush_epoch()
            self._current_epoch = epoch
        self.history.loss_curve.append(s)
        self._epoch_losses.append(s)
        self.history.training_time_millis = \
            (time.perf_counter() - self._t0) * 1000.0

    def _flush_epoch(self) -> None:
        if self._epoch_losses:
            self.history.epoch_losses.append(
                sum(self._epoch_losses) / len(self._epoch_losses))
            self._epoch_losses = []

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        self._flush_epoch()

    def finalize(self) -> History:
        """Flush any open epoch and return the History."""
        self._flush_epoch()
        return self.history


class UIListener:
    """UIListener analog: streams iteration stats into a StatsStorage that
    a running UIServer serves — SameDiff training shows up on the same
    dashboard as network training."""

    def __init__(self, storage, frequency: int = 1):
        self.storage = storage
        self.frequency = max(1, frequency)

    def iteration_done(self, model, iteration, epoch, score) -> None:
        if iteration % self.frequency != 0:
            return
        self.storage.put({
            "iteration": int(iteration), "epoch": int(epoch),
            "score": float(score), "timestamp": time.time(), "layers": {},
        })

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass
