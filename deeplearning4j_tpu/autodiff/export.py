"""AOT export + persistent executable cache (ROADMAP item: compile once,
serve every shape, restart warm).

Compilation is the tax every cold process pays: both supervisors (the
serving engine's ``_recover`` in a fresh process, ``TrainingSupervisor.
resume``) re-jit every compiled fn from scratch. This module makes compiled
computations a *persistent artifact* instead:

* **Export** — an optimized computation (a SameDiff :class:`GraphPlan`
  exec fn, an MLN fused train step, or a serving engine fn) is exported
  through ``jax.export`` into a serialized StableHLO module. Batch axes
  are exported **symbolically** (``jax.export.symbolic_shape``) so ONE
  serialized executable serves arbitrary batch sizes — a fresh signature
  on a restored fn is served without a retrace, and the recompile ledger
  records it as ``cache_hit`` rather than ``new_shape``.
* **Cache** — :class:`ExportCache` persists serialized exports under
  ``$DL4J_TPU_COMPILE_CACHE`` following ops/tuning.py's table-cache
  discipline: atomic tmp+``os.replace`` writes, corrupt-entry warn-once
  fallback to a fresh compile, entries keyed on
  ``(fingerprint, device_kind, jax version)`` so a jax upgrade or a
  different accelerator invalidates by construction.
* **Restore** — :func:`restore_callable` deserializes an entry back into
  a callable and registers it on the recompile ledger with the
  ``cache_hit`` cause (warm restores are attributable, not invisible).

Typed PRNG keys (jax's ``key<fry>`` dtype) cannot cross the export
boundary, so key-taking fns are exported as *raw-key* wrappers taking the
``uint32`` key data and rebuilding the typed key with
``jax.random.wrap_key_data`` inside the computation; the restore-side
wrapper feeds ``jax.random.key_data(key)``. Outputs are bit-identical to
the in-process jit (test-asserted in tests/test_export.py).

Consumers: ``serving/aot.py`` (engine warm boot — the six config-stable
fns plus the draft fns), :func:`warm_boot_net` (``TrainingSupervisor.
resume``), and :func:`install_exec` (SameDiff whole-graph exec).
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jexport

from deeplearning4j_tpu import observe
from deeplearning4j_tpu.ops.tuning import current_device_kind

logger = logging.getLogger(__name__)

SCHEMA = "dl4j_tpu_aot_v1"
ENV_DIR = "DL4J_TPU_COMPILE_CACHE"

# warn-once set for corrupt/stale entries (tuning-table discipline): the
# first bad load of a path logs a warning, later loads stay silent misses
_WARNED_PATHS: set = set()

# the XLA persistent compilation cache is armed at most once per process
# (jax config is global); remembers the directory it was armed with and
# the config values it displaced, so disarm_xla_cache can restore them
_XLA_CACHE_ARMED: List[str] = []
_XLA_CACHE_PRIOR: Dict[str, Any] = {}


def reset_export_cache() -> None:
    """Test seam: forget warn-once state (mirrors tuning.reset_tables)
    and disarm the XLA persistent cache so later compiles in the same
    process stop paying cache serialization."""
    _WARNED_PATHS.clear()
    disarm_xla_cache()


def _arm_xla_cache(root: str) -> None:
    """Best-effort: point jax's own persistent compilation cache at a
    subdir of ours, so the warm leg skips the XLA backend compile of the
    deserialized StableHLO too (the jax.export payload caches the
    *program*; this caches the *backend binary*)."""
    if _XLA_CACHE_ARMED:
        return
    try:
        xla_dir = os.path.join(root, "xla")
        os.makedirs(xla_dir, exist_ok=True)
        for name, val in (
            ("jax_compilation_cache_dir", xla_dir),
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                _XLA_CACHE_PRIOR.setdefault(name, getattr(jax.config, name))
                jax.config.update(name, val)
            except Exception as e:  # older jax: flag absent — size gating
                logger.debug("%s unavailable: %r", name, e)  # keep default
        _XLA_CACHE_ARMED.append(xla_dir)
    except Exception as e:  # pragma: no cover - config surface varies
        logger.warning("could not arm XLA persistent cache under %s: %r",
                       root, e)


def disarm_xla_cache() -> None:
    """Restore jax's persistent-cache config to its pre-arm values.

    The arm is global jax config: without this, every compile after the
    first ExportCache construction — anywhere in the process — keeps
    serializing backend binaries to disk."""
    for name, val in _XLA_CACHE_PRIOR.items():
        try:
            jax.config.update(name, val)
        except Exception as e:  # pragma: no cover - config surface varies
            logger.debug("could not restore %s: %r", name, e)
    _XLA_CACHE_PRIOR.clear()
    del _XLA_CACHE_ARMED[:]


class ExportCache:
    """Persistent on-disk cache of serialized ``jax.export`` artifacts.

    One JSON document per entry at ``<root>/<device_kind>/<digest>.json``:
    ``{schema, key, fingerprint, jax_version, device_kind, created, meta,
    payload}`` with the serialized Exported base64-encoded in ``payload``.
    The digest already hashes (fingerprint, device_kind, jax version); the
    stored fields are re-checked at load so a hand-copied or stale file
    can never restore under the wrong toolchain — it degrades to a miss.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.device_kind = current_device_kind()
        m = observe.metrics()
        self._hits = m.counter("dl4j_tpu_aot_cache_hits")
        self._misses = m.counter("dl4j_tpu_aot_cache_misses")
        self._export_h = m.histogram("dl4j_tpu_aot_export_seconds")
        _arm_xla_cache(self.root)

    @classmethod
    def from_env(cls) -> Optional["ExportCache"]:
        """The cache configured by ``$DL4J_TPU_COMPILE_CACHE``, or None —
        the whole AOT layer is inert unless the env var opts in."""
        root = os.environ.get(ENV_DIR)
        return cls(root) if root else None

    # ------------------------------------------------------------------ keys
    def digest(self, fingerprint: str, key: str) -> str:
        raw = "|".join((SCHEMA, fingerprint, key, self.device_kind,
                        jax.__version__))
        return hashlib.sha256(raw.encode()).hexdigest()

    def _path(self, digest: str) -> str:
        return os.path.join(self.root, self.device_kind, digest + ".json")

    # ------------------------------------------------------------------- i/o
    def store(self, fingerprint: str, key: str, exported,
              meta: Optional[Dict[str, Any]] = None) -> str:
        """Atomically persist one exported fn. Returns the entry path."""
        digest = self.digest(fingerprint, key)
        path = self._path(digest)
        doc = {
            "schema": SCHEMA,
            "key": key,
            "fingerprint": fingerprint,
            "jax_version": jax.__version__,
            "device_kind": self.device_kind,
            "created": time.time(),
            "meta": dict(meta or {}),
            "payload": base64.b64encode(exported.serialize()).decode("ascii"),
        }
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # atomic: readers never see a torn entry
        return path

    def _load_doc(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("schema") != SCHEMA:
                raise ValueError(f"schema {doc.get('schema')!r} != {SCHEMA}")
            return doc
        except FileNotFoundError:
            return None
        except (ValueError, TypeError, KeyError, OSError,
                json.JSONDecodeError) as e:
            if path not in _WARNED_PATHS:
                _WARNED_PATHS.add(path)
                logger.warning(
                    "ignoring corrupt AOT cache entry %s (%r) — "
                    "falling back to fresh compile", path, e)
            return None

    def load(self, fingerprint: str, key: str):
        """Deserialized ``Exported`` for (fingerprint, key), or None on
        miss/corrupt/stale — every non-hit degrades to a fresh compile."""
        path = self._path(self.digest(fingerprint, key))
        doc = self._load_doc(path)
        if doc is not None and (doc.get("jax_version") != jax.__version__
                                or doc.get("device_kind") != self.device_kind):
            # belt-and-braces: the digest already pins both, but a renamed
            # or hand-copied file must still never restore cross-toolchain
            if path not in _WARNED_PATHS:
                _WARNED_PATHS.add(path)
                logger.warning(
                    "ignoring stale AOT cache entry %s "
                    "(jax %s/%s, device %s/%s)", path,
                    doc.get("jax_version"), jax.__version__,
                    doc.get("device_kind"), self.device_kind)
            doc = None
        if doc is None:
            self._misses.inc()
            return None
        try:
            exported = jexport.deserialize(
                base64.b64decode(doc["payload"]))
        except Exception as e:
            if path not in _WARNED_PATHS:
                _WARNED_PATHS.add(path)
                logger.warning(
                    "ignoring undeserializable AOT cache entry %s (%r) — "
                    "falling back to fresh compile", path, e)
            self._misses.inc()
            return None
        self._hits.inc()
        return exported

    def entries(self) -> Iterator[Dict[str, Any]]:
        """Metadata of every readable entry for this device kind (payload
        omitted) — the scan warm_boot_net uses to find a net's steps."""
        d = os.path.join(self.root, self.device_kind)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return
        for name in names:
            if not name.endswith(".json"):
                continue
            doc = self._load_doc(os.path.join(d, name))
            if doc is not None and doc.get("jax_version") == jax.__version__:
                yield {k: doc[k] for k in
                       ("key", "fingerprint", "meta", "created")}

    def observe_export_seconds(self, seconds: float) -> None:
        """Feed the ``dl4j_tpu_aot_export_seconds`` histogram. Export
        sites call ``jax.export.export`` inline (graftshape's GS001 sees
        the jit→export flow in-module) and time around it."""
        self._export_h.observe(seconds)


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------


def restore_callable(exported, *, graph: str, key: str, hit: bool,
                     polymorphic: bool = False,
                     signature: Optional[str] = None):
    """Wrap a (de)serialized ``Exported`` back into a jitted callable and
    mark it for the recompile ledger.

    ``hit=True`` (restored from a populated cache) registers a restore
    event immediately — ``cache_hit``, attributed here — and marks the fn
    so every later dispatch-site registration also records ``cache_hit``:
    the warm leg's ledger shows zero fresh compiles for restored fns.
    ``hit=False`` (the exporting process itself installs the executable it
    just built, keeping both legs on the SAME compiled artifact for
    bit-identity) leaves the first dispatch to record ``first_compile`` as
    usual. ``polymorphic=True`` marks a symbolic-batch-dim export: later
    *new* signatures are served by the same executable, so they record
    ``cache_hit`` instead of ``new_shape``."""
    fn = jax.jit(exported.call)
    fn._aot_restored = bool(hit)
    if polymorphic:
        fn._aot_polymorphic = True
    if hit:
        observe.note_jit_signature(
            fn, graph=graph, key=key,
            signature=signature or f"aot[{key}]")
    return fn


def spec_of(tree, symbolic_axis0=None):
    """ShapeDtypeStruct pytree mirroring ``tree``; with ``symbolic_axis0``
    (a dim name, or an already-built symbolic dim when several argument
    trees must share ONE symbolic scope) every leaf's leading axis
    becomes that symbolic dim (batch)."""
    dim = symbolic_axis0
    if isinstance(dim, str):
        dim = jexport.symbolic_shape(dim)[0]

    def one(a):
        if a is None:
            return None
        a = jnp.asarray(a)
        shape = tuple(a.shape)
        if dim is not None and shape:
            shape = (dim,) + shape[1:]
        return jax.ShapeDtypeStruct(shape, a.dtype)

    return jax.tree.map(one, tree)


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


def fingerprint_tokens(*tokens) -> str:
    """sha256 over a flat token tuple — config-identity fingerprints for
    consumers without a GraphPlan (engine configs, net configs)."""
    h = hashlib.sha256()
    for t in tokens:
        h.update(repr(t).encode())
        h.update(b"|")
    return h.hexdigest()


def _tree_spec_tokens(tree) -> List[Tuple[str, Tuple[int, ...], str]]:
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [(jax.tree_util.keystr(path), tuple(np.shape(a)),
             np.dtype(getattr(a, "dtype", np.asarray(a).dtype)).name)
            for path, a in leaves]


def net_fingerprint(net) -> str:
    """Identity of an MLN-style net for cache keying: the layer config
    plus the full param/opt/net-state tree structure (shapes + dtypes).
    Weight VALUES are deliberately excluded — the executable is a
    function of structure, and params are runtime arguments."""
    conf = getattr(net, "conf", None)
    try:
        conf_token = conf.to_json()
    except AttributeError:
        conf_token = repr(conf)
    return fingerprint_tokens(
        "mln", conf_token,
        _tree_spec_tokens(net.params),
        _tree_spec_tokens(net.opt_state),
        _tree_spec_tokens(net.net_state))


# ---------------------------------------------------------------------------
# MLN train step (TrainingSupervisor.resume's restore consumer)
# ---------------------------------------------------------------------------


def _mln_raw_step(inner):
    """Raw-key adapter around a jitted train step: typed PRNG keys cannot
    cross the export boundary, so the exported computation takes uint32
    key data and rebuilds the key inside."""
    def raw_step(params, opt_state, net_state, step, key_data,
                 features, labels, fmask, lmask):
        return inner(params, opt_state, net_state, step,
                     jax.random.wrap_key_data(key_data),
                     features, labels, fmask, lmask)
    return raw_step


def _mln_wrapper(net, restored):
    """fit()-compatible step fn over a restored symbolic-batch executable.

    Converts the typed key to raw key data per call. The export covered
    the dominant signature (mask-free, fixed trailing dims, symbolic
    batch); a batch outside it — masks present, or different feature
    dims — permanently falls back to a freshly built plain jit, clearing
    the ledger markers so later events report honestly."""
    state: Dict[str, Any] = {"plain": None}

    def step(params, opt_state, net_state, step_i, key, x, y, fm, lm):
        if state["plain"] is None and fm is None and lm is None:
            try:
                return restored(params, opt_state, net_state, step_i,
                                jax.random.key_data(key), x, y, fm, lm)
            except (TypeError, ValueError):
                pass  # aval/structure mismatch — fall back below
        if state["plain"] is None:
            state["plain"] = net._make_train_step()
            step._aot_restored = False
            step._aot_polymorphic = False
        return state["plain"](params, opt_state, net_state, step_i, key,
                              x, y, fm, lm)

    return step


def export_train_step(net, features, labels,
                      cache: Optional[ExportCache] = None,
                      batch_symbol: str = "b") -> Optional[str]:
    """Export ``net``'s fused train step with a symbolic batch dim and
    persist it; install the SAME exported executable as the net's live
    step fn so this (populating) process and every warm restore run one
    artifact — bit-identity across legs by construction.

    Returns the cache entry path, or None when no cache is configured."""
    cache = cache or ExportCache.from_env()
    if cache is None:
        return None
    inner = net._make_train_step()
    jitted = jax.jit(_mln_raw_step(inner), donate_argnums=(0, 1, 2))
    kd = jax.random.key_data(net._key)
    b = jexport.symbolic_shape(batch_symbol)[0]  # ONE scope for x AND y
    specs = (
        spec_of(net.params), spec_of(net.opt_state), spec_of(net.net_state),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct(tuple(kd.shape), kd.dtype),
        spec_of(jnp.asarray(features), b),
        spec_of(jnp.asarray(labels), b),
        None, None,
    )
    fp = net_fingerprint(net)
    t0 = time.perf_counter()
    exported = jexport.export(jitted)(*specs)
    cache.observe_export_seconds(time.perf_counter() - t0)
    path = cache.store(fp, "train_step", exported, meta={
        "graph": "mln",
        "feature_dims": list(np.shape(features)[1:]),
        "label_dims": list(np.shape(labels)[1:]),
    })
    restored = restore_callable(exported, graph="mln", key="train_step",
                                hit=False, polymorphic=True)
    wrapper = _mln_wrapper(net, restored)
    wrapper._aot_restored = False
    wrapper._aot_polymorphic = True
    net._jit_cache["train_step"] = wrapper
    return path


def warm_boot_net(net, cache: Optional[ExportCache] = None) -> int:
    """Restore every cached step for this net's fingerprint into its
    ``_jit_cache`` — the training half of cold-start restore, called by
    ``TrainingSupervisor.resume`` in a fresh process. Zero fresh XLA
    compiles for restored steps: the first fit batch dispatches straight
    into the deserialized executable and the ledger records only
    ``cache_hit``. Returns the number of steps restored (0 when no cache
    is configured or nothing matches)."""
    cache = cache or ExportCache.from_env()
    if cache is None or not hasattr(net, "_jit_cache"):
        return 0
    fp = net_fingerprint(net)
    restored = 0
    for entry in cache.entries():
        if entry["fingerprint"] != fp or entry["key"] in net._jit_cache:
            continue
        exported = cache.load(fp, entry["key"])
        if exported is None:
            continue
        fn = restore_callable(exported, graph=entry["meta"].get("graph", "mln"),
                              key=entry["key"], hit=True, polymorphic=True)
        wrapper = _mln_wrapper(net, fn)
        wrapper._aot_restored = True
        wrapper._aot_polymorphic = True
        # the restore event was recorded on the inner fn; mirror the seen
        # set onto the wrapper fit() registers against
        wrapper._obs_sigs = set(fn._obs_sigs)
        net._jit_cache[entry["key"]] = wrapper
        restored += 1
    if restored:
        observe.log_event("aot_warm_boot", consumer="mln", restored=restored)
    return restored


def maybe_warm_boot_net(net) -> int:
    """Env-gated :func:`warm_boot_net` — inert without the cache dir."""
    if not os.environ.get(ENV_DIR):
        return 0
    return warm_boot_net(net)


# ---------------------------------------------------------------------------
# SameDiff whole-graph exec
# ---------------------------------------------------------------------------


def samediff_fingerprint(sd, outputs: Tuple[str, ...]) -> str:
    """Plan-identity fingerprint for a SameDiff output set: the optimized
    GraphPlan hash when the optimizer is on (autodiff/optimize.py
    ``GraphPlan.fingerprint``), else the raw recording's node/var
    structure; either way joined with the VARIABLE argument specs."""
    plan = sd._graph_plan(tuple(outputs))
    if plan is not None:
        plan_token = plan.fingerprint()
    else:
        plan_token = fingerprint_tokens(
            [(n.op, tuple(n.inputs),
              sorted((k, repr(v)) for k, v in n.kwargs.items()),
              tuple(n.outputs)) for n in sd._needed_nodes(tuple(outputs))],
            tuple(outputs))
    var_specs = sorted(
        (n, tuple(np.shape(a)), np.dtype(a.dtype).name)
        for n, a in sd._arrays.items()
        if sd._vars[n].vtype != "CONSTANT")
    return fingerprint_tokens("samediff", plan_token, var_specs)


def export_exec(sd, feeds: Dict[str, Any], outputs,
                cache: Optional[ExportCache] = None,
                batch_symbol: Optional[str] = "b") -> Optional[str]:
    """Export a SameDiff output set's whole-graph exec fn (symbolic batch
    over the feeds) and persist it; install the exported executable as the
    live exec fn (see :func:`export_train_step` for the bit-identity
    rationale). Returns the entry path, or None without a cache."""
    if isinstance(outputs, str):
        outputs = [outputs]
    outputs = tuple(outputs)
    cache = cache or ExportCache.from_env()
    if cache is None:
        return None
    fn = sd._exec_fn(outputs)  # CompiledGraph (builds + caches the plan)
    var_arrays = sd._var_arrays(fn)
    feed_arrays = {k: jnp.asarray(v) for k, v in feeds.items()}
    specs = (spec_of(var_arrays),
             spec_of(feed_arrays, batch_symbol))
    fp = samediff_fingerprint(sd, outputs)
    t0 = time.perf_counter()
    exported = fn.export(*specs)  # CompiledGraph.export — jexport in-module
    cache.observe_export_seconds(time.perf_counter() - t0)
    path = cache.store(fp, "exec", exported, meta={
        "graph": "samediff", "outputs": list(outputs)})
    install_exec(sd, exported, outputs, fn_const_names=fn._const_names,
                 hit=False)
    return path


class _RestoredGraph:
    """Stands where ``CompiledGraph`` would in ``SameDiff._jit_cache``:
    callable on (var_arrays, feeds), carries ``_const_names`` (the
    VARIABLE/feed split) and ``stats`` (None — the restore never re-ran
    the optimizer, so there are no fresh timings to report)."""

    def __init__(self, call, const_names):
        self._call = call
        self._const_names = frozenset(const_names)
        self.stats = None
        self._aot_restored = getattr(call, "_aot_restored", False)
        self._aot_polymorphic = getattr(call, "_aot_polymorphic", False)
        self._obs_sigs = set(getattr(call, "_obs_sigs", ()))

    def __call__(self, var_arrays, feeds):
        return self._call(var_arrays, feeds)


def install_exec(sd, exported, outputs, *, fn_const_names=None,
                 hit: bool = True):
    """Install a (de)serialized exec executable into ``sd._jit_cache`` so
    ``output()`` dispatches straight into it. Returns the shim."""
    outputs = tuple([outputs] if isinstance(outputs, str) else outputs)
    if fn_const_names is None:
        plan = sd._graph_plan(outputs)
        const_names = set(sd._const_env())
        if plan is not None:
            const_names |= set(plan.extra_consts)
    else:
        const_names = set(fn_const_names)
    fn = restore_callable(exported, graph="samediff", key="exec", hit=hit,
                          polymorphic=True)
    shim = _RestoredGraph(fn, const_names)
    cache_key = ("exec", outputs, bool(sd.optimize), sd._effective_passes())
    sd._jit_cache[cache_key] = shim
    return shim


def warm_boot_samediff(sd, outputs,
                       cache: Optional[ExportCache] = None) -> bool:
    """Restore a cached exec for ``(sd, outputs)`` if present. Returns
    True on restore — the next ``output()`` call runs the deserialized
    executable with only ``cache_hit`` ledger events."""
    outputs = tuple([outputs] if isinstance(outputs, str) else outputs)
    cache = cache or ExportCache.from_env()
    if cache is None:
        return False
    fp = samediff_fingerprint(sd, outputs)
    exported = cache.load(fp, "exec")
    if exported is None:
        return False
    install_exec(sd, exported, outputs, hit=True)
    observe.log_event("aot_warm_boot", consumer="samediff",
                      outputs=list(outputs))
    return True
