"""SameDiff graph optimizer — pre-trace pass pipeline (docs/OPTIMIZER.md).

The paper's core bet is whole-graph compilation: one ``jax.jit`` trace per
requested output set instead of the reference's per-op interpreter. But the
importers (imports/ir.py) emit every source node verbatim, so BERT-scale
ONNX/TF graphs carry dead branches, per-layer duplicated subexpressions
(attention-mask expansion chains), foldable constant chains, and no-op
Identity/Dropout/Reshape nodes straight into the trace — inflating both
trace time and XLA compile time. This module is the standard fix (XLA and
TVM/Relay both lead with the same trio): shrink the node graph BEFORE
tracing.

Passes (each independently sound; pipeline loops to a fixpoint):

``dce``        dead-code elimination backwards from the requested outputs.
``fold``       constant folding: a node whose inputs are all CONSTANT-derived
               (never VARIABLE — training updates must not invalidate folds)
               is evaluated eagerly once and its outputs become plan-local
               constants. Respects the const-invalidation contract: plans are
               cached in ``SameDiff._jit_cache``, which ``set_arr`` on a
               CONSTANT and every graph mutation already clear.
``cse``        common-subexpression elimination keyed on
               (op, input ids, canonical kwargs); later duplicates alias the
               first occurrence's outputs.
``algebraic``  identity cleanup: identity nodes, transpose∘transpose
               (cancelled or composed), reshape∘reshape fusion,
               reshape-to-same-shape, and x*1 / x+0 / x-0 / x/1 / x**1 strips
               (only when the surviving operand's dtype provably absorbs the
               promotion — see ``_infer_dtypes``).
``fusion``     rewrite imported subgraphs onto registry fast paths:
               matmul→scale→(+mask)→softmax→matmul becomes ONE
               ``dot_product_attention`` node (the Pallas flash dispatch
               applies), matmul+bias(+activation) becomes
               ``fused_matmul_bias_act``. Opt-out: ``DL4J_TPU_FUSION=0``.
``autocast``   OPT-IN (``DL4J_TPU_AUTOCAST=bf16`` or an explicit
               ``passes=`` entry): bf16 inputs for matmul/conv-class nodes
               with an f32 interface (cast back at the node output);
               softmax/layernorm/losses stay f32.

The result is a :class:`GraphPlan` — an optimized node list, extra folded
constants, and an alias map — which ``SameDiff._interpret`` executes instead
of the raw recording. The graph itself (``sd._nodes``) is NEVER mutated:
serde, ``summary()``, and later mutation all see the full recording.

Instrumentation: :class:`OptimizeStats` carries per-pass node counts and, on
the ``output()`` execution path (via :class:`CompiledGraph`), the measured
trace seconds and XLA compile seconds — surfaced as
``SameDiff.last_compile_stats`` and by ``bench.py`` (BENCH_MODEL=
graph_compile / ``make bench-compile``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

PASS_ORDER: Tuple[str, ...] = ("dce", "fold", "cse", "algebraic", "fusion")

# opt-in passes: valid names for `passes=`, but never part of the default
# pipeline. "autocast" (bf16 matmul inputs / f32 interface) changes VALUES
# within bf16 tolerance, so it must be asked for — via passes= or
# DL4J_TPU_AUTOCAST=bf16.
OPTIONAL_PASSES: Tuple[str, ...] = ("autocast",)


_ENV_ON = ("1", "on", "true", "yes")
_ENV_OFF = ("", "0", "off", "false", "none", "no")
_AUTOCAST_ON = _ENV_ON + ("bf16", "bfloat16")
_AUTOCAST_OFF = _ENV_OFF + ("f32", "float32")

# warn-once guards: default_passes() runs on EVERY cache-key computation
# (_effective_passes per output/grad/train step) — an env typo must log a
# single line, not one per step
_WARNED_ENVS: set = set()


def _env_warn_once(var: str, val: str, on_values) -> None:
    import logging

    if (var, val) not in _WARNED_ENVS:
        _WARNED_ENVS.add((var, val))
        logging.getLogger(__name__).warning(
            "%s=%r not recognized (on: %s); using the default", var, val,
            "/".join(v for v in on_values if v))


def default_passes() -> Tuple[str, ...]:
    """The pipeline the env asks for: PASS_ORDER, minus fusion under
    DL4J_TPU_FUSION=0/off/false, plus autocast under
    DL4J_TPU_AUTOCAST=bf16. Unrecognized values keep the default and log
    one warning — a silent env typo (fp16, ofF) would otherwise be
    invisible forever (the cache key matches the default plan)."""
    import os

    enabled = [p for p in PASS_ORDER]
    fu = os.environ.get("DL4J_TPU_FUSION", "1").strip().lower() or "1"
    if fu in _ENV_OFF:
        enabled.remove("fusion")
    elif fu not in _ENV_ON:
        _env_warn_once("DL4J_TPU_FUSION", fu, _ENV_OFF)
    ac = os.environ.get("DL4J_TPU_AUTOCAST", "").strip().lower()
    if ac in _AUTOCAST_ON:
        enabled.append("autocast")
    elif ac not in _AUTOCAST_OFF:
        _env_warn_once("DL4J_TPU_AUTOCAST", ac, _AUTOCAST_ON)
    return tuple(enabled)

# folded outputs larger than this (elements) stay in the graph: XLA would
# bake them anyway, but materializing giants at plan time trades trace
# savings for host memory with no wall-clock win
FOLD_SIZE_LIMIT = 1 << 24

_MAX_ITERS = 10  # fixpoint safety cap; real graphs settle in 2-3


@dataclasses.dataclass
class OptimizeStats:
    """Per-compile instrumentation (SameDiff.last_compile_stats)."""

    nodes_before: int = 0
    nodes_after: int = 0
    # pass name -> {"before": n at first application, "after": n at last,
    #               "removed": cumulative node delta across iterations}
    passes: Dict[str, Dict[str, int]] = dataclasses.field(default_factory=dict)
    optimize_seconds: float = 0.0
    # populated by CompiledGraph on the output() path (AOT lower/compile)
    trace_seconds: Optional[float] = None
    compile_seconds: Optional[float] = None
    # graftcheck pass-invariance runs (docs/ANALYSIS.md): how many times
    # the interface shapes/dtypes were re-verified between passes
    invariant_checks: int = 0
    # fusion-tier hit counts: {"attention": n, "epilogue": n,
    # "autocast_casts": n} — docs/OPTIMIZER.md § Fusion tier
    fusions: Dict[str, int] = dataclasses.field(default_factory=dict)

    def record_fusion(self, kind: str, n: int = 1) -> None:
        self.fusions[kind] = self.fusions.get(kind, 0) + n

    def record_pass(self, name: str, before: int, after: int) -> None:
        entry = self.passes.setdefault(
            name, {"before": before, "after": after, "removed": 0})
        entry["after"] = after
        entry["removed"] += before - after

    @property
    def removed(self) -> int:
        return self.nodes_before - self.nodes_after

    def to_dict(self) -> Dict[str, Any]:
        return {"nodes_before": self.nodes_before,
                "nodes_after": self.nodes_after,
                "removed": self.removed,
                "passes": {k: dict(v) for k, v in self.passes.items()},
                "optimize_seconds": round(self.optimize_seconds, 4),
                "trace_seconds": self.trace_seconds,
                "compile_seconds": self.compile_seconds,
                "invariant_checks": self.invariant_checks,
                "fusions": dict(self.fusions)}


class GraphPlan:
    """Optimized execution plan for one requested-output set."""

    __slots__ = ("nodes", "extra_consts", "alias", "outputs", "stats")

    def __init__(self, nodes, extra_consts, alias, outputs, stats):
        self.nodes = nodes
        self.extra_consts = extra_consts  # folded values, merged into env
        self.alias = alias                # removed-output name -> survivor
        self.outputs = outputs
        self.stats = stats

    def resolve(self, name: str) -> str:
        return _resolve(self.alias, name)

    def fingerprint(self) -> str:
        """Canonical sha256 over everything that determines the compiled
        computation: the optimized node list (op, inputs, sorted kwargs,
        outputs), the folded extra constants (shape/dtype/value digest),
        the alias map, and the requested outputs. Two plans with equal
        fingerprints lower to the same computation — this is the
        plan-identity half of the persistent export-cache key
        (autodiff/export.py; the other halves are device_kind and the
        jax version)."""
        h = hashlib.sha256()
        for n in self.nodes:
            h.update(repr((n.op, tuple(n.inputs),
                           sorted((k, repr(v)) for k, v in n.kwargs.items()),
                           tuple(n.outputs))).encode())
        for name in sorted(self.extra_consts):
            a = np.asarray(self.extra_consts[name])
            h.update(repr((name, a.shape, a.dtype.name)).encode())
            h.update(a.tobytes())
        h.update(repr(sorted(self.alias.items())).encode())
        h.update(repr(tuple(self.outputs)).encode())
        return h.hexdigest()


def _resolve(alias: Dict[str, str], name: str) -> str:
    seen = []
    while name in alias:
        seen.append(name)
        name = alias[name]
    for s in seen:  # path compression keeps chains O(1) amortized
        alias[s] = name
    return name


def _copy_node(n):
    return type(n)(n.op, list(n.inputs), dict(n.kwargs), list(n.outputs))


def _rewrite_inputs(nodes, alias: Dict[str, str]) -> bool:
    changed = False
    for n in nodes:
        for i, name in enumerate(n.inputs):
            r = _resolve(alias, name)
            if r != name:
                n.inputs[i] = r
                changed = True
    return changed


# ---------------------------------------------------------------------------
# dce
# ---------------------------------------------------------------------------


def _dce(nodes, outputs: Sequence[str], alias: Dict[str, str]):
    needed = {_resolve(alias, o) for o in outputs}
    keep = []
    for n in reversed(nodes):
        if any(o in needed for o in n.outputs):
            keep.append(n)
            needed.update(n.inputs)
    keep.reverse()
    return keep, len(keep) != len(nodes)


# ---------------------------------------------------------------------------
# fold
# ---------------------------------------------------------------------------


def _fold(nodes, const_vals: Dict[str, Any], resolve_op, local_ops,
          size_limit: int, precision_policy: str):
    from deeplearning4j_tpu.nn import dtype as DT

    out_nodes, changed = [], False
    with DT.precision_scope(precision_policy):
        for n in nodes:
            if n.op in local_ops or any(i not in const_vals for i in n.inputs):
                out_nodes.append(n)
                continue
            try:
                fn = resolve_op(n.op)
                res = fn(*[const_vals[i] for i in n.inputs], **n.kwargs)
            except Exception:
                # not statically evaluable (shape mismatch under fold,
                # helper needing a device feature, ...) — leave it traced
                out_nodes.append(n)
                continue
            vals = [res] if len(n.outputs) == 1 else list(res)
            if (len(vals) != len(n.outputs)
                    or any(np.size(v) > size_limit for v in vals)):
                out_nodes.append(n)
                continue
            for name, val in zip(n.outputs, vals):
                const_vals[name] = val
            changed = True
    return out_nodes, changed


# ---------------------------------------------------------------------------
# cse
# ---------------------------------------------------------------------------


def _canon_kwargs(kwargs: Dict[str, Any]):
    def c(v):
        if isinstance(v, (list, tuple)):
            return tuple(c(x) for x in v)
        if isinstance(v, dict):
            # repr-sort the keys: mixed-type keys (int vs str) are
            # unorderable and would abort the whole pass pipeline
            return tuple(sorted(((k, c(x)) for k, x in v.items()),
                                key=lambda kv: repr(kv[0])))
        if isinstance(v, np.ndarray):
            return ("__nd", v.shape, str(v.dtype), v.tobytes())
        return v

    # Exclude-from-CSE fallback must cover EVERYTHING canonicalization can
    # throw, not just TypeError: ndarray-like values with ambiguous
    # truthiness raise ValueError inside sorted(), device arrays can raise
    # their own errors from repr/compare, self-referential containers hit
    # RecursionError. Any failure means "this node is not CSE-able",
    # never "the optimizer pipeline dies".
    try:
        key = tuple(sorted((k, c(v)) for k, v in kwargs.items()))
        hash(key)
    except Exception:
        return None  # not canonicalizable/hashable — not CSE-able
    return key


def _cse(nodes, alias: Dict[str, str], local_ops):
    seen: Dict[Any, Any] = {}
    out_nodes, changed = [], False
    for n in nodes:
        if n.op in local_ops:  # opaque control-flow closures: never merge
            out_nodes.append(n)
            continue
        ck = _canon_kwargs(n.kwargs)
        if ck is None:
            out_nodes.append(n)
            continue
        key = (n.op, tuple(n.inputs), ck)
        prev = seen.get(key)
        if prev is None:
            seen[key] = n
            out_nodes.append(n)
        else:
            for o, po in zip(n.outputs, prev.outputs):
                alias[o] = po
            changed = True
    return out_nodes, changed


# ---------------------------------------------------------------------------
# algebraic
# ---------------------------------------------------------------------------

# unary ops whose output dtype equals a floating input's dtype
_DTYPE_PRESERVING_UNARY = frozenset([
    "identity", "neg", "abs", "exp", "log", "log1p", "sqrt", "rsqrt",
    "square", "sign", "floor", "ceil", "round", "sin", "cos", "tan",
    "tanh", "sinh", "cosh", "erf", "relu", "relu6", "elu", "selu", "gelu",
    "sigmoid", "softplus", "softsign", "swish", "mish", "leakyrelu",
    "softmax", "log_softmax", "reshape", "transpose", "permute",
    "expand_dims", "squeeze", "tile", "reduce_sum", "reduce_mean",
    "reduce_max", "reduce_min", "zeros_like", "ones_like",
])
_DTYPE_PROMOTING_BINARY = frozenset(
    ["add", "sub", "mul", "div", "pow", "maximum", "minimum", "mmul"])


def _infer_dtypes(nodes, const_vals, seed_dtypes):
    """Best-effort forward dtype propagation (floating dtypes only). A name
    absent from the result means "unknown" — identity strips then bail."""
    import jax.numpy as jnp

    dt: Dict[str, Any] = dict(seed_dtypes)
    for name, v in const_vals.items():
        vd = getattr(v, "dtype", None)
        if vd is not None:
            dt[name] = np.dtype(vd)
    for n in nodes:
        ins = [dt.get(i) for i in n.inputs]
        if n.op == "cast":
            try:
                dt[n.outputs[0]] = np.dtype(n.kwargs.get("dtype"))
            except TypeError:
                pass
        elif (n.op in _DTYPE_PRESERVING_UNARY and ins and ins[0] is not None
                and np.issubdtype(ins[0], np.inexact)):
            dt[n.outputs[0]] = ins[0]
        elif (n.op in _DTYPE_PROMOTING_BINARY and len(ins) >= 2
                and all(d is not None and np.issubdtype(d, np.inexact)
                        for d in ins[:2])):
            dt[n.outputs[0]] = np.dtype(jnp.promote_types(ins[0], ins[1]))
    return dt


def _scalar_const(const_vals, name):
    """0-d (or absent) → (value, dtype) for identity matching; None if the
    constant is non-scalar (a broadcast would change the result shape)."""
    v = const_vals.get(name)
    if v is None:
        return None
    arr = np.asarray(v)
    if arr.ndim != 0:
        return None
    try:
        return float(arr), arr.dtype
    except (TypeError, ValueError):
        return None


# op -> (identity value, which operand positions may carry it)
_BINARY_IDENTITIES = {"mul": (1.0, (0, 1)), "add": (0.0, (0, 1)),
                      "sub": (0.0, (1,)), "div": (1.0, (1,)),
                      "pow": (1.0, (1,))}


def _algebraic(nodes, const_vals, var_shapes, seed_dtypes,
               alias: Dict[str, str], local_ops):
    import jax.numpy as jnp

    dtypes = _infer_dtypes(nodes, const_vals, seed_dtypes)
    producer = {o: n for n in nodes for o in n.outputs}
    out_nodes, changed = [], False

    def known_shape(name):
        s = var_shapes.get(name)
        if s is not None:
            return s
        v = const_vals.get(name)
        return tuple(np.shape(v)) if v is not None else None

    def perm_of(axes, rank):
        return (tuple(reversed(range(rank))) if axes is None
                else tuple(int(a) for a in axes))

    for n in nodes:
        if n.op in local_ops:
            out_nodes.append(n)
            continue

        if n.op == "identity" and len(n.outputs) == 1:
            alias[n.outputs[0]] = n.inputs[0]
            changed = True
            continue

        if n.op == "transpose" and len(n.inputs) == 1:
            inner = producer.get(n.inputs[0])
            if inner is not None and inner.op == "transpose":
                a_out = n.kwargs.get("axes")
                a_in = inner.kwargs.get("axes")
                rank = (len(a_out) if a_out is not None
                        else len(a_in) if a_in is not None else None)
                if a_out is None and a_in is None:
                    # reverse twice = identity at any rank
                    alias[n.outputs[0]] = inner.inputs[0]
                    changed = True
                    continue
                if rank is not None:
                    p_in = perm_of(a_in, rank)
                    p_out = perm_of(a_out, rank)
                    combined = tuple(p_in[k] for k in p_out)
                    if combined == tuple(range(rank)):
                        alias[n.outputs[0]] = inner.inputs[0]
                        changed = True
                        continue
                    if n.inputs[0] != inner.inputs[0] or \
                            n.kwargs.get("axes") != combined:
                        n.inputs[0] = inner.inputs[0]
                        n.kwargs["axes"] = combined
                        changed = True
            out_nodes.append(n)
            continue

        if n.op == "reshape" and len(n.inputs) == 1:
            target = n.kwargs.get("shape")
            inner = producer.get(n.inputs[0])
            if inner is not None and inner.op == "reshape":
                # reshape∘reshape ≡ the outer reshape (row-major order)
                n.inputs[0] = inner.inputs[0]
                changed = True
            src = known_shape(n.inputs[0])
            if (target is not None and src is not None
                    and all(int(d) >= 0 for d in target)
                    and tuple(int(d) for d in target) == tuple(src)):
                alias[n.outputs[0]] = n.inputs[0]
                changed = True
                continue
            out_nodes.append(n)
            continue

        ident = _BINARY_IDENTITIES.get(n.op)
        if ident is not None and len(n.inputs) == 2:
            value, positions = ident
            stripped = False
            for pos in positions:
                sc = _scalar_const(const_vals, n.inputs[pos])
                if sc is None or sc[0] != value:
                    continue
                other = n.inputs[1 - pos]
                dt_other = dtypes.get(other)
                # only strip when the surviving operand's dtype provably
                # absorbs the promotion — else x(bf16)+0.0(f32) would
                # silently change the result dtype/precision
                if dt_other is None or not np.issubdtype(dt_other, np.inexact):
                    continue
                if np.dtype(jnp.promote_types(dt_other, sc[1])) != dt_other:
                    continue
                alias[n.outputs[0]] = other
                changed = True
                stripped = True
                break
            if stripped:
                continue

        out_nodes.append(n)
    return out_nodes, changed


# ---------------------------------------------------------------------------
# fusion (docs/OPTIMIZER.md § Fusion tier)
#
# Pattern-match imported subgraphs onto registry fast paths:
#   * attention: matmul → scale → (+additive mask) → softmax → matmul
#     becomes ONE `dot_product_attention` node, so the shape-aware Pallas
#     flash dispatch (ops/pallas_attention.py, PR 7) applies to imported
#     ONNX/TF graphs — which otherwise execute the verbatim softmax(QKᵀ)V
#     chain forever (ROADMAP item 3).
#   * epilogue: matmul + bias (+ relu/tanh/gelu or the decomposed erf-gelu
#     chain exporters emit) becomes `fused_matmul_bias_act` (Pallas fused
#     epilogue on TPU, exact same op chain via XLA elsewhere).
#
# Soundness: a rewrite only fires when the shape/dtype evidence (from the
# graftcheck abstract interpreter over bound arrays, placeholder decls and
# the const env) proves the pattern — scale value matches 1/sqrt(head_dim),
# softmax normalizes the last axis, the mask chain is the standard
# (1 - mask) * -big penalty, and every interior tensor is consumed only
# inside the pattern. Anything else is left verbatim; the per-pass
# invariant checker then re-verifies the fused graph via the first-class
# analysis rules for the fused ops.
# ---------------------------------------------------------------------------

_FUSION_PASSTHROUGH = frozenset(["identity", "dropout_graph"])

# epilogue activations matched as a single node (op name -> activation kwarg)
_EPILOGUE_ACTS = {"relu": "relu", "tanh": "tanh", "gelu": "gelu"}

_SQRT2 = float(np.sqrt(np.float32(2.0)))


class _Namer:
    """Fresh names for synthesized nodes, collision-checked per pipeline."""

    def __init__(self, taken):
        self._taken = taken
        self._n = 0

    def fresh(self, tag: str) -> str:
        while True:
            self._n += 1
            name = f"__opt_{tag}_{self._n}"
            if name not in self._taken:
                self._taken.add(name)
                return name


def _abstract_avals(nodes, const_vals, var_shapes, seed_dtypes, input_avals,
                    local_ops):
    """Shape/dtype evidence for the fusion/autocast matchers — the same
    seeding the invariant checker uses, walked once over the current list."""
    from deeplearning4j_tpu import analysis as _an

    avals: Dict[str, Any] = {}
    for n, s in (var_shapes or {}).items():
        avals[n] = _an.AVal(shape=tuple(s), dtype=(seed_dtypes or {}).get(n))
    for n, dt in (seed_dtypes or {}).items():
        if n not in avals:
            avals[n] = _an.AVal(dtype=dt)
    for n, a in (input_avals or {}).items():
        avals.setdefault(n, a)
    for n, v in const_vals.items():
        avals[n] = _an.AVal.of_array(v, keep_value=np.size(v) <= 4096)
    _an.infer_nodes(list(enumerate(nodes)), avals, local_ops,
                    graph_name="<fusion>", findings=[])
    return avals


def _close(a: float, b: float, rtol: float = 1e-5) -> bool:
    return abs(a - b) <= rtol * max(abs(a), abs(b), 1e-30)


def _identity_perm(perm) -> bool:
    return tuple(perm) == tuple(range(len(perm)))


def _norm_perm(axes, rank):
    if axes is None:
        return tuple(reversed(range(rank)))
    return tuple(int(a) % rank for a in axes)


class _GraphView:
    """Shared lookup state for one fusion-pass application."""

    def __init__(self, nodes, outputs, alias, const_vals, avals,
                 local_ops=None):
        self.nodes = nodes
        self.const_vals = const_vals
        self.avals = avals
        self.local_ops = local_ops or {}
        self.producer: Dict[str, Tuple[int, Any]] = {}
        self.consumers: Dict[str, int] = {}
        # name -> [(idx, node), ...] distinct consumer NODES, in order
        self._consumer_nodes: Dict[str, List[Tuple[int, Any]]] = {}
        for idx, n in enumerate(nodes):
            for o in n.outputs:
                self.producer[o] = (idx, n)
            for i in n.inputs:
                self.consumers[i] = self.consumers.get(i, 0) + 1
                lst = self._consumer_nodes.setdefault(i, [])
                if not lst or lst[-1][0] != idx:
                    lst.append((idx, n))
        self.external = {_resolve(alias, o) for o in outputs}

    def interior(self, name: str) -> bool:
        """name is consumed exactly once and is not a requested output —
        the precondition for removing its producer."""
        return self.consumers.get(name, 0) == 1 and name not in self.external

    def single_consumer(self, name: str):
        """(idx, node) of the unique consumer of name, or None."""
        if not self.interior(name):
            return None
        lst = self._consumer_nodes.get(name)
        return lst[0] if lst else None

    def consumer_nodes(self, name: str):
        """All distinct consumer (idx, node) pairs of name, in order."""
        return self._consumer_nodes.get(name, [])

    def is_op(self, node, *names) -> bool:
        """node matches one of the CATALOG ops ``names`` — an
        instance-local op shadowing a catalog name (resolution order is
        local-first) has arbitrary semantics and must never pattern-match."""
        return node.op in names and node.op not in self.local_ops

    def scalar(self, name: str):
        """(value, dtype) for any SIZE-1 constant — unlike the algebraic
        strips' 0-d-only ``_scalar_const``, rank does not matter here: the
        fusion rewrite removes the whole chain, so a (1,)-shaped ONNX
        scalar (the wire format's usual encoding) is as good as a 0-d."""
        v = self.const_vals.get(name)
        if v is None:
            return None
        arr = np.asarray(v)
        if arr.size != 1:
            return None
        try:
            return float(arr.reshape(())), arr.dtype
        except (TypeError, ValueError):
            return None

    def aval(self, name: str):
        return self.avals.get(name)


def _match_mask_penalty(gv: _GraphView, name: str):
    """Recognize the additive attention-mask penalty chains importers emit.

    Returns ``("tensor", mask_name, expand_axes)`` for the standard
    ``(1 - mask) * -big`` key-padding chain (``expand_axes``: expand_dims
    axes applied AFTER the mul, to mirror onto the mask),
    ``("causal", None, None)`` for a constant lower-triangular 0/-big
    matrix, or None."""
    # constant additive mask: causal tril pattern (decoder imports)
    v = gv.const_vals.get(name)
    if v is not None:
        arr = np.asarray(v)
        sq = arr.reshape(arr.shape[-2:]) if arr.ndim > 2 and \
            all(d == 1 for d in arr.shape[:-2]) else arr
        if sq.ndim == 2 and sq.shape[0] == sq.shape[1] and sq.shape[0] > 1:
            tril = np.tril(np.ones(sq.shape, bool))
            if np.all(sq[tril] == 0.0) and np.all(sq[~tril] <= -1e3):
                return ("causal", None, None)
        return None
    expand_axes = []
    prod = gv.producer.get(name)
    while prod is not None and gv.is_op(prod[1], "expand_dims"):
        expand_axes.append(prod[1].kwargs.get("axis", 0))
        name = prod[1].inputs[0]
        prod = gv.producer.get(name)
    if prod is None or not gv.is_op(prod[1], "mul") \
            or len(prod[1].inputs) != 2:
        return None
    mul = prod[1]
    for pos in (0, 1):
        sc = gv.scalar(mul.inputs[pos])
        if sc is None or sc[0] > -1e3:
            continue
        inv = gv.producer.get(mul.inputs[1 - pos])
        if inv is None or not gv.is_op(inv[1], "sub") \
                or len(inv[1].inputs) != 2:
            continue
        one = gv.scalar(inv[1].inputs[0])
        if one is None or one[0] != 1.0:
            continue
        mask_name = inv[1].inputs[1]
        a = gv.aval(mask_name)
        # mask contract: a float/bool BINARY attend mask. The matched
        # (1 - mask) * -big chain is the exporters' encoding of a 0/1
        # key-padding mask; the rewrite turns it into the fused op's
        # where-style mask operand, which agrees with the additive penalty
        # exactly for 0/1 values (ONNX Runtime's attention fuser makes the
        # same binary-mask assumption). Fractional masks are outside the
        # pattern: provably-non-binary CONSTANT masks are rejected here,
        # runtime-fed masks are 0/1 by the documented contract
        # (docs/OPTIMIZER.md § Fusion tier; opt-out DL4J_TPU_FUSION=0).
        # Unknown or integral dtypes are a pattern miss — leave verbatim.
        if a is None or a.dtype is None:
            return None
        if not (np.issubdtype(a.dtype, np.floating)
                or a.dtype == np.dtype(bool)):
            return None
        mv = gv.const_vals.get(mask_name)
        if mv is not None:
            arr = np.asarray(mv)
            if not np.all((arr == 0) | (arr == 1)):
                return None
        return ("tensor", mask_name, list(reversed(expand_axes)))
    return None


def _peel_transposed_k(gv: _GraphView, kt_name: str, namer: _Namer):
    """scores = q @ B requires B = kᵀ (last two axes swapped). Recover k:
    if B is a transpose node, compose its perm with a last-two swap — the
    result is either the transpose's own input (plain kᵀ) or one
    synthesized transpose (the composed head-split form the algebraic pass
    produces). Returns (k_name, synth_node_or_None, kt_idx_or_None,
    k_shape) or None."""
    prod = gv.producer.get(kt_name)
    if prod is None or not gv.is_op(prod[1], "transpose") \
            or len(prod[1].inputs) != 1:
        return None
    kt_idx, kt = prod
    axes = kt.kwargs.get("axes")
    src_aval = gv.aval(kt.inputs[0])
    rank = len(axes) if axes is not None else \
        (src_aval.rank if src_aval is not None else None)
    if rank is None or rank < 2:
        return None
    perm = _norm_perm(axes, rank)
    k_perm = perm[:-2] + (perm[-1], perm[-2])
    src_shape = src_aval.shape if src_aval is not None else None
    k_shape = (tuple(src_shape[p] for p in k_perm)
               if src_shape is not None and len(src_shape) == rank else None)
    if _identity_perm(k_perm):
        return kt.inputs[0], None, kt_idx, k_shape
    synth = _Node_like(kt, "transpose", [kt.inputs[0]], {"axes": k_perm},
                       [namer.fresh("k")])
    return synth.outputs[0], synth, kt_idx, k_shape


def _Node_like(template, op, inputs, kwargs, outputs):
    return type(template)(op, list(inputs), dict(kwargs), list(outputs))


def _try_attention(gv: _GraphView, ctx_idx: int, ctx, namer: _Namer):
    """Match one attention block ending at ``ctx = mmul(probs, v)``.

    Returns ``(removed_idxs, synth_nodes, fused_node, mask_pending)`` or
    None. ``mask_pending`` is None or ``(mask_name, expand_axes)``: a
    tensor mask the CALLER appends to the fused node's inputs — after the
    claim check accepts the match — synthesizing (and caching) any
    expand_dims mirror chain only for matches that actually apply."""
    if not gv.is_op(ctx, "mmul") or len(ctx.inputs) != 2 or \
            ctx.kwargs.get("transpose_a") or ctx.kwargs.get("transpose_b"):
        return None
    removed = {ctx_idx}
    synth: List[Any] = []

    # probs side: optional identity/dropout passthroughs over the softmax
    p_name, v_name = ctx.inputs
    while True:
        prod = gv.producer.get(p_name)
        if prod is None:
            return None
        if gv.is_op(prod[1], *_FUSION_PASSTHROUGH) \
                and len(prod[1].outputs) == 1:
            if not gv.interior(prod[1].outputs[0]):
                return None
            removed.add(prod[0])
            p_name = prod[1].inputs[0]
            continue
        break
    sm_idx, sm = prod
    if not gv.is_op(sm, "softmax") or not gv.interior(sm.outputs[0]):
        return None
    axis = int(sm.kwargs.get("axis", -1))
    sm_aval = gv.aval(sm.inputs[0])
    rank = sm_aval.rank if sm_aval is not None else None
    if axis != -1 and (rank is None or axis != rank - 1):
        return None
    removed.add(sm_idx)

    # optional additive mask
    s_name = sm.inputs[0]
    prod = gv.producer.get(s_name)
    if prod is None:
        return None
    mask = None
    if gv.is_op(prod[1], "add") and len(prod[1].inputs) == 2:
        if not gv.interior(prod[1].outputs[0]):
            return None
        for pos in (0, 1):
            mask = _match_mask_penalty(gv, prod[1].inputs[pos])
            if mask is not None:
                removed.add(prod[0])
                s_name = prod[1].inputs[1 - pos]
                prod = gv.producer.get(s_name)
                break
        if mask is None:
            return None  # an add that is not a recognized mask penalty
        if prod is None:
            return None

    # optional scale on the scores: (kind, value, const name). The NAME is
    # kept because the rewrite re-applies the ORIGINAL constant to q (see
    # below) — never a freshly computed sqrt.
    scale = None
    if gv.is_op(prod[1], "div") and len(prod[1].inputs) == 2:
        sc = gv.scalar(prod[1].inputs[1])
        if sc is not None:
            if not gv.interior(prod[1].outputs[0]):
                return None
            scale = ("div", sc[0], prod[1].inputs[1])
            removed.add(prod[0])
            s_name = prod[1].inputs[0]
            prod = gv.producer.get(s_name)
    elif gv.is_op(prod[1], "mul") and len(prod[1].inputs) == 2:
        for pos in (0, 1):
            sc = gv.scalar(prod[1].inputs[pos])
            if sc is not None:
                if not gv.interior(prod[1].outputs[0]):
                    return None
                scale = ("mul", sc[0], prod[1].inputs[pos])
                removed.add(prod[0])
                s_name = prod[1].inputs[1 - pos]
                prod = gv.producer.get(s_name)
                break
    if prod is None:
        return None

    scores_idx, scores = prod
    if not gv.is_op(scores, "mmul") or len(scores.inputs) != 2 or \
            scores.kwargs.get("transpose_a") or \
            not gv.interior(scores.outputs[0]):
        return None
    removed.add(scores_idx)

    q_name = scores.inputs[0]
    if scores.kwargs.get("transpose_b"):
        k_name, k_shape = scores.inputs[1], None
        ka = gv.aval(k_name)
        if ka is not None:
            k_shape = ka.shape
    else:
        peeled = _peel_transposed_k(gv, scores.inputs[1], namer)
        if peeled is None:
            return None
        k_name, k_synth, kt_idx, k_shape = peeled
        if k_synth is not None:
            synth.append(k_synth)
        if gv.interior(scores.inputs[1]):
            removed.add(kt_idx)

    # optional scale on q instead of on the scores: the q-side node is
    # KEPT as the fused node's q input (already feed-robust — it applies
    # the original constant to whatever is fed), only value-gated below
    q_prescaled = False
    if scale is None:
        prod_q = gv.producer.get(q_name)
        if prod_q is not None and gv.is_op(prod_q[1], "div", "mul") and \
                len(prod_q[1].inputs) == 2:
            qn, qd = prod_q[1].inputs[0], prod_q[1].inputs[1]
            sc = gv.scalar(qd)
            if prod_q[1].op == "mul" and sc is None:
                sc = gv.scalar(qn)
            if sc is not None:
                scale = (prod_q[1].op, sc[0], None)
                q_prescaled = True

    # ---- shape/value evidence ------------------------------------------
    qa = gv.aval(q_name)
    va = gv.aval(v_name)
    if qa is None or va is None or qa.rank not in (3, 4) or \
            va.rank != qa.rank:
        return None
    dk = qa.shape[-1]
    if not isinstance(dk, int) or dk <= 0:
        return None
    if k_shape is not None and len(k_shape) != qa.rank:
        return None
    if k_shape is not None and isinstance(k_shape[-1], int) \
            and k_shape[-1] != dk:
        return None
    if scale is not None:
        # pattern gate only: "is this the canonical attention scaling" —
        # the REWRITE never recomputes sqrt(dk) at runtime (dk evidence
        # may be placeholder-declared, and declarations are not enforced
        # at feed time), it re-applies the matched constant to q
        kind, val = scale[0], scale[1]
        want = float(np.sqrt(np.float32(dk)))
        ok = _close(val, want) if kind == "div" else _close(val, 1.0 / want)
        if not ok:
            return None
    else:
        scale = None

    # ---- build the fused node ------------------------------------------
    # scaled=False always: a matched scores-side scale becomes a
    # synthesized q-side node reusing the ORIGINAL constant — linearity
    # makes (q∘c) @ kᵀ ≡ (q @ kᵀ)∘c, and the numerics stay pinned to the
    # imported graph's own constant under any feed shape
    if scale is not None and not q_prescaled:
        pre = _Node_like(ctx, scale[0], [q_name, scale[2]], {},
                         [namer.fresh("qscale")])
        synth.append(pre)
        q_name = pre.outputs[0]
    inputs = [q_name, k_name, v_name]
    kwargs: Dict[str, Any] = {"scaled": False}
    mask_pending = None
    if mask is not None and mask[0] == "causal":
        kwargs["causal"] = True
    elif mask is not None:
        mask_pending = (mask[1], tuple(mask[2]))
    fused = _Node_like(ctx, "dot_product_attention", inputs, kwargs,
                       list(ctx.outputs))
    return removed, synth, fused, mask_pending


def _match_erf_gelu(gv: _GraphView, h_name: str):
    """Match the decomposed exact-gelu chain exporters emit downstream of a
    bias add: ``h * 0.5 * (1 + erf(h / sqrt(2)))`` in its canonical node
    order. Returns (removed_idxs, final_node) or None."""
    if gv.consumers.get(h_name, 0) != 2 or h_name in gv.external:
        return None
    div_entry = None
    for idx, n in gv.consumer_nodes(h_name):
        if gv.is_op(n, "div") and n.inputs[0] == h_name:
            sc = gv.scalar(n.inputs[1])
            if sc is not None and _close(sc[0], _SQRT2):
                div_entry = (idx, n)
        elif gv.is_op(n, "mul"):
            other = [i for i in n.inputs if i != h_name]
            sc = gv.scalar(other[0]) if len(other) == 1 else None
            if sc is not None and _close(sc[0], 1.0 / _SQRT2):
                div_entry = (idx, n)
    if div_entry is None:
        return None
    removed = {div_entry[0]}

    def step(name, want_op):
        nxt = gv.single_consumer(name)
        if nxt is None or not gv.is_op(nxt[1], want_op):
            return None
        return nxt

    erf = step(div_entry[1].outputs[0], "erf")
    if erf is None:
        return None
    removed.add(erf[0])
    add1 = step(erf[1].outputs[0], "add")
    if add1 is None:
        return None
    other = [i for i in add1[1].inputs if i != erf[1].outputs[0]]
    sc = gv.scalar(other[0]) if len(other) == 1 else None
    if sc is None or sc[0] != 1.0:
        return None
    removed.add(add1[0])
    mul_h = step(add1[1].outputs[0], "mul")
    if mul_h is None or h_name not in mul_h[1].inputs:
        return None
    removed.add(mul_h[0])
    half = step(mul_h[1].outputs[0], "mul")
    if half is None:
        return None
    other = [i for i in half[1].inputs if i != mul_h[1].outputs[0]]
    sc = gv.scalar(other[0]) if len(other) == 1 else None
    if sc is None or sc[0] != 0.5:
        return None
    removed.add(half[0])
    return removed, half[1]


def _try_epilogue(gv: _GraphView, add_idx: int, add):
    """Match ``act(x @ w + b)`` ending at the bias add (optionally plus an
    activation node or the decomposed erf-gelu chain).

    Returns ``(removed_idxs, fused_node)`` or None."""
    if not gv.is_op(add, "add") or len(add.inputs) != 2:
        return None
    for pos in (0, 1):
        prod = gv.producer.get(add.inputs[pos])
        if prod is None or not gv.is_op(prod[1], "mmul"):
            continue
        mm_idx, mm = prod
        if len(mm.inputs) != 2 or not gv.interior(mm.outputs[0]):
            continue
        b_name = add.inputs[1 - pos]
        ba = gv.aval(b_name)
        wa = gv.aval(mm.inputs[1])
        if ba is None or ba.rank != 1 or wa is None or wa.rank != 2:
            continue
        kwargs: Dict[str, Any] = {"activation": "none"}
        if mm.kwargs.get("transpose_a"):
            kwargs["transpose_a"] = True
        if mm.kwargs.get("transpose_b"):
            kwargs["transpose_b"] = True
        removed = {mm_idx, add_idx}
        out_node = add

        h_name = add.outputs[0]
        act = gv.single_consumer(h_name)
        if act is not None and gv.is_op(act[1], *_EPILOGUE_ACTS) and \
                len(act[1].inputs) == 1 and not act[1].kwargs:
            kwargs["activation"] = _EPILOGUE_ACTS[act[1].op]
            removed.add(act[0])
            out_node = act[1]
        else:
            gelu = _match_erf_gelu(gv, h_name)
            if gelu is not None:
                kwargs["activation"] = "gelu_exact"
                removed |= gelu[0]
                out_node = gelu[1]
        fused = _Node_like(add, "fused_matmul_bias_act",
                           [mm.inputs[0], mm.inputs[1], b_name], kwargs,
                           list(out_node.outputs))
        return removed, fused
    return None


_LN_OPS = ("layer_norm", "layer_norm_graph")


def _try_layernorm(gv: _GraphView, ln_idx: int, ln):
    """Match ``gelu(layer_norm(x, gain[, bias]))`` — a trailing-axis
    layer_norm whose single consumer is a gelu node (or the decomposed
    erf-gelu chain exporters emit) becomes ONE ``fused_layer_norm`` node:
    the Pallas one-HBM-pass LN(+activation) kernel on TPU
    (ops/pallas_layernorm.py), the exact same op chain via XLA elsewhere.
    Plain layer_norm without an activation is left verbatim — there is no
    epilogue to fuse.

    Returns ``(removed_idxs, fused_node)`` or None."""
    if not gv.is_op(ln, *_LN_OPS) or len(ln.inputs) not in (2, 3):
        return None
    xa = gv.aval(ln.inputs[0])
    if xa is None or xa.rank is None:
        return None
    axis = ln.kwargs.get("axis", -1)
    if axis not in (-1, xa.rank - 1):
        return None  # only trailing-axis norms map onto the fused kernel
    h_name = ln.outputs[0]
    removed = {ln_idx}
    # single_consumer enforces interior for the plain-gelu form;
    # _match_erf_gelu enforces its own exactly-two-consumers + non-output
    # contract for the decomposed chain (both branches of h feed the chain)
    act = gv.single_consumer(h_name)
    if act is not None and gv.is_op(act[1], "gelu") and \
            len(act[1].inputs) == 1 and not act[1].kwargs:
        activation = "gelu"
        removed.add(act[0])
        out_node = act[1]
    else:
        gelu = _match_erf_gelu(gv, h_name)
        if gelu is None:
            return None
        activation = "gelu_exact"
        removed |= gelu[0]
        out_node = gelu[1]
    fused = _Node_like(ln, "fused_layer_norm", list(ln.inputs),
                       {"axis": -1, "eps": ln.kwargs.get("eps", 1e-5),
                        "activation": activation},
                       list(out_node.outputs))
    return removed, fused


def _pass_workspace(nodes, const_vals, var_shapes, seed_dtypes,
                    input_avals, local_ops):
    """(avals, namer) for one fusion/autocast pass application: the
    abstract-interpreter evidence plus a fresh-name generator seeded with
    every name the working graph can see."""
    avals = _abstract_avals(nodes, const_vals, var_shapes, seed_dtypes,
                            input_avals, local_ops)
    taken = set(avals)
    for n in nodes:
        taken.update(n.outputs)
        taken.update(n.inputs)
    return avals, _Namer(taken)


def _fusion(nodes, outputs, const_vals, var_shapes, seed_dtypes,
            input_avals, alias, local_ops, stats):
    """The fusion tier: attention first (its chain contains matmuls the
    epilogue matcher must not claim), then matmul epilogues, then
    layer_norm(+gelu) chains, one linear scan each. Rewrites splice in
    place: removed nodes drop out, synthesized nodes land immediately
    before the fused node, output names are preserved so downstream
    consumers (and the alias map) never move."""
    # every pattern anchors on a catalog mmul or layer_norm; graphs with
    # neither (conv nets, elementwise chains, most train steps) skip the
    # abstract interpretation entirely — fusion is on the default compile
    # path
    if not any(n.op not in local_ops and (n.op == "mmul" or n.op in _LN_OPS)
               for n in nodes):
        return nodes, False
    avals, namer = _pass_workspace(nodes, const_vals, var_shapes,
                                   seed_dtypes, input_avals, local_ops)
    changed = False

    for matcher, kind in ((_try_attention, "attention"),
                          (_try_epilogue, "epilogue"),
                          (_try_layernorm, "layernorm")):
        gv = _GraphView(nodes, outputs, alias, const_vals, avals, local_ops)
        mask_cache: Dict[Any, str] = {}
        rewrites = {}   # anchor idx -> (removed, synth, fused)
        claimed: set = set()
        for idx, n in enumerate(nodes):
            if n.op in local_ops:
                continue
            if matcher is _try_attention:
                m = matcher(gv, idx, n, namer)
                if m is None:
                    continue
                removed, synth, fused, mask_pending = m
            else:
                m = matcher(gv, idx, n)
                if m is None:
                    continue
                removed, fused = m
                synth, mask_pending = [], None
            if removed & claimed:
                continue  # overlaps an accepted match: discard whole
            claimed |= removed
            if mask_pending is not None:
                # synthesize/cache the mask expansion mirror only for
                # ACCEPTED matches — a discarded match must never leave a
                # cache entry whose defining nodes were not spliced in
                m_final = mask_cache.get(mask_pending)
                if m_final is None:
                    mask_name, expand_axes = mask_pending
                    m_final = mask_name
                    for ax in expand_axes:
                        nd = _Node_like(fused, "expand_dims", [m_final],
                                        {"axis": ax}, [namer.fresh("mask")])
                        synth.append(nd)
                        m_final = nd.outputs[0]
                    mask_cache[mask_pending] = m_final
                fused.inputs.append(m_final)
            rewrites[idx] = (removed, synth, fused)
            stats.record_fusion(kind)
        if rewrites:
            out_nodes = []
            all_removed = set()
            for removed, _s, _f in rewrites.values():
                all_removed |= removed
            for idx, n in enumerate(nodes):
                if idx in rewrites:
                    removed, synth, fused = rewrites[idx]
                    out_nodes.extend(synth)
                    out_nodes.append(fused)
                elif idx not in all_removed:
                    out_nodes.append(n)
            nodes = out_nodes
            changed = True
    return nodes, changed


# ---------------------------------------------------------------------------
# autocast (opt-in — DL4J_TPU_AUTOCAST=bf16 or passes=(..., "autocast"))
# ---------------------------------------------------------------------------

# matmul/conv-class ops whose inputs are cast to bf16 (the MXU-fed set).
# Softmax/layernorm/loss ops are deliberately NOT here: the policy keeps
# normalizers and losses in f32 (the standard mixed-precision recipe).
_AUTOCAST_OPS = frozenset(
    ["mmul", "linear", "tensordot", "conv2d", "fused_matmul_bias_act"])


def _autocast(nodes, const_vals, var_shapes, seed_dtypes, input_avals,
              local_ops, stats):
    """Cast the first two (matrix) operands of each matmul/conv-class node
    to bf16 and the node's output back to f32 — bf16 MXU math with an f32
    interface (on TPU the MXU accumulates bf16 products in f32 natively;
    the result is rounded to bf16 at the node output, and the cast-back
    keeps every downstream dtype unchanged, so the invariant checker's
    interface contract still holds). Bias/residual operands (input 2+)
    stay f32 — they join after the accumulator. Idempotent: once inputs
    are bf16 there is nothing left to cast."""
    import jax.numpy as jnp

    bf16 = np.dtype(jnp.bfloat16)
    f32 = np.dtype(np.float32)
    if not any(n.op in _AUTOCAST_OPS and n.op not in local_ops
               for n in nodes):
        return nodes, False
    avals, namer = _pass_workspace(nodes, const_vals, var_shapes,
                                   seed_dtypes, input_avals, local_ops)
    from deeplearning4j_tpu import analysis as _an

    cast_cache: Dict[str, str] = {}
    out_nodes, changed = [], False
    for n in nodes:
        if n.op not in _AUTOCAST_OPS or n.op in local_ops or \
                len(n.outputs) != 1:
            out_nodes.append(n)
            continue
        # only touch nodes whose ORIGINAL result is f32: the cast-back
        # pins the interface to the inferred dtype, and hardcoding f32
        # onto e.g. an f64-promoting matmul would change it (a mixed-f64
        # node simply keeps full precision)
        oa = avals.get(n.outputs[0])
        if oa is None or oa.dtype != f32:
            out_nodes.append(n)
            continue
        new_inputs = list(n.inputs)
        n_cast = 0
        for i, name in enumerate(n.inputs[:2]):
            a = avals.get(name)
            if a is None or a.dtype != f32:
                continue
            bf_name = cast_cache.get(name)
            if bf_name is None:
                bf_name = namer.fresh("autocast")
                out_nodes.append(_Node_like(n, "cast", [name],
                                            {"dtype": "bfloat16"},
                                            [bf_name]))
                avals[bf_name] = _an.AVal(a.shape, bf16)
                cast_cache[name] = bf_name
            new_inputs[i] = bf_name
            n_cast += 1
        if not n_cast:
            out_nodes.append(n)
            continue
        out_name = n.outputs[0]
        raw = namer.fresh("autocast_raw")
        n.inputs = new_inputs
        n.outputs = [raw]
        out_nodes.append(n)
        out_nodes.append(_Node_like(n, "cast", [raw], {"dtype": "float32"},
                                    [out_name]))
        oa = avals.get(out_name)
        avals[raw] = _an.AVal(oa.shape if oa is not None else None, bf16)
        stats.record_fusion("autocast_casts", n_cast)
        changed = True
    return out_nodes, changed


# ---------------------------------------------------------------------------
# pass-invariance checking (graftcheck — docs/ANALYSIS.md)
# ---------------------------------------------------------------------------


class _InvariantChecker:
    """Abstract-interpret the working node list and compare the interface
    (requested-output) shapes/dtypes against the pre-pipeline snapshot.

    Every pass must be shape/dtype-preserving; a provable change (both the
    snapshot and the current value concrete, and different) raises
    :class:`~deeplearning4j_tpu.analysis.PassInvariantError` naming the
    pass that introduced the miscompile. Symbolic/unknown entries are
    skipped — soundness over coverage."""

    def __init__(self, outputs, input_avals, var_shapes, seed_dtypes,
                 local_ops, stats):
        from deeplearning4j_tpu import analysis as _an

        self._an = _an
        self.outputs = list(outputs)
        self.local_ops = local_ops
        self.stats = stats
        self.baseline: Dict[str, Any] = {}
        # the non-const seed never changes across passes — build it once
        self._static_seed: Dict[str, Any] = {}
        for n, s in (var_shapes or {}).items():
            self._static_seed[n] = _an.AVal(
                shape=tuple(s), dtype=(seed_dtypes or {}).get(n))
        for n, dt in (seed_dtypes or {}).items():
            if n not in self._static_seed:
                self._static_seed[n] = _an.AVal(dtype=dt)
        for n, a in (input_avals or {}).items():
            self._static_seed.setdefault(n, a)
        # const_vals only ever GROWS (fold adds, nothing removes): abstract
        # each value once instead of re-copying every <=4096-element
        # constant to host on every verify call
        self._const_avals: Dict[str, Any] = {}

    def _interface(self, work, const_vals, alias) -> Dict[str, Any]:
        an = self._an
        for n, v in const_vals.items():
            if n not in self._const_avals:
                self._const_avals[n] = an.AVal.of_array(
                    v, keep_value=np.size(v) <= 4096)
        avals: Dict[str, Any] = dict(self._static_seed)
        avals.update(self._const_avals)
        an.infer_nodes(list(enumerate(work)), avals, self.local_ops,
                       graph_name="<optimizer>", findings=[])
        return {o: avals.get(_resolve(alias, o), an.AVal.unknown())
                for o in self.outputs}

    def snapshot(self, work, const_vals, alias) -> None:
        self.baseline = self._interface(work, const_vals, alias)

    def verify(self, pass_name, work, const_vals, alias) -> None:
        an = self._an
        current = self._interface(work, const_vals, alias)
        self.stats.invariant_checks += 1
        for out, before in self.baseline.items():
            after = current[out]
            if before.dtype is not None and after.dtype is not None \
                    and before.dtype != after.dtype:
                raise an.PassInvariantError(pass_name, out, "dtype",
                                            before.dtype, after.dtype)
            if before.shape is None or after.shape is None:
                continue
            if len(before.shape) != len(after.shape):
                raise an.PassInvariantError(pass_name, out, "rank",
                                            before.shape, after.shape)
            for db, da in zip(before.shape, after.shape):
                if isinstance(db, int) and isinstance(da, int) and db != da:
                    raise an.PassInvariantError(pass_name, out, "shape",
                                                before.shape, after.shape)


# ---------------------------------------------------------------------------
# pipeline driver
# ---------------------------------------------------------------------------


def optimize_graph(nodes, outputs: Sequence[str], *,
                   const_env: Dict[str, Any],
                   seed_dtypes: Optional[Dict[str, Any]] = None,
                   var_shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                   local_ops: Optional[Dict[str, Callable]] = None,
                   resolve_op: Optional[Callable[[str], Callable]] = None,
                   passes: Optional[Sequence[str]] = None,
                   fold_size_limit: int = FOLD_SIZE_LIMIT,
                   precision_policy: str = "float32",
                   max_iters: int = _MAX_ITERS,
                   input_avals: Optional[Dict[str, Any]] = None,
                   check_invariants: Optional[bool] = None) -> GraphPlan:
    """Run the enabled passes over ``nodes`` until a fixpoint.

    Pure with respect to the inputs: ``nodes`` entries are copied, and
    ``const_env`` is never mutated (folded values land in
    ``GraphPlan.extra_consts``). ``passes=None`` enables
    :func:`default_passes` — all of :data:`PASS_ORDER` minus ``fusion``
    under ``DL4J_TPU_FUSION=0``, plus ``autocast`` under
    ``DL4J_TPU_AUTOCAST=bf16``; pass an explicit subset (which may include
    :data:`OPTIONAL_PASSES` names) for per-pass control.

    ``check_invariants`` (default on; env opt-out
    ``DL4J_TPU_CHECK_PASSES=0``): after every pass application the
    graftcheck interpreter re-derives the interface shapes/dtypes of the
    requested outputs and compares them to the pre-pipeline snapshot —
    a pass that provably changes one (a bad transpose composition, a
    dtype-unsound strip) raises PassInvariantError AT THE PASS that
    introduced it, instead of shipping a miscompiled plan.
    ``input_avals``: symbolic placeholder avals (name -> analysis.AVal)
    so named batch dims survive into the invariance check.
    """
    t0 = time.perf_counter()
    local_ops = local_ops or {}
    if resolve_op is None:
        from deeplearning4j_tpu.autodiff import samediff as _sd

        def resolve_op(name, _lo=local_ops):
            return _sd.resolve_graph_op(name, _lo)
    enabled = tuple(passes) if passes is not None else default_passes()
    valid = PASS_ORDER + OPTIONAL_PASSES
    unknown = [p for p in enabled if p not in valid]
    if unknown:
        raise ValueError(f"unknown optimizer pass(es) {unknown}; "
                         f"valid: {list(valid)}")

    alias: Dict[str, str] = {}
    const_vals = dict(const_env)
    work = [_copy_node(n) for n in nodes]
    stats = OptimizeStats(nodes_before=len(work))

    if check_invariants is None:
        import os

        check_invariants = os.environ.get("DL4J_TPU_CHECK_PASSES",
                                          "1") != "0"
    checker = None
    if check_invariants:
        checker = _InvariantChecker(outputs, input_avals, var_shapes,
                                    seed_dtypes, local_ops, stats)
        checker.snapshot(work, const_vals, alias)

    for _ in range(max_iters):
        changed = False
        for p in PASS_ORDER + OPTIONAL_PASSES:
            if p not in enabled:
                continue
            before = len(work)
            if p == "dce":
                work, ch = _dce(work, outputs, alias)
            elif p == "fold":
                work, ch = _fold(work, const_vals, resolve_op, local_ops,
                                 fold_size_limit, precision_policy)
            elif p == "cse":
                work, ch = _cse(work, alias, local_ops)
            elif p == "fusion":
                work, ch = _fusion(work, outputs, const_vals,
                                   var_shapes or {}, seed_dtypes or {},
                                   input_avals, alias, local_ops, stats)
            elif p == "autocast":
                work, ch = _autocast(work, const_vals, var_shapes or {},
                                     seed_dtypes or {}, input_avals,
                                     local_ops, stats)
            else:
                work, ch = _algebraic(work, const_vals, var_shapes or {},
                                      seed_dtypes or {}, alias, local_ops)
            ch |= _rewrite_inputs(work, alias)
            stats.record_pass(p, before, len(work))
            if ch and checker is not None:
                # every pass must preserve the interface shapes/dtypes;
                # verify against the pre-pipeline snapshot so the FIRST
                # deviating pass is the one named in the error
                checker.verify(p, work, const_vals, alias)
            changed |= ch
        if not changed:
            break

    referenced = {i for n in work for i in n.inputs}
    referenced.update(_resolve(alias, o) for o in outputs)
    extra = {k: v for k, v in const_vals.items()
             if k not in const_env and k in referenced}
    stats.nodes_after = len(work)
    t1 = time.perf_counter()
    stats.optimize_seconds = t1 - t0
    # telemetry (observe/ — docs/OBSERVABILITY.md): the optimizer pipeline
    # is part of every compile; count it and put it on the shared timeline
    from deeplearning4j_tpu import observe

    m = observe.metrics()
    m.counter("dl4j_tpu_graph_optimizations_total").inc()
    m.histogram("dl4j_tpu_graph_optimize_seconds").observe(
        stats.optimize_seconds)
    # fusion-tier hit counters (labelled family: kind=attention|epilogue|
    # layernorm|autocast_casts) — docs/OBSERVABILITY.md
    for kind, hits in stats.fusions.items():
        m.counter("dl4j_tpu_graph_fusions_total", kind=kind).inc(hits)
    observe.tracer().complete_between(
        "optimize_graph", t0, t1, category="compile",
        nodes_before=stats.nodes_before, nodes_after=stats.nodes_after)
    return GraphPlan(nodes=work, extra_consts=extra, alias=alias,
                     outputs=list(outputs), stats=stats)


# ---------------------------------------------------------------------------
# compile instrumentation (the trace/compile split of last_compile_stats)
# ---------------------------------------------------------------------------


class CompiledGraph:
    """Wraps a jitted whole-graph function so trace seconds and XLA compile
    seconds are measured separately (jax.jit hides both inside the first
    call). Only the FIRST call goes through AOT ``lower()``/``.compile()``
    (exact timings, result from the AOT executable); every later call
    dispatches through plain ``jax.jit`` — its C++ fast path beats the AOT
    executable's Python argument handling, and per-call Python signature
    hashing would tax every inference step to instrument one compile."""

    def __init__(self, jit_fn, stats: Optional[OptimizeStats] = None):
        self._jit = jit_fn
        self.stats = stats if stats is not None else OptimizeStats()
        self._timed = False

    def lower(self, *args, **kwargs):  # as_stablehlo parity surface
        return self._jit.lower(*args, **kwargs)

    def export(self, *specs):
        """AOT export hook: serialize this graph's jitted fn through
        ``jax.export`` at the given arg specs (``jax.ShapeDtypeStruct``,
        possibly with symbolic dims). Returns the ``Exported`` —
        autodiff/export.py serializes it into the persistent cache."""
        from jax import export as jexport

        return jexport.export(self._jit)(*specs)

    def __call__(self, var_arrays, feeds):
        if not self._timed:
            self._timed = True
            t0 = time.perf_counter()
            lowered = self._jit.lower(var_arrays, feeds)
            t1 = time.perf_counter()
            ex = lowered.compile()
            t2 = time.perf_counter()
            self.stats.trace_seconds = round(t1 - t0, 4)
            self.stats.compile_seconds = round(t2 - t1, 4)
            # the trace/compile split joins the unified span timeline and
            # the compile-latency histograms (observe/)
            from deeplearning4j_tpu import observe

            tr = observe.tracer()
            tr.complete_between("jit_trace", t0, t1, category="compile")
            tr.complete_between("xla_compile", t1, t2, category="compile")
            m = observe.metrics()
            m.histogram("dl4j_tpu_trace_seconds").observe(t1 - t0)
            m.histogram("dl4j_tpu_xla_compile_seconds").observe(t2 - t1)
            try:
                return ex(var_arrays, feeds)
            except TypeError:
                # aval mismatch (e.g. weak-typed scalar feeds) — plain jit
                # handles it below; genuine runtime failures (XLA OOM etc.)
                # propagate unmasked
                pass
        return self._jit(var_arrays, feeds)
