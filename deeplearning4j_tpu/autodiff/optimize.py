"""SameDiff graph optimizer — pre-trace pass pipeline (docs/OPTIMIZER.md).

The paper's core bet is whole-graph compilation: one ``jax.jit`` trace per
requested output set instead of the reference's per-op interpreter. But the
importers (imports/ir.py) emit every source node verbatim, so BERT-scale
ONNX/TF graphs carry dead branches, per-layer duplicated subexpressions
(attention-mask expansion chains), foldable constant chains, and no-op
Identity/Dropout/Reshape nodes straight into the trace — inflating both
trace time and XLA compile time. This module is the standard fix (XLA and
TVM/Relay both lead with the same trio): shrink the node graph BEFORE
tracing.

Passes (each independently sound; pipeline loops to a fixpoint):

``dce``        dead-code elimination backwards from the requested outputs.
``fold``       constant folding: a node whose inputs are all CONSTANT-derived
               (never VARIABLE — training updates must not invalidate folds)
               is evaluated eagerly once and its outputs become plan-local
               constants. Respects the const-invalidation contract: plans are
               cached in ``SameDiff._jit_cache``, which ``set_arr`` on a
               CONSTANT and every graph mutation already clear.
``cse``        common-subexpression elimination keyed on
               (op, input ids, canonical kwargs); later duplicates alias the
               first occurrence's outputs.
``algebraic``  identity cleanup: identity nodes, transpose∘transpose
               (cancelled or composed), reshape∘reshape fusion,
               reshape-to-same-shape, and x*1 / x+0 / x-0 / x/1 / x**1 strips
               (only when the surviving operand's dtype provably absorbs the
               promotion — see ``_infer_dtypes``).

The result is a :class:`GraphPlan` — an optimized node list, extra folded
constants, and an alias map — which ``SameDiff._interpret`` executes instead
of the raw recording. The graph itself (``sd._nodes``) is NEVER mutated:
serde, ``summary()``, and later mutation all see the full recording.

Instrumentation: :class:`OptimizeStats` carries per-pass node counts and, on
the ``output()`` execution path (via :class:`CompiledGraph`), the measured
trace seconds and XLA compile seconds — surfaced as
``SameDiff.last_compile_stats`` and by ``bench.py`` (BENCH_MODEL=
graph_compile / ``make bench-compile``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

PASS_ORDER: Tuple[str, ...] = ("dce", "fold", "cse", "algebraic")

# folded outputs larger than this (elements) stay in the graph: XLA would
# bake them anyway, but materializing giants at plan time trades trace
# savings for host memory with no wall-clock win
FOLD_SIZE_LIMIT = 1 << 24

_MAX_ITERS = 10  # fixpoint safety cap; real graphs settle in 2-3


@dataclasses.dataclass
class OptimizeStats:
    """Per-compile instrumentation (SameDiff.last_compile_stats)."""

    nodes_before: int = 0
    nodes_after: int = 0
    # pass name -> {"before": n at first application, "after": n at last,
    #               "removed": cumulative node delta across iterations}
    passes: Dict[str, Dict[str, int]] = dataclasses.field(default_factory=dict)
    optimize_seconds: float = 0.0
    # populated by CompiledGraph on the output() path (AOT lower/compile)
    trace_seconds: Optional[float] = None
    compile_seconds: Optional[float] = None
    # graftcheck pass-invariance runs (docs/ANALYSIS.md): how many times
    # the interface shapes/dtypes were re-verified between passes
    invariant_checks: int = 0

    def record_pass(self, name: str, before: int, after: int) -> None:
        entry = self.passes.setdefault(
            name, {"before": before, "after": after, "removed": 0})
        entry["after"] = after
        entry["removed"] += before - after

    @property
    def removed(self) -> int:
        return self.nodes_before - self.nodes_after

    def to_dict(self) -> Dict[str, Any]:
        return {"nodes_before": self.nodes_before,
                "nodes_after": self.nodes_after,
                "removed": self.removed,
                "passes": {k: dict(v) for k, v in self.passes.items()},
                "optimize_seconds": round(self.optimize_seconds, 4),
                "trace_seconds": self.trace_seconds,
                "compile_seconds": self.compile_seconds,
                "invariant_checks": self.invariant_checks}


class GraphPlan:
    """Optimized execution plan for one requested-output set."""

    __slots__ = ("nodes", "extra_consts", "alias", "outputs", "stats")

    def __init__(self, nodes, extra_consts, alias, outputs, stats):
        self.nodes = nodes
        self.extra_consts = extra_consts  # folded values, merged into env
        self.alias = alias                # removed-output name -> survivor
        self.outputs = outputs
        self.stats = stats

    def resolve(self, name: str) -> str:
        return _resolve(self.alias, name)


def _resolve(alias: Dict[str, str], name: str) -> str:
    seen = []
    while name in alias:
        seen.append(name)
        name = alias[name]
    for s in seen:  # path compression keeps chains O(1) amortized
        alias[s] = name
    return name


def _copy_node(n):
    return type(n)(n.op, list(n.inputs), dict(n.kwargs), list(n.outputs))


def _rewrite_inputs(nodes, alias: Dict[str, str]) -> bool:
    changed = False
    for n in nodes:
        for i, name in enumerate(n.inputs):
            r = _resolve(alias, name)
            if r != name:
                n.inputs[i] = r
                changed = True
    return changed


# ---------------------------------------------------------------------------
# dce
# ---------------------------------------------------------------------------


def _dce(nodes, outputs: Sequence[str], alias: Dict[str, str]):
    needed = {_resolve(alias, o) for o in outputs}
    keep = []
    for n in reversed(nodes):
        if any(o in needed for o in n.outputs):
            keep.append(n)
            needed.update(n.inputs)
    keep.reverse()
    return keep, len(keep) != len(nodes)


# ---------------------------------------------------------------------------
# fold
# ---------------------------------------------------------------------------


def _fold(nodes, const_vals: Dict[str, Any], resolve_op, local_ops,
          size_limit: int, precision_policy: str):
    from deeplearning4j_tpu.nn import dtype as DT

    out_nodes, changed = [], False
    with DT.precision_scope(precision_policy):
        for n in nodes:
            if n.op in local_ops or any(i not in const_vals for i in n.inputs):
                out_nodes.append(n)
                continue
            try:
                fn = resolve_op(n.op)
                res = fn(*[const_vals[i] for i in n.inputs], **n.kwargs)
            except Exception:
                # not statically evaluable (shape mismatch under fold,
                # helper needing a device feature, ...) — leave it traced
                out_nodes.append(n)
                continue
            vals = [res] if len(n.outputs) == 1 else list(res)
            if (len(vals) != len(n.outputs)
                    or any(np.size(v) > size_limit for v in vals)):
                out_nodes.append(n)
                continue
            for name, val in zip(n.outputs, vals):
                const_vals[name] = val
            changed = True
    return out_nodes, changed


# ---------------------------------------------------------------------------
# cse
# ---------------------------------------------------------------------------


def _canon_kwargs(kwargs: Dict[str, Any]):
    def c(v):
        if isinstance(v, (list, tuple)):
            return tuple(c(x) for x in v)
        if isinstance(v, dict):
            # repr-sort the keys: mixed-type keys (int vs str) are
            # unorderable and would abort the whole pass pipeline
            return tuple(sorted(((k, c(x)) for k, x in v.items()),
                                key=lambda kv: repr(kv[0])))
        if isinstance(v, np.ndarray):
            return ("__nd", v.shape, str(v.dtype), v.tobytes())
        return v

    # Exclude-from-CSE fallback must cover EVERYTHING canonicalization can
    # throw, not just TypeError: ndarray-like values with ambiguous
    # truthiness raise ValueError inside sorted(), device arrays can raise
    # their own errors from repr/compare, self-referential containers hit
    # RecursionError. Any failure means "this node is not CSE-able",
    # never "the optimizer pipeline dies".
    try:
        key = tuple(sorted((k, c(v)) for k, v in kwargs.items()))
        hash(key)
    except Exception:
        return None  # not canonicalizable/hashable — not CSE-able
    return key


def _cse(nodes, alias: Dict[str, str], local_ops):
    seen: Dict[Any, Any] = {}
    out_nodes, changed = [], False
    for n in nodes:
        if n.op in local_ops:  # opaque control-flow closures: never merge
            out_nodes.append(n)
            continue
        ck = _canon_kwargs(n.kwargs)
        if ck is None:
            out_nodes.append(n)
            continue
        key = (n.op, tuple(n.inputs), ck)
        prev = seen.get(key)
        if prev is None:
            seen[key] = n
            out_nodes.append(n)
        else:
            for o, po in zip(n.outputs, prev.outputs):
                alias[o] = po
            changed = True
    return out_nodes, changed


# ---------------------------------------------------------------------------
# algebraic
# ---------------------------------------------------------------------------

# unary ops whose output dtype equals a floating input's dtype
_DTYPE_PRESERVING_UNARY = frozenset([
    "identity", "neg", "abs", "exp", "log", "log1p", "sqrt", "rsqrt",
    "square", "sign", "floor", "ceil", "round", "sin", "cos", "tan",
    "tanh", "sinh", "cosh", "erf", "relu", "relu6", "elu", "selu", "gelu",
    "sigmoid", "softplus", "softsign", "swish", "mish", "leakyrelu",
    "softmax", "log_softmax", "reshape", "transpose", "permute",
    "expand_dims", "squeeze", "tile", "reduce_sum", "reduce_mean",
    "reduce_max", "reduce_min", "zeros_like", "ones_like",
])
_DTYPE_PROMOTING_BINARY = frozenset(
    ["add", "sub", "mul", "div", "pow", "maximum", "minimum", "mmul"])


def _infer_dtypes(nodes, const_vals, seed_dtypes):
    """Best-effort forward dtype propagation (floating dtypes only). A name
    absent from the result means "unknown" — identity strips then bail."""
    import jax.numpy as jnp

    dt: Dict[str, Any] = dict(seed_dtypes)
    for name, v in const_vals.items():
        vd = getattr(v, "dtype", None)
        if vd is not None:
            dt[name] = np.dtype(vd)
    for n in nodes:
        ins = [dt.get(i) for i in n.inputs]
        if n.op == "cast":
            try:
                dt[n.outputs[0]] = np.dtype(n.kwargs.get("dtype"))
            except TypeError:
                pass
        elif (n.op in _DTYPE_PRESERVING_UNARY and ins and ins[0] is not None
                and np.issubdtype(ins[0], np.inexact)):
            dt[n.outputs[0]] = ins[0]
        elif (n.op in _DTYPE_PROMOTING_BINARY and len(ins) >= 2
                and all(d is not None and np.issubdtype(d, np.inexact)
                        for d in ins[:2])):
            dt[n.outputs[0]] = np.dtype(jnp.promote_types(ins[0], ins[1]))
    return dt


def _scalar_const(const_vals, name):
    """0-d (or absent) → (value, dtype) for identity matching; None if the
    constant is non-scalar (a broadcast would change the result shape)."""
    v = const_vals.get(name)
    if v is None:
        return None
    arr = np.asarray(v)
    if arr.ndim != 0:
        return None
    try:
        return float(arr), arr.dtype
    except (TypeError, ValueError):
        return None


# op -> (identity value, which operand positions may carry it)
_BINARY_IDENTITIES = {"mul": (1.0, (0, 1)), "add": (0.0, (0, 1)),
                      "sub": (0.0, (1,)), "div": (1.0, (1,)),
                      "pow": (1.0, (1,))}


def _algebraic(nodes, const_vals, var_shapes, seed_dtypes,
               alias: Dict[str, str], local_ops):
    import jax.numpy as jnp

    dtypes = _infer_dtypes(nodes, const_vals, seed_dtypes)
    producer = {o: n for n in nodes for o in n.outputs}
    out_nodes, changed = [], False

    def known_shape(name):
        s = var_shapes.get(name)
        if s is not None:
            return s
        v = const_vals.get(name)
        return tuple(np.shape(v)) if v is not None else None

    def perm_of(axes, rank):
        return (tuple(reversed(range(rank))) if axes is None
                else tuple(int(a) for a in axes))

    for n in nodes:
        if n.op in local_ops:
            out_nodes.append(n)
            continue

        if n.op == "identity" and len(n.outputs) == 1:
            alias[n.outputs[0]] = n.inputs[0]
            changed = True
            continue

        if n.op == "transpose" and len(n.inputs) == 1:
            inner = producer.get(n.inputs[0])
            if inner is not None and inner.op == "transpose":
                a_out = n.kwargs.get("axes")
                a_in = inner.kwargs.get("axes")
                rank = (len(a_out) if a_out is not None
                        else len(a_in) if a_in is not None else None)
                if a_out is None and a_in is None:
                    # reverse twice = identity at any rank
                    alias[n.outputs[0]] = inner.inputs[0]
                    changed = True
                    continue
                if rank is not None:
                    p_in = perm_of(a_in, rank)
                    p_out = perm_of(a_out, rank)
                    combined = tuple(p_in[k] for k in p_out)
                    if combined == tuple(range(rank)):
                        alias[n.outputs[0]] = inner.inputs[0]
                        changed = True
                        continue
                    if n.inputs[0] != inner.inputs[0] or \
                            n.kwargs.get("axes") != combined:
                        n.inputs[0] = inner.inputs[0]
                        n.kwargs["axes"] = combined
                        changed = True
            out_nodes.append(n)
            continue

        if n.op == "reshape" and len(n.inputs) == 1:
            target = n.kwargs.get("shape")
            inner = producer.get(n.inputs[0])
            if inner is not None and inner.op == "reshape":
                # reshape∘reshape ≡ the outer reshape (row-major order)
                n.inputs[0] = inner.inputs[0]
                changed = True
            src = known_shape(n.inputs[0])
            if (target is not None and src is not None
                    and all(int(d) >= 0 for d in target)
                    and tuple(int(d) for d in target) == tuple(src)):
                alias[n.outputs[0]] = n.inputs[0]
                changed = True
                continue
            out_nodes.append(n)
            continue

        ident = _BINARY_IDENTITIES.get(n.op)
        if ident is not None and len(n.inputs) == 2:
            value, positions = ident
            stripped = False
            for pos in positions:
                sc = _scalar_const(const_vals, n.inputs[pos])
                if sc is None or sc[0] != value:
                    continue
                other = n.inputs[1 - pos]
                dt_other = dtypes.get(other)
                # only strip when the surviving operand's dtype provably
                # absorbs the promotion — else x(bf16)+0.0(f32) would
                # silently change the result dtype/precision
                if dt_other is None or not np.issubdtype(dt_other, np.inexact):
                    continue
                if np.dtype(jnp.promote_types(dt_other, sc[1])) != dt_other:
                    continue
                alias[n.outputs[0]] = other
                changed = True
                stripped = True
                break
            if stripped:
                continue

        out_nodes.append(n)
    return out_nodes, changed


# ---------------------------------------------------------------------------
# pass-invariance checking (graftcheck — docs/ANALYSIS.md)
# ---------------------------------------------------------------------------


class _InvariantChecker:
    """Abstract-interpret the working node list and compare the interface
    (requested-output) shapes/dtypes against the pre-pipeline snapshot.

    Every pass must be shape/dtype-preserving; a provable change (both the
    snapshot and the current value concrete, and different) raises
    :class:`~deeplearning4j_tpu.analysis.PassInvariantError` naming the
    pass that introduced the miscompile. Symbolic/unknown entries are
    skipped — soundness over coverage."""

    def __init__(self, outputs, input_avals, var_shapes, seed_dtypes,
                 local_ops, stats):
        from deeplearning4j_tpu import analysis as _an

        self._an = _an
        self.outputs = list(outputs)
        self.local_ops = local_ops
        self.stats = stats
        self.baseline: Dict[str, Any] = {}
        # the non-const seed never changes across passes — build it once
        self._static_seed: Dict[str, Any] = {}
        for n, s in (var_shapes or {}).items():
            self._static_seed[n] = _an.AVal(
                shape=tuple(s), dtype=(seed_dtypes or {}).get(n))
        for n, dt in (seed_dtypes or {}).items():
            if n not in self._static_seed:
                self._static_seed[n] = _an.AVal(dtype=dt)
        for n, a in (input_avals or {}).items():
            self._static_seed.setdefault(n, a)
        # const_vals only ever GROWS (fold adds, nothing removes): abstract
        # each value once instead of re-copying every <=4096-element
        # constant to host on every verify call
        self._const_avals: Dict[str, Any] = {}

    def _interface(self, work, const_vals, alias) -> Dict[str, Any]:
        an = self._an
        for n, v in const_vals.items():
            if n not in self._const_avals:
                self._const_avals[n] = an.AVal.of_array(
                    v, keep_value=np.size(v) <= 4096)
        avals: Dict[str, Any] = dict(self._static_seed)
        avals.update(self._const_avals)
        an.infer_nodes(list(enumerate(work)), avals, self.local_ops,
                       graph_name="<optimizer>", findings=[])
        return {o: avals.get(_resolve(alias, o), an.AVal.unknown())
                for o in self.outputs}

    def snapshot(self, work, const_vals, alias) -> None:
        self.baseline = self._interface(work, const_vals, alias)

    def verify(self, pass_name, work, const_vals, alias) -> None:
        an = self._an
        current = self._interface(work, const_vals, alias)
        self.stats.invariant_checks += 1
        for out, before in self.baseline.items():
            after = current[out]
            if before.dtype is not None and after.dtype is not None \
                    and before.dtype != after.dtype:
                raise an.PassInvariantError(pass_name, out, "dtype",
                                            before.dtype, after.dtype)
            if before.shape is None or after.shape is None:
                continue
            if len(before.shape) != len(after.shape):
                raise an.PassInvariantError(pass_name, out, "rank",
                                            before.shape, after.shape)
            for db, da in zip(before.shape, after.shape):
                if isinstance(db, int) and isinstance(da, int) and db != da:
                    raise an.PassInvariantError(pass_name, out, "shape",
                                                before.shape, after.shape)


# ---------------------------------------------------------------------------
# pipeline driver
# ---------------------------------------------------------------------------


def optimize_graph(nodes, outputs: Sequence[str], *,
                   const_env: Dict[str, Any],
                   seed_dtypes: Optional[Dict[str, Any]] = None,
                   var_shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                   local_ops: Optional[Dict[str, Callable]] = None,
                   resolve_op: Optional[Callable[[str], Callable]] = None,
                   passes: Optional[Sequence[str]] = None,
                   fold_size_limit: int = FOLD_SIZE_LIMIT,
                   precision_policy: str = "float32",
                   max_iters: int = _MAX_ITERS,
                   input_avals: Optional[Dict[str, Any]] = None,
                   check_invariants: Optional[bool] = None) -> GraphPlan:
    """Run the enabled passes over ``nodes`` until a fixpoint.

    Pure with respect to the inputs: ``nodes`` entries are copied, and
    ``const_env`` is never mutated (folded values land in
    ``GraphPlan.extra_consts``). ``passes=None`` enables all of
    :data:`PASS_ORDER`; pass a subset for per-pass opt-out.

    ``check_invariants`` (default on; env opt-out
    ``DL4J_TPU_CHECK_PASSES=0``): after every pass application the
    graftcheck interpreter re-derives the interface shapes/dtypes of the
    requested outputs and compares them to the pre-pipeline snapshot —
    a pass that provably changes one (a bad transpose composition, a
    dtype-unsound strip) raises PassInvariantError AT THE PASS that
    introduced it, instead of shipping a miscompiled plan.
    ``input_avals``: symbolic placeholder avals (name -> analysis.AVal)
    so named batch dims survive into the invariance check.
    """
    t0 = time.perf_counter()
    local_ops = local_ops or {}
    if resolve_op is None:
        from deeplearning4j_tpu.autodiff import samediff as _sd

        def resolve_op(name, _lo=local_ops):
            return _sd.resolve_graph_op(name, _lo)
    enabled = tuple(passes) if passes is not None else PASS_ORDER
    unknown = [p for p in enabled if p not in PASS_ORDER]
    if unknown:
        raise ValueError(f"unknown optimizer pass(es) {unknown}; "
                         f"valid: {list(PASS_ORDER)}")

    alias: Dict[str, str] = {}
    const_vals = dict(const_env)
    work = [_copy_node(n) for n in nodes]
    stats = OptimizeStats(nodes_before=len(work))

    if check_invariants is None:
        import os

        check_invariants = os.environ.get("DL4J_TPU_CHECK_PASSES",
                                          "1") != "0"
    checker = None
    if check_invariants:
        checker = _InvariantChecker(outputs, input_avals, var_shapes,
                                    seed_dtypes, local_ops, stats)
        checker.snapshot(work, const_vals, alias)

    for _ in range(max_iters):
        changed = False
        for p in PASS_ORDER:
            if p not in enabled:
                continue
            before = len(work)
            if p == "dce":
                work, ch = _dce(work, outputs, alias)
            elif p == "fold":
                work, ch = _fold(work, const_vals, resolve_op, local_ops,
                                 fold_size_limit, precision_policy)
            elif p == "cse":
                work, ch = _cse(work, alias, local_ops)
            else:
                work, ch = _algebraic(work, const_vals, var_shapes or {},
                                      seed_dtypes or {}, alias, local_ops)
            ch |= _rewrite_inputs(work, alias)
            stats.record_pass(p, before, len(work))
            if ch and checker is not None:
                # every pass must preserve the interface shapes/dtypes;
                # verify against the pre-pipeline snapshot so the FIRST
                # deviating pass is the one named in the error
                checker.verify(p, work, const_vals, alias)
            changed |= ch
        if not changed:
            break

    referenced = {i for n in work for i in n.inputs}
    referenced.update(_resolve(alias, o) for o in outputs)
    extra = {k: v for k, v in const_vals.items()
             if k not in const_env and k in referenced}
    stats.nodes_after = len(work)
    t1 = time.perf_counter()
    stats.optimize_seconds = t1 - t0
    # telemetry (observe/ — docs/OBSERVABILITY.md): the optimizer pipeline
    # is part of every compile; count it and put it on the shared timeline
    from deeplearning4j_tpu import observe

    m = observe.metrics()
    m.counter("dl4j_tpu_graph_optimizations_total").inc()
    m.histogram("dl4j_tpu_graph_optimize_seconds").observe(
        stats.optimize_seconds)
    observe.tracer().complete_between(
        "optimize_graph", t0, t1, category="compile",
        nodes_before=stats.nodes_before, nodes_after=stats.nodes_after)
    return GraphPlan(nodes=work, extra_consts=extra, alias=alias,
                     outputs=list(outputs), stats=stats)


# ---------------------------------------------------------------------------
# compile instrumentation (the trace/compile split of last_compile_stats)
# ---------------------------------------------------------------------------


class CompiledGraph:
    """Wraps a jitted whole-graph function so trace seconds and XLA compile
    seconds are measured separately (jax.jit hides both inside the first
    call). Only the FIRST call goes through AOT ``lower()``/``.compile()``
    (exact timings, result from the AOT executable); every later call
    dispatches through plain ``jax.jit`` — its C++ fast path beats the AOT
    executable's Python argument handling, and per-call Python signature
    hashing would tax every inference step to instrument one compile."""

    def __init__(self, jit_fn, stats: Optional[OptimizeStats] = None):
        self._jit = jit_fn
        self.stats = stats if stats is not None else OptimizeStats()
        self._timed = False

    def lower(self, *args, **kwargs):  # as_stablehlo parity surface
        return self._jit.lower(*args, **kwargs)

    def __call__(self, var_arrays, feeds):
        if not self._timed:
            self._timed = True
            t0 = time.perf_counter()
            lowered = self._jit.lower(var_arrays, feeds)
            t1 = time.perf_counter()
            ex = lowered.compile()
            t2 = time.perf_counter()
            self.stats.trace_seconds = round(t1 - t0, 4)
            self.stats.compile_seconds = round(t2 - t1, 4)
            # the trace/compile split joins the unified span timeline and
            # the compile-latency histograms (observe/)
            from deeplearning4j_tpu import observe

            tr = observe.tracer()
            tr.complete_between("jit_trace", t0, t1, category="compile")
            tr.complete_between("xla_compile", t1, t2, category="compile")
            m = observe.metrics()
            m.histogram("dl4j_tpu_trace_seconds").observe(t1 - t0)
            m.histogram("dl4j_tpu_xla_compile_seconds").observe(t2 - t1)
            try:
                return ex(var_arrays, feeds)
            except TypeError:
                # aval mismatch (e.g. weak-typed scalar feeds) — plain jit
                # handles it below; genuine runtime failures (XLA OOM etc.)
                # propagate unmasked
                pass
        return self._jit(var_arrays, feeds)
