"""Autodiff graph engine — the SameDiff role (SURVEY §3.2, §4.3)."""

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable, TrainingConfig
from deeplearning4j_tpu.autodiff.optimize import (
    GraphPlan,
    OptimizeStats,
    optimize_graph,
)
from deeplearning4j_tpu.autodiff.gradcheck import (
    check_gradients,
    check_gradients_fn,
    check_samediff_gradients,
)
from deeplearning4j_tpu.autodiff.listeners import (
    History,
    HistoryListener,
    UIListener,
)
