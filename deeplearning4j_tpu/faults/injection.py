"""Deterministic fault injection — the chaos layer (docs/ROBUSTNESS.md).

A production serving system is defined less by its fast path than by what
happens when that path breaks: a decode step throwing, the page pool
running dry, a checkpoint torn mid-write, a worker thread dying. This
module makes those failures *injectable on demand* at a fixed catalog of
named points (:data:`FAULT_POINTS`) so the supervision/recovery machinery
(engine restarts, retry re-admission, checkpoint fallback) can be proven
under test and in the ``chaos`` gate stage instead of trusted.

Design constraints, in order:

* **Off means off.** With ``DL4J_TPU_FAULTS`` unset and nothing armed
  programmatically, :func:`should_fire` is one module-bool read — the
  hooks compile away to a predictable-branch no-op in every hot loop they
  sit in (the generate bench shows no measurable delta).
* **Deterministic.** Every armed point draws from its own seeded
  ``random.Random`` stream keyed on (seed, point name) — a fault schedule
  replays identically across runs, which is what makes a chaos failure
  debuggable.
* **Observable.** Every fired fault increments
  ``dl4j_tpu_faults_injected_total{point=...}`` and writes a
  ``fault_injected`` JSONL event, so a chaos run's injected failures are
  first-class telemetry next to the recoveries they caused.

Arming::

    DL4J_TPU_FAULTS=decode_step_error:1:4,page_oom:0.2   # env schedule
    faults.arm("worker_death", prob=1.0, after_n=10, max_fires=1)  # tests

Env syntax is ``point:prob[:after_n]`` comma-separated; programmatic
:func:`arm` adds ``max_fires`` and ``seed``. Call sites use
:func:`should_fire` (branch), :func:`maybe_fail` (raise
:class:`InjectedFault`), or :func:`maybe_sleep` (latency injection).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
import zlib
from typing import Dict, Optional

from deeplearning4j_tpu import observe

logger = logging.getLogger(__name__)

FAULTS_ENV = "DL4J_TPU_FAULTS"

#: The injection-point catalog. Each name is hooked at ONE class of real
#: call site (docs/ROBUSTNESS.md has the full table):
#:   page_oom              serving/cache.py  ensure_capacity -> forced "oom"
#:   decode_step_error     serving/engine.py step            -> raise
#:   slow_decode           serving/engine.py step            -> sleep
#:   worker_death          serving/engine.py _serve_loop     -> raise
#:   checkpoint_torn_write parallel/checkpoint.py save       -> truncate file
#:   backend_init_fail     parallel/mesh.py  ParallelInference -> raise
#:   burst_arrival         serving/frontend.py SLOFrontend.submit
#:                                            -> inject synthetic arrivals
#:   preemption            nn fit loops (MLN/CG/SameDiff), per step -> raise
#:                         (a hard TPU-pod preemption: no snapshot chance);
#:                         worker_death ALSO fires inside the async
#:                         checkpoint writer thread (parallel/checkpoint.py)
#:   engine_death          serving/engine.py _serve_loop     -> raise with the
#:                         restart budget spent first: a HARD unrestartable
#:                         kill of the whole engine (vs worker_death, which
#:                         the supervisor absorbs). The cluster router's
#:                         failure domain (serving/cluster.py).
FAULT_POINTS = (
    "page_oom",
    "decode_step_error",
    "slow_decode",
    "worker_death",
    "checkpoint_torn_write",
    "backend_init_fail",
    "burst_arrival",
    "preemption",
    "engine_death",
)


class InjectedFault(RuntimeError):
    """The exception raised by raising fault points. Carries the point
    name so recovery paths (and tests) can attribute the failure."""

    def __init__(self, point: str):
        super().__init__(f"injected fault: {point}")
        self.point = point


@dataclasses.dataclass
class FaultSpec:
    """One armed injection point and its firing schedule."""

    point: str
    prob: float = 1.0            # per-eligible-call fire probability
    after_n: int = 0             # skip the first N eligible calls
    max_fires: Optional[int] = None   # stop firing after this many
    seed: int = 0
    calls: int = 0               # bookkeeping (under the module lock)
    fires: int = 0

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {self.point!r}; known: {FAULT_POINTS}")
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")
        if self.after_n < 0:
            raise ValueError(f"after_n must be >= 0, got {self.after_n}")
        # per-(seed, point) stream: deterministic replay, independent points
        self._rng = random.Random(
            (self.seed << 32) ^ zlib.crc32(self.point.encode()))


# one lock guards the armed-spec table and the env-parse cache; fault
# checks are cheap and rare enough (host-side scheduler boundaries, never
# under jit) that a single lock is not a contention concern
_LOCK = threading.Lock()
_ARMED: Dict[str, FaultSpec] = {}
_ANY_ARMED = False          # the fast-path gate: one bool read when idle
_ENV_CACHE: tuple = ("", ())  # (raw env value, parsed specs)


def _parse_env(raw: str):
    """``point:prob[:after_n]`` comma-separated -> FaultSpec list. A
    malformed entry disables itself with ONE warning instead of taking
    down the process that exported it."""
    specs = []
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        try:
            spec = FaultSpec(
                point=parts[0],
                prob=float(parts[1]) if len(parts) > 1 else 1.0,
                after_n=int(parts[2]) if len(parts) > 2 else 0)
        except (ValueError, IndexError) as e:
            logger.warning("%s: ignoring malformed entry %r (%s)",
                           FAULTS_ENV, entry, e)
            continue
        specs.append(spec)
    return tuple(specs)


def _lookup(point: str) -> Optional[FaultSpec]:
    """The armed spec for ``point``: programmatic arms win over env."""
    global _ENV_CACHE
    spec = _ARMED.get(point)
    if spec is not None:
        return spec
    raw = os.environ.get(FAULTS_ENV, "")
    if not raw:
        return None
    if _ENV_CACHE[0] != raw:
        _ENV_CACHE = (raw, _parse_env(raw))
    for s in _ENV_CACHE[1]:
        if s.point == point:
            return s
    return None


def arm(point: str, prob: float = 1.0, after_n: int = 0,
        max_fires: Optional[int] = None, seed: int = 0) -> FaultSpec:
    """Arm ``point`` programmatically (tests, the chaos harness). Wins
    over any env schedule for the same point."""
    global _ANY_ARMED
    spec = FaultSpec(point=point, prob=prob, after_n=after_n,
                     max_fires=max_fires, seed=seed)
    with _LOCK:
        _ARMED[point] = spec
        _ANY_ARMED = True
    return spec


def disarm(point: str) -> None:
    global _ANY_ARMED
    with _LOCK:
        _ARMED.pop(point, None)
        _ANY_ARMED = bool(_ARMED)


def reset() -> None:
    """Disarm every programmatic point and drop the env-parse cache (so a
    changed ``DL4J_TPU_FAULTS`` re-parses with fresh call counters). Also
    clears a pending graceful-preemption request."""
    global _ANY_ARMED, _ENV_CACHE
    with _LOCK:
        _ARMED.clear()
        _ANY_ARMED = False
        _ENV_CACHE = ("", ())
    _PREEMPTION.clear()


def active() -> bool:
    """Anything armed (programmatically or via env)?"""
    return _ANY_ARMED or bool(os.environ.get(FAULTS_ENV))


def fire_counts() -> Dict[str, int]:
    """point -> times fired, across programmatic AND env arms."""
    with _LOCK:
        out = {s.point: s.fires for s in _ENV_CACHE[1] if s.fires}
        for s in _ARMED.values():
            if s.fires:
                out[s.point] = out.get(s.point, 0) + s.fires
    return out


def should_fire(point: str) -> bool:
    """ONE call-site check: does the armed schedule for ``point`` fire
    now? The unarmed fast path is a bool read + (when env is also unset)
    one dict lookup — safe in any loop this framework has."""
    if not _ANY_ARMED and not os.environ.get(FAULTS_ENV):
        return False
    with _LOCK:
        spec = _lookup(point)
        if spec is None:
            return False
        spec.calls += 1
        if spec.calls <= spec.after_n:
            return False
        if spec.max_fires is not None and spec.fires >= spec.max_fires:
            return False
        if spec.prob < 1.0 and spec._rng.random() >= spec.prob:
            return False
        spec.fires += 1
    observe.metrics().counter(
        "dl4j_tpu_faults_injected_total", point=point).inc()
    observe.log_event("fault_injected", point=point)
    logger.warning("fault injected: %s (fire %d)", point, spec.fires)
    return True


def maybe_fail(point: str) -> None:
    """Raise :class:`InjectedFault` when the schedule fires."""
    if should_fire(point):
        raise InjectedFault(point)


def maybe_sleep(point: str, seconds: float) -> None:
    """Inject latency when the schedule fires (e.g. ``slow_decode``)."""
    if should_fire(point):
        time.sleep(seconds)


# ---------------------------------------------------------------------------
# graceful preemption (docs/ROBUSTNESS.md § Preemption-proof training)
# ---------------------------------------------------------------------------
# Distinct from the ``preemption`` FAULT point above: the fault is a HARD
# kill (raise mid-fit, no snapshot chance); this flag is the SOFT path a
# SIGTERM handler sets so the fit loops can take one final synchronous
# snapshot and exit cleanly before the scheduler's grace period expires.
# It lives here (not in parallel/) because the nn fit loops poll it every
# step and faults/ is the one layer they can all import without cycles.

_PREEMPTION = threading.Event()


def request_preemption() -> None:
    """Ask every running fit loop to snapshot and exit cleanly at its next
    step boundary (the SIGTERM handler's one job). Idempotent.

    ASYNC-SIGNAL-SAFE by design: one Event.set(), nothing else. The
    handler may interrupt the main thread while it holds the JSONL log
    lock or a logging-module lock — any log/metric call here could
    deadlock the very grace period this flag exists to use. The polling
    site (``nn/listeners.notify_preemption``) does the logging."""
    _PREEMPTION.set()


def preemption_requested() -> bool:
    """Polled by the fit loops once per step (an Event read — safe in any
    training loop)."""
    return _PREEMPTION.is_set()


def clear_preemption() -> None:
    """Drop a pending graceful-preemption request (after the supervisor
    has handled it, or in test teardown)."""
    _PREEMPTION.clear()
