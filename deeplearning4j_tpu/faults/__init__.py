"""Process-wide fault injection + the vocabulary of supervised recovery.

See :mod:`deeplearning4j_tpu.faults.injection` for the design and
docs/ROBUSTNESS.md for the fault-point catalog, the engine supervisor
state machine this layer exists to exercise, and the ``chaos`` gate
stage that runs a full fault schedule before every snapshot.

Imports neither jax nor the model runtimes (only ``observe``) — safe to
import from any layer, including ``parallel/checkpoint.py`` and the
serving hot loops.
"""

from deeplearning4j_tpu.faults.injection import (
    FAULT_POINTS,
    FAULTS_ENV,
    FaultSpec,
    InjectedFault,
    active,
    arm,
    clear_preemption,
    disarm,
    fire_counts,
    maybe_fail,
    maybe_sleep,
    preemption_requested,
    request_preemption,
    reset,
    should_fire,
)

__all__ = [
    "FAULT_POINTS", "FAULTS_ENV", "FaultSpec", "InjectedFault",
    "active", "arm", "clear_preemption", "disarm", "fire_counts",
    "maybe_fail", "maybe_sleep", "preemption_requested",
    "request_preemption", "reset", "should_fire",
]
