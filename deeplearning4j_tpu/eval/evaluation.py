"""Evaluation result objects — org/nd4j/evaluation/** parity.

Reference parity:
  * classification/Evaluation.java — accuracy/precision/recall/F1 (micro &
    macro), confusion matrix, per-class stats, ``stats()`` pretty-print.
  * classification/ROC.java / ROCMultiClass.java — exact-mode AUC/AUPRC.
  * regression/RegressionEvaluation.java — MSE/MAE/RMSE/RSE/PC/R².
  * EvaluationBinary, EvaluationCalibration (reliability buckets).

These are host-side accumulators over numpy arrays (eval runs the jitted
forward on device; the metric bookkeeping is cheap host work, as in the
reference where Evaluation runs on the JVM side).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class Evaluation:
    """Multiclass classification evaluation (Evaluation.java)."""

    def __init__(self, num_classes: Optional[int] = None, labels: Optional[Sequence[str]] = None):
        self.num_classes = num_classes
        self.label_names = list(labels) if labels else None
        self.confusion: Optional[np.ndarray] = None  # [actual, predicted]

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = np.zeros((self.num_classes, self.num_classes), dtype=np.int64)

    def eval(self, labels, predictions, mask=None) -> None:
        """Accumulate a batch. labels/predictions: one-hot/prob (N, C) or
        (N, T, C) with optional (N, T) mask — reference evalTimeSeries."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], dtype=bool)
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        elif mask is not None:
            # per-example mask on (N, C) input — reference drops masked rows
            m = np.asarray(mask)
            if m.size != labels.shape[0]:
                raise ValueError(
                    f"per-output masks are not supported by Evaluation "
                    f"(mask shape {m.shape} vs {labels.shape[0]} examples); "
                    "use EvaluationBinary for per-output masking")
            m = m.astype(bool).reshape(-1)
            labels = labels[m]
            predictions = predictions[m]
        self._ensure(labels.shape[-1])
        actual = labels.argmax(-1)
        pred = predictions.argmax(-1)
        np.add.at(self.confusion, (actual, pred), 1)

    # ---- metrics ----------------------------------------------------------
    def _tp(self):
        return np.diag(self.confusion).astype(np.float64)

    def accuracy(self) -> float:
        c = self.confusion
        return float(np.diag(c).sum() / max(c.sum(), 1))

    def precision(self, cls: Optional[int] = None) -> float:
        c = self.confusion
        col = c.sum(axis=0).astype(np.float64)
        with np.errstate(invalid="ignore", divide="ignore"):
            p = np.where(col > 0, self._tp() / col, np.nan)
        return float(p[cls]) if cls is not None else float(np.nanmean(p))

    def recall(self, cls: Optional[int] = None) -> float:
        c = self.confusion
        row = c.sum(axis=1).astype(np.float64)
        with np.errstate(invalid="ignore", divide="ignore"):
            r = np.where(row > 0, self._tp() / row, np.nan)
        return float(r[cls]) if cls is not None else float(np.nanmean(r))

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 0.0 if p + r == 0 or np.isnan(p + r) else 2 * p * r / (p + r)

    def stats(self) -> str:
        n = self.num_classes or 0
        names = self.label_names or [str(i) for i in range(n)]
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {n}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
        ]
        header = "     " + " ".join(f"{names[j]:>5}" for j in range(n))
        lines.append(header)
        for i in range(n):
            lines.append(f"{names[i]:>4} " + " ".join(f"{self.confusion[i, j]:>5}" for j in range(n)))
        return "\n".join(lines)

    def merge(self, other: "Evaluation") -> "Evaluation":
        """Distributed-eval combiner (IEvaluation.merge in the reference —
        what Spark RDD evaluation reduces with)."""
        if other.confusion is not None:
            self._ensure(other.confusion.shape[0])
            self.confusion += other.confusion
        return self


class EvaluationBinary:
    """EvaluationBinary.java: per-output independent binary eval at 0.5."""

    def __init__(self):
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels) > 0.5
        pred = np.asarray(predictions) > 0.5
        if self.tp is None:
            n = labels.shape[-1]
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)
        flat_l = labels.reshape(-1, labels.shape[-1])
        flat_p = pred.reshape(-1, pred.shape[-1])
        if mask is not None:
            m = np.asarray(mask).astype(bool).reshape(-1)
            flat_l, flat_p = flat_l[m], flat_p[m]
        self.tp += (flat_l & flat_p).sum(0)
        self.fp += (~flat_l & flat_p).sum(0)
        self.tn += (~flat_l & ~flat_p).sum(0)
        self.fn += (flat_l & ~flat_p).sum(0)

    def accuracy(self):
        tot = self.tp + self.fp + self.tn + self.fn
        return float(((self.tp + self.tn) / np.maximum(tot, 1)).mean())

    def f1(self):
        p = self.tp / np.maximum(self.tp + self.fp, 1)
        r = self.tp / np.maximum(self.tp + self.fn, 1)
        f = np.where(p + r > 0, 2 * p * r / np.maximum(p + r, 1e-12), 0.0)
        return float(f.mean())


class ROC:
    """ROC.java in exact mode: full-resolution AUC / AUPRC for binary output."""

    def __init__(self):
        self.scores: List[np.ndarray] = []
        self.labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim > 1 and labels.shape[-1] == 2:  # two-column softmax output
            labels = labels[..., 1]
            predictions = predictions[..., 1]
        labels = labels.reshape(-1)
        predictions = predictions.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).astype(bool).reshape(-1)
            labels, predictions = labels[m], predictions[m]
        self.labels.append(labels)
        self.scores.append(predictions)

    def _sorted(self):
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        return y[order] > 0.5, s[order]

    def calculate_auc(self) -> float:
        y, _ = self._sorted()
        pos = y.sum()
        neg = len(y) - pos
        if pos == 0 or neg == 0:
            return float("nan")
        tpr = np.concatenate([[0], np.cumsum(y) / pos])
        fpr = np.concatenate([[0], np.cumsum(~y) / neg])
        return float(np.trapezoid(tpr, fpr))

    def calculate_auprc(self) -> float:
        y, _ = self._sorted()
        pos = y.sum()
        if pos == 0:
            return float("nan")
        cum_tp = np.cumsum(y)
        precision = cum_tp / np.arange(1, len(y) + 1)
        recall = cum_tp / pos
        return float(np.trapezoid(precision, recall))


class ROCBinary:
    """ROCBinary.java: an independent ROC per OUTPUT of a multi-label
    binary network (sigmoid outputs), unlike ROCMultiClass's one-vs-all
    over a softmax."""

    def __init__(self):
        self.per_output: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        orig_shape = labels.shape
        labels = labels.reshape(-1, labels.shape[-1])
        predictions = predictions.reshape(-1, labels.shape[-1])
        per_output_mask = None
        m = None
        if mask is not None:
            mk = np.asarray(mask)
            # per-output mask iff it matches the labels' FULL shape — a
            # last-dim-only match would misread a per-timestep (N, T) mask
            # whenever T == nOut
            if mk.shape == orig_shape:
                per_output_mask = mk.reshape(-1, labels.shape[-1])
            else:
                m = mk.reshape(-1)  # per-example/timestep mask, all outputs
        for c in range(labels.shape[-1]):
            mc = per_output_mask[:, c] if per_output_mask is not None else m
            self.per_output.setdefault(c, ROC()).eval(
                labels[:, c], predictions[:, c], mc)

    def calculate_auc(self, output: int) -> float:
        return self.per_output[output].calculate_auc()

    def calculate_auprc(self, output: int) -> float:
        return self.per_output[output].calculate_auprc()

    def calculate_average_auc(self) -> float:
        return float(np.nanmean(
            [r.calculate_auc() for r in self.per_output.values()]))


class ROCMultiClass:
    """ROCMultiClass.java: one-vs-all ROC per class."""

    def __init__(self):
        self.per_class: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        predictions = np.asarray(predictions).reshape(-1, labels.shape[-1])
        for c in range(labels.shape[-1]):
            self.per_class.setdefault(c, ROC()).eval(labels[:, c], predictions[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self.per_class[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.nanmean([r.calculate_auc() for r in self.per_class.values()]))


class RegressionEvaluation:
    """RegressionEvaluation.java: column-wise MSE/MAE/RMSE/R²/pearson."""

    def __init__(self):
        self.preds: List[np.ndarray] = []
        self.labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None) -> None:
        l = np.asarray(labels).astype(np.float64)
        p = np.asarray(predictions).astype(np.float64)
        l = l.reshape(-1, l.shape[-1])
        p = p.reshape(-1, p.shape[-1])
        if mask is not None:
            m = np.asarray(mask).astype(bool).reshape(-1)
            l, p = l[m], p[m]
        self.labels.append(l)
        self.preds.append(p)

    def _cat(self):
        return np.concatenate(self.labels), np.concatenate(self.preds)

    def mean_squared_error(self, col: int = 0) -> float:
        l, p = self._cat()
        return float(((l[:, col] - p[:, col]) ** 2).mean())

    def mean_absolute_error(self, col: int = 0) -> float:
        l, p = self._cat()
        return float(np.abs(l[:, col] - p[:, col]).mean())

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        l, p = self._cat()
        ss_res = ((l[:, col] - p[:, col]) ** 2).sum()
        ss_tot = ((l[:, col] - l[:, col].mean()) ** 2).sum()
        return float(1 - ss_res / max(ss_tot, 1e-12))

    def pearson_correlation(self, col: int = 0) -> float:
        l, p = self._cat()
        return float(np.corrcoef(l[:, col], p[:, col])[0, 1])

    def average_mean_squared_error(self) -> float:
        l, p = self._cat()
        return float(((l - p) ** 2).mean())

    def stats(self) -> str:
        l, p = self._cat()
        n = l.shape[1]
        lines = ["Column    MSE            MAE            RMSE           R^2"]
        for c in range(n):
            lines.append(
                f"col_{c:<5} {self.mean_squared_error(c):<14.6f} "
                f"{self.mean_absolute_error(c):<14.6f} "
                f"{self.root_mean_squared_error(c):<14.6f} {self.r_squared(c):<10.6f}"
            )
        return "\n".join(lines)


class EvaluationCalibration:
    """EvaluationCalibration.java: reliability diagram buckets."""

    def __init__(self, n_bins: int = 10):
        self.n_bins = n_bins
        self.bin_counts = np.zeros(n_bins, np.int64)
        self.bin_pos = np.zeros(n_bins, np.int64)
        self.bin_prob_sum = np.zeros(n_bins, np.float64)

    def eval(self, labels, predictions, mask=None) -> None:
        l = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        p = np.asarray(predictions).reshape(-1, l.shape[-1])
        if mask is not None:
            m = np.asarray(mask).astype(bool).reshape(-1)
            l, p = l[m], p[m]
        probs = p.reshape(-1)
        hits = l.reshape(-1) > 0.5
        bins = np.clip((probs * self.n_bins).astype(int), 0, self.n_bins - 1)
        np.add.at(self.bin_counts, bins, 1)
        np.add.at(self.bin_pos, bins, hits.astype(np.int64))
        np.add.at(self.bin_prob_sum, bins, probs)

    def reliability(self):
        """(mean predicted prob, empirical freq) per bin."""
        cnt = np.maximum(self.bin_counts, 1)
        return self.bin_prob_sum / cnt, self.bin_pos / cnt
