"""Evaluation — org/nd4j/evaluation/** parity (SURVEY §3.2)."""

from deeplearning4j_tpu.eval.evaluation import (
    Evaluation,
    EvaluationBinary,
    ROC,
    ROCBinary,
    ROCMultiClass,
    RegressionEvaluation,
    EvaluationCalibration,
)
