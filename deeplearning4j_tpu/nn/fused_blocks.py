"""TPU-fused ResNet bottleneck block (round-5 perf lever 1 executed).

One layer = the whole canonical v1 bottleneck {1×1 → BN+relu → 3×3 →
BN+relu → 1×1 → BN → (+shortcut) → relu}, arranged so the two 1×1 convs run
through ``ops/pallas_convbn.fused_matmul_bn`` — a single-HBM-pass Pallas
kernel that folds the previous BN's affine+relu into the matmul's operand
read and this conv's BN statistics into its output write. Per block this
eliminates the standalone BN-stats passes of bn1/bn3/bn_sc, and the
materialized normalize pass between bn2 and c3 (docs/PERF_ANALYSIS.md: the
step is HBM-bound on exactly these passes).

Mathematically identical to the composed layers (same one-pass shifted
moments as ``_bn_core``, same running-buffer decay semantics); the Pallas
path engages only on TPU/bf16, so the CPU mesh runs the reference chain —
``tests/test_fused_block.py`` pins equality against the composed-layer
graph for forward, gradients, and running stats.

Reference parity: this fuses the same (Conv, BatchNormalization, Activation)
triple the reference builds ResNet50 from (zoo/model/ResNet50.java), the
role cuDNN's fused ConvScaleBiasActivation kernels play on GPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn.layers import Layer
from deeplearning4j_tpu.ops.pallas_convbn import fused_matmul_bn
from deeplearning4j_tpu.ops import nn_ops
from deeplearning4j_tpu.ops.weight_init import init_weights

_F32 = jnp.float32


def _affine(gamma, beta, mean, var, eps):
    """Fold BN (stats, γ, β) into per-channel scale/shift, f32."""
    inv = lax.rsqrt(var.astype(_F32) + eps)
    sc = inv if gamma is None else inv * gamma.astype(_F32)
    sh = -mean.astype(_F32) * sc
    if beta is not None:
        sh = sh + beta.astype(_F32)
    return sc, sh


def _shifted_stats(z, stat_shift):
    """One-pass running-mean-shifted batch moments over all but the channel
    axis (same numerics contract as ``_bn_core``)."""
    sf = lax.stop_gradient(stat_shift.astype(_F32))
    axes = tuple(range(z.ndim - 1))
    c = z.astype(_F32) - sf
    m1 = jnp.mean(c, axis=axes)
    m2 = jnp.mean(jnp.square(c), axis=axes)
    return m1 + sf, jnp.maximum(m2 - jnp.square(m1), 0.0)


class FusedBottleneckImpl(Layer):
    """Runtime twin of conf.FusedBottleneck."""

    def init(self, key):
        lc = self.lc
        c_in, f = lc.n_in, lc.filters
        k1, k2, k3, k4 = jax.random.split(key, 4)
        dt = self.dtype
        p = {
            "W1": init_weights(k1, (1, 1, c_in, f), self.winit, dtype=dt),
            "g1": jnp.ones((f,), dt), "b1": jnp.zeros((f,), dt),
            "W2": init_weights(k2, (3, 3, f, f), self.winit, dtype=dt),
            "g2": jnp.ones((f,), dt), "b2": jnp.zeros((f,), dt),
            "W3": init_weights(k3, (1, 1, f, 4 * f), self.winit, dtype=dt),
            "g3": jnp.ones((4 * f,), dt), "b3": jnp.zeros((4 * f,), dt),
        }
        if lc.project:
            p["Wsc"] = init_weights(k4, (1, 1, c_in, 4 * f), self.winit, dtype=dt)
            p["gsc"] = jnp.ones((4 * f,), dt)
            p["bsc"] = jnp.zeros((4 * f,), dt)
        return p

    def init_state(self):
        f = self.lc.filters
        s = {"m1": jnp.zeros((f,), _F32), "v1": jnp.ones((f,), _F32),
             "m2": jnp.zeros((f,), _F32), "v2": jnp.ones((f,), _F32),
             "m3": jnp.zeros((4 * f,), _F32), "v3": jnp.ones((4 * f,), _F32)}
        if self.lc.project:
            s["msc"] = jnp.zeros((4 * f,), _F32)
            s["vsc"] = jnp.ones((4 * f,), _F32)
        return s

    # ------------------------------------------------------------------
    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        s = lc.stride
        xs = x[:, ::s, ::s, :] if s != 1 else x
        n, h, w_, c_in = xs.shape
        m = n * h * w_
        x2 = xs.reshape(m, c_in)
        if not train:
            return self._apply_eval(params, x, xs, x2, state), state, mask
        eps, decay = lc.eps, lc.decay
        f = lc.filters
        ones1 = jnp.ones((c_in,), _F32)
        zeros1 = jnp.zeros((c_in,), _F32)

        # c1 (1×1, stride folded into the slice) + bn1 stats in-epilogue
        z1, mean1, var1 = fused_matmul_bn(
            x2, ones1, zeros1, params["W1"].reshape(c_in, f), state["m1"],
            False, False)
        sc1, sh1 = _affine(params["g1"], params["b1"], mean1, var1, eps)
        # normalize+relu must materialize for the 3×3 conv (XLA convs take
        # HBM operands) — one elementwise pass
        y1 = jnp.maximum(z1.astype(_F32) * sc1 + sh1, 0.0).astype(z1.dtype)
        z2 = nn_ops.conv2d.fn(y1.reshape(n, h, w_, f), params["W2"], None,
                              stride=(1, 1), padding="same")
        # bn2 stats: separate pass (its affine feeds c3's fused prologue,
        # so the normalize write/read pair is eliminated instead)
        mean2, var2 = _shifted_stats(z2, state["m2"])
        sc2, sh2 = _affine(params["g2"], params["b2"], mean2, var2, eps)
        z3, mean3, var3 = fused_matmul_bn(
            z2.reshape(m, f), sc2, sh2, params["W3"].reshape(f, 4 * f),
            state["m3"], True, True)
        sc3, sh3 = _affine(params["g3"], params["b3"], mean3, var3, eps)

        new_state = dict(state)
        if lc.project:
            zsc, meansc, varsc = fused_matmul_bn(
                x2, ones1, zeros1, params["Wsc"].reshape(c_in, 4 * f),
                state["msc"], False, False)
            scsc, shsc = _affine(params["gsc"], params["bsc"], meansc, varsc, eps)
            shortcut = zsc.astype(_F32) * scsc + shsc
            self._update_running(new_state, "sc", meansc, varsc, m, decay)
        else:
            shortcut = x2.astype(_F32)
        out = jnp.maximum(z3.astype(_F32) * sc3 + sh3 + shortcut, 0.0)
        out = out.astype(x.dtype).reshape(n, h, w_, 4 * f)
        for tag, mu, var in (("1", mean1, var1), ("2", mean2, var2),
                             ("3", mean3, var3)):
            self._update_running(new_state, tag, mu, var, m, decay)
        return out, new_state, mask

    @staticmethod
    def _update_running(state, tag, mean, var, count, decay):
        unbiased = var * count / max(count - 1, 1)
        state["m" + tag] = (decay * state["m" + tag]
                            + (1 - decay) * lax.stop_gradient(mean))
        state["v" + tag] = (decay * state["v" + tag]
                            + (1 - decay) * lax.stop_gradient(unbiased))

    def _apply_eval(self, params, x, xs, x2, state):
        lc = self.lc
        eps = lc.eps
        n, h, w_, c_in = xs.shape
        f = lc.filters
        dt = x.dtype

        def bn(z, tag):
            g, b = params["g" + tag], params["b" + tag]
            sc, sh = _affine(g, b, state["m" + tag], state["v" + tag], eps)
            return z.astype(_F32) * sc + sh

        y1 = jnp.maximum(bn(x2 @ params["W1"].reshape(c_in, f), "1"), 0.0)
        z2 = nn_ops.conv2d.fn(y1.astype(dt).reshape(n, h, w_, f),
                              params["W2"], None, stride=(1, 1),
                              padding="same")
        y2 = jnp.maximum(bn(z2, "2"), 0.0).astype(dt)
        z3 = bn(y2.reshape(-1, f) @ params["W3"].reshape(f, 4 * f), "3")
        if lc.project:
            shortcut = bn(x2 @ params["Wsc"].reshape(c_in, 4 * f), "sc")
        else:
            shortcut = x2.astype(_F32)
        out = jnp.maximum(z3 + shortcut, 0.0)
        return out.astype(dt).reshape(n, h, w_, 4 * f)
