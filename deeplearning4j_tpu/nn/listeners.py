"""Training listeners — org/deeplearning4j/optimize/listeners parity.

Reference parity:
  * TrainingListener.java iface: iterationDone / onEpochStart / onEpochEnd /
    onForwardPass / onBackwardPass / onGradientCalculation.
  * ScoreIterationListener, PerformanceListener (samples/sec + memory),
    TimeIterationListener, CollectScoresIterationListener, CheckpointListener
    (periodic save with retention policy), EvaluativeListener.

The listener API is user-visible surface in the reference, so the shape is
kept; model hooks call these from the host-side training loop (the device
step itself is one fused XLA program — listeners observe per-iteration host
state, exactly the granularity the reference offers).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


class TrainingListener:
    """TrainingListener.java analog. All hooks optional.

    ``fit_done`` / ``on_preemption`` extend the reference surface for the
    preemption-proof training tier (docs/ROBUSTNESS.md): fit calls
    ``fit_done`` once when the loop completes normally, and
    ``on_preemption`` when a graceful-preemption request (SIGTERM) makes
    it exit early — the checkpoint listener uses both to guarantee a
    final snapshot."""

    def iteration_done(self, model, iteration: int, epoch: int, score: float) -> None:
        pass

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass

    def fit_done(self, model) -> None:
        pass

    def on_preemption(self, model) -> None:
        pass


def notify_fit_done(model, listeners) -> None:
    """Fire ``fit_done`` across listeners (hasattr-guarded: user listeners
    written against the pre-preemption base class keep working)."""
    for lst in listeners:
        fn = getattr(lst, "fit_done", None)
        if fn is not None:
            try:
                fn(model)
            except Exception:
                logger.warning("fit_done listener %r raised", lst,
                               exc_info=True)


def notify_preemption(model, listeners) -> None:
    """Graceful-preemption exit: fire ``on_preemption`` (the checkpoint
    listener's final synchronous snapshot), count + log the event. A
    raising listener cannot block the clean exit — the grace period is
    finite."""
    from deeplearning4j_tpu import observe

    # all logging for the preemption request happens HERE, at the polling
    # site — faults.request_preemption() runs inside a signal handler and
    # must stay async-signal-safe (no locks)
    observe.metrics().counter("dl4j_tpu_train_preemptions_total").inc()
    observe.log_event(
        "train_preempt", phase="snapshot",
        iteration=int(getattr(model, "iteration_count",
                              getattr(model, "_step", 0))))
    logger.warning("preemption requested — taking a final snapshot and "
                   "exiting the fit loop cleanly")
    for lst in listeners:
        fn = getattr(lst, "on_preemption", None)
        if fn is not None:
            try:
                fn(model)
            except Exception:
                logger.warning("on_preemption listener %r raised", lst,
                               exc_info=True)
    logger.warning("fit exiting cleanly on preemption request "
                   "(final snapshot taken)")


class ScoreIterationListener(TrainingListener):
    """ScoreIterationListener.java: log score every N iterations."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.print_iterations == 0:
            logger.info("Score at iteration %d is %s", iteration, score)


class PerformanceListener(TrainingListener):
    """PerformanceListener.java: throughput (samples/sec, batches/sec)."""

    def __init__(self, frequency: int = 10, report_score: bool = False):
        self.frequency = max(1, frequency)
        self.report_score = report_score
        self._last_time = None
        self._last_iter = 0
        self.history: List[Dict[str, float]] = []

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time, self._last_iter = now, iteration
            return
        if iteration - self._last_iter >= self.frequency:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            batch = getattr(model, "last_batch_size", 0)
            rec = {
                "iteration": iteration,
                "batches_per_sec": iters / dt,
                "samples_per_sec": iters * batch / dt,
                "iter_ms": 1000.0 * dt / iters,
            }
            self.history.append(rec)
            # re-based onto the process-wide registry (observe/): the same
            # throughput numbers the log line carries become scrapeable
            # gauges on /metrics
            from deeplearning4j_tpu import observe

            m = observe.metrics()
            m.gauge("dl4j_tpu_examples_per_sec").set(rec["samples_per_sec"])
            m.gauge("dl4j_tpu_batches_per_sec").set(rec["batches_per_sec"])
            msg = (f"iteration {iteration}: {rec['batches_per_sec']:.1f} batches/sec, "
                   f"{rec['samples_per_sec']:.1f} samples/sec, {rec['iter_ms']:.2f} ms/iter")
            if self.report_score:
                msg += f", score {score}"
            logger.info(msg)
            self._last_time, self._last_iter = now, iteration


class TimeIterationListener(TrainingListener):
    """TimeIterationListener.java: ETA logging."""

    def __init__(self, total_iterations: int, frequency: int = 50):
        self.total = total_iterations
        self.frequency = max(1, frequency)
        self._start = None

    def iteration_done(self, model, iteration, epoch, score):
        if self._start is None:
            self._start = time.perf_counter()
            return
        if iteration and iteration % self.frequency == 0:
            elapsed = time.perf_counter() - self._start
            remaining = elapsed / iteration * max(self.total - iteration, 0)
            logger.info("iteration %d/%d — est. remaining %.0fs", iteration, self.total, remaining)


class CollectScoresIterationListener(TrainingListener):
    """CollectScoresIterationListener.java: record (iteration, score) pairs."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class EvaluativeListener(TrainingListener):
    """EvaluativeListener.java: run evaluation every N iterations/epochs."""

    def __init__(self, iterator, frequency: int = 1, unit: str = "epoch"):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.unit = unit
        self.evaluations: List[Any] = []

    def _evaluate(self, model):
        e = model.evaluate(self.iterator)
        self.evaluations.append(e)
        logger.info("EvaluativeListener accuracy: %.4f", e.accuracy())

    def iteration_done(self, model, iteration, epoch, score):
        if self.unit == "iteration" and iteration and iteration % self.frequency == 0:
            self._evaluate(model)

    def on_epoch_end(self, model):
        if self.unit == "epoch":
            self._evaluate(model)


class CheckpointListener(TrainingListener):
    """CheckpointListener.java: periodic model save with retention.

    save_every_n_iterations / save_every_n_epochs; keep_last N deletes older
    checkpoints (reference keepLast/keepEvery retention policy).
    """

    def __init__(self, directory: str, save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None, keep_last: Optional[int] = None):
        self.dir = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str):
        from deeplearning4j_tpu.nn.serde import save_model

        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        save_model(model, path)
        self.saved.append(path)
        if self.keep_last and len(self.saved) > self.keep_last:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)
        logger.info("checkpoint saved: %s", path)

    def iteration_done(self, model, iteration, epoch, score):
        if self.every_iter and iteration and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model):
        if self.every_epoch:
            ep = getattr(model, "epoch_count", 0)
            if ep % self.every_epoch == 0:
                self._save(model, f"epoch_{ep}")
