"""Updaters (optimizer math) + learning-rate schedules.

Reference parity:
  * ND4J ``GradientUpdater`` impls (org/nd4j/linalg/learning/ — AdamUpdater,
    NesterovsUpdater, RmsPropUpdater, …) and their config twins
    (org/nd4j/linalg/learning/config/Adam.java etc.): stateful in-place
    view-buffer updates over the flattened gradient.
  * ISchedule impls (org/nd4j/linalg/schedule/ — StepSchedule,
    ExponentialSchedule, InverseSchedule, PolySchedule, SigmoidSchedule,
    MapSchedule, CycleSchedule).

TPU-native realization: each updater is a pure function
``(grad, state, lr, step) -> (update, new_state)`` applied leaf-wise over the
param pytree inside the single compiled train step (the reference's separate
updater pass fuses away). The update MATH matches the reference exactly so
parity tests can compare trajectories; optax exists in-env but we keep our own
transparent impls for exact-parity control, exposing ``as_optax()`` adapters.

State is a dict of pytrees (like the reference's single flat
``updaterStateViewArray`` carved into per-updater views — here a pytree keeps
the same exact-resume capability, see serde.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Schedules (ISchedule analog). All are pure fns of (initial leaning rate
# params..., iteration, epoch) evaluated inside jit — step is a traced scalar.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Base schedule: fixed value (the no-schedule default)."""

    value: float = 1e-3

    def __call__(self, iteration, epoch=None):
        return jnp.asarray(self.value, jnp.float32)

    # -- JSON round trip (Jackson-polymorphic analog) -----------------------
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["Schedule"]:
        if d is None:
            return None
        d = dict(d)
        cls = _SCHEDULES[d.pop("@type")]
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class StepSchedule(Schedule):
    """value * decay^floor(iter / step) — reference StepSchedule.java."""

    decay_rate: float = 0.1
    step: float = 1000.0

    def __call__(self, iteration, epoch=None):
        it = jnp.asarray(iteration, jnp.float32)
        return self.value * self.decay_rate ** jnp.floor(it / self.step)


@dataclasses.dataclass(frozen=True)
class ExponentialSchedule(Schedule):
    """value * gamma^iter — reference ExponentialSchedule.java."""

    gamma: float = 0.99

    def __call__(self, iteration, epoch=None):
        return self.value * self.gamma ** jnp.asarray(iteration, jnp.float32)


@dataclasses.dataclass(frozen=True)
class InverseSchedule(Schedule):
    """value / (1 + gamma*iter)^power — reference InverseSchedule.java."""

    gamma: float = 0.01
    power: float = 1.0

    def __call__(self, iteration, epoch=None):
        it = jnp.asarray(iteration, jnp.float32)
        return self.value / (1.0 + self.gamma * it) ** self.power


@dataclasses.dataclass(frozen=True)
class PolySchedule(Schedule):
    """value * (1 - iter/maxIter)^power — reference PolySchedule.java."""

    power: float = 1.0
    max_iter: int = 10000

    def __call__(self, iteration, epoch=None):
        it = jnp.asarray(iteration, jnp.float32)
        frac = jnp.clip(it / float(self.max_iter), 0.0, 1.0)
        return self.value * (1.0 - frac) ** self.power


@dataclasses.dataclass(frozen=True)
class SigmoidSchedule(Schedule):
    """value / (1 + exp(-gamma*(iter-stepSize))) — reference SigmoidSchedule."""

    gamma: float = 0.01
    step_size: int = 1000

    def __call__(self, iteration, epoch=None):
        it = jnp.asarray(iteration, jnp.float32)
        return self.value / (1.0 + jnp.exp(-self.gamma * (it - self.step_size)))


@dataclasses.dataclass(frozen=True)
class CycleSchedule(Schedule):
    """1cycle policy (reference CycleSchedule.java): ramp up then anneal."""

    initial_lr: float = 1e-4
    max_lr: float = 1e-2
    cycle_length: int = 1000
    annealing_length: int = 100
    annealing_decay: float = 0.1

    def __call__(self, iteration, epoch=None):
        it = jnp.asarray(iteration, jnp.float32)
        pos = jnp.mod(it, float(self.cycle_length))
        up = float(self.cycle_length - self.annealing_length) / 2.0
        lr_up = self.initial_lr + (self.max_lr - self.initial_lr) * (pos / up)
        lr_down = self.max_lr - (self.max_lr - self.initial_lr) * ((pos - up) / up)
        ann_pos = (pos - (self.cycle_length - self.annealing_length)) / float(
            self.annealing_length
        )
        lr_ann = self.initial_lr * (
            1.0 + ann_pos * (self.annealing_decay - 1.0)
        )
        lr = jnp.where(pos < up, lr_up, jnp.where(pos < 2 * up, lr_down, lr_ann))
        return lr


@dataclasses.dataclass(frozen=True)
class MapSchedule(Schedule):
    """Piecewise-constant from an {iteration: lr} map — reference MapSchedule."""

    values: Tuple[Tuple[int, float], ...] = ()

    def __call__(self, iteration, epoch=None):
        it = jnp.asarray(iteration, jnp.float32)
        pts = sorted(self.values)
        lr = jnp.asarray(self.value, jnp.float32)
        for start, v in pts:
            lr = jnp.where(it >= start, v, lr)
        return lr

    def to_dict(self) -> Dict[str, Any]:
        return {
            "@type": "MapSchedule",
            "value": self.value,
            "values": [list(p) for p in self.values],
        }

    @staticmethod
    def _from(value, values):
        return MapSchedule(value=value, values=tuple((int(a), float(b)) for a, b in values))


_SCHEDULES = {
    c.__name__: c
    for c in [
        Schedule,
        StepSchedule,
        ExponentialSchedule,
        InverseSchedule,
        PolySchedule,
        SigmoidSchedule,
        CycleSchedule,
    ]
}
_SCHEDULES["MapSchedule"] = MapSchedule._from  # type: ignore[assignment]


# ---------------------------------------------------------------------------
# Updaters (GradientUpdater analog). Pure leaf-wise transforms.
# ---------------------------------------------------------------------------


def _fused_updater_enabled() -> bool:
    """``DL4J_TPU_FUSED_UPDATER`` opt-out, read at trace time (train steps
    re-read it only on recompile — same contract as the fusion passes)."""
    import os

    v = os.environ.get("DL4J_TPU_FUSED_UPDATER", "1").strip().lower()
    return v not in ("0", "false", "off", "no")


@dataclasses.dataclass(frozen=True)
class Updater:
    """Base updater config. Subclasses define the exact reference math.

    ``learning_rate`` may be a float or a Schedule. ``init_state`` /
    ``apply`` operate on a single leaf; MultiLayerUpdater maps them over the
    param pytree (the reference's per-param UpdaterBlock decomposition).
    """

    learning_rate: Any = 1e-3

    def lr(self, iteration, epoch=None):
        if isinstance(self.learning_rate, Schedule):
            return self.learning_rate(iteration, epoch)
        return jnp.asarray(self.learning_rate, jnp.float32)

    # state: dict name -> array shaped like the param leaf
    def init_state(self, param) -> Dict[str, jax.Array]:
        return {}

    def apply(self, grad, state, lr, step):
        """Return (update, new_state); params -= update downstream."""
        raise NotImplementedError

    # -- fused step (ops/pallas_updater.py) ---------------------------------
    def _fusable(self) -> bool:
        """Only the exact catalog classes route through the registry op: a
        user subclass overriding ``apply`` must keep its override."""
        return UPDATERS.get(type(self).__name__) is type(self)

    def fused_hyper(self) -> Dict[str, float]:
        """Constructor fields as static kwargs for the fused registry op
        (``learning_rate`` excluded — the scheduled lr rides as a traced
        scalar)."""
        return {f.name: float(getattr(self, f.name))
                for f in dataclasses.fields(self)
                if f.name != "learning_rate"}

    def apply_fused(self, param, grad, state, lr, step):
        """One fused optimizer step: ``(new_param, new_state)``.

        Routes through the ``fused_updater_step`` registry op so the TPU
        platform helper (one Pallas kernel reading param/grad/state once)
        can take the leaf when the tuning table says it wins; the generic
        impl calls this class's own ``apply``, so trajectories are
        bit-identical to the unfused path everywhere. Opt-out:
        ``DL4J_TPU_FUSED_UPDATER=0`` (falls back to ``apply`` inline)."""
        if _fused_updater_enabled() and self._fusable():
            from deeplearning4j_tpu.ops.registry import registry

            keys = sorted(state)
            out = registry().get("fused_updater_step")(
                param, grad, lr, step, *(state[k] for k in keys),
                kind=type(self).__name__, **self.fused_hyper())
            return out[0], dict(zip(keys, out[1:]))
        u, new_state = self.apply(grad, state, lr, step)
        return param - u, new_state

    def to_dict(self) -> Dict[str, Any]:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Schedule):
                v = {"__schedule__": v.to_dict()}
            d[f.name] = v
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Updater":
        d = dict(d)
        cls = UPDATERS[d.pop("@type")]
        for k, v in list(d.items()):
            if isinstance(v, dict) and "__schedule__" in v:
                d[k] = Schedule.from_dict(v["__schedule__"])
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class Sgd(Updater):
    """SgdUpdater: update = lr * g."""

    learning_rate: Any = 1e-1

    def apply(self, grad, state, lr, step):
        return lr * grad, state


@dataclasses.dataclass(frozen=True)
class NoOp(Updater):
    """NoOpUpdater: passes the raw gradient through (update = g)."""

    def apply(self, grad, state, lr, step):
        return grad, state


@dataclasses.dataclass(frozen=True)
class Frozen(Updater):
    """The FrozenLayer effect at the updater level: update is exactly zero,
    so the layer's params never move (reference FrozenLayer zeroes the
    gradient in backprop; here the layer stays in the fused step but its
    update is dropped)."""

    learning_rate: Any = 0.0

    def apply(self, grad, state, lr, step):
        return jnp.zeros_like(grad), state


@dataclasses.dataclass(frozen=True)
class Nesterovs(Updater):
    """NesterovsUpdater (Nesterov momentum).

    Reference math (NesterovsUpdater.java): vPrev = v; v = mu*v - lr*g;
    params += mu*vPrev - (1+mu)*v. We return `update` s.t. params -= update.
    """

    learning_rate: Any = 1e-1
    momentum: float = 0.9

    def init_state(self, param):
        return {"v": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, step):
        mu = self.momentum
        v_prev = state["v"]
        v = mu * v_prev - lr * grad
        # Sutskever form: params += (1+mu)*v - mu*vPrev; with our
        # params -= update convention, update = mu*vPrev - (1+mu)*v.
        update = mu * v_prev - (1 + mu) * v
        return update, {"v": v}


@dataclasses.dataclass(frozen=True)
class AdaGrad(Updater):
    """AdaGradUpdater: h += g²; update = lr * g / (sqrt(h) + eps)."""

    learning_rate: Any = 1e-1
    epsilon: float = 1e-6

    def init_state(self, param):
        return {"h": jnp.full_like(param, self.epsilon)}

    def apply(self, grad, state, lr, step):
        h = state["h"] + grad * grad
        update = lr * grad / (jnp.sqrt(h) + self.epsilon)
        return update, {"h": h}


@dataclasses.dataclass(frozen=True)
class RmsProp(Updater):
    """RmsPropUpdater: g2 = d*g2 + (1-d)*g²; update = lr*g/sqrt(g2+eps)."""

    learning_rate: Any = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"g2": jnp.full_like(param, self.epsilon)}

    def apply(self, grad, state, lr, step):
        g2 = self.rms_decay * state["g2"] + (1 - self.rms_decay) * grad * grad
        update = grad * lr / jnp.sqrt(g2 + self.epsilon)
        return update, {"g2": g2}


@dataclasses.dataclass(frozen=True)
class AdaDelta(Updater):
    """AdaDeltaUpdater: rho-averaged g² and Δ² ratio; lr-free."""

    rho: float = 0.95
    epsilon: float = 1e-6

    def init_state(self, param):
        return {"msg": jnp.zeros_like(param), "msdx": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, step):
        msg = self.rho * state["msg"] + (1 - self.rho) * grad * grad
        dx = (
            jnp.sqrt(state["msdx"] + self.epsilon)
            / jnp.sqrt(msg + self.epsilon)
        ) * grad
        msdx = self.rho * state["msdx"] + (1 - self.rho) * dx * dx
        return dx, {"msg": msg, "msdx": msdx}


@dataclasses.dataclass(frozen=True)
class Adam(Updater):
    """AdamUpdater — exact reference math incl. bias correction.

    m = b1*m + (1-b1)*g ; v = b2*v + (1-b2)*g²
    alpha_t = lr * sqrt(1-b2^t)/(1-b1^t) ; update = alpha_t * m / (sqrt(v)+eps)
    """

    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, step):
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        alpha = lr * jnp.sqrt(1 - self.beta2**t) / (1 - self.beta1**t)
        update = alpha * m / (jnp.sqrt(v) + self.epsilon)
        return update, {"m": m, "v": v}


@dataclasses.dataclass(frozen=True)
class AdaMax(Updater):
    """AdaMaxUpdater: v = max(b2*v, |g|); update = lr/(1-b1^t) * m/v."""

    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "u": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, step):
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        u = jnp.maximum(self.beta2 * state["u"], jnp.abs(grad))
        update = lr / (1 - self.beta1**t) * m / (u + self.epsilon)
        return update, {"m": m, "u": u}


@dataclasses.dataclass(frozen=True)
class Nadam(Updater):
    """NadamUpdater: Nesterov-accelerated Adam (reference math)."""

    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def apply(self, grad, state, lr, step):
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        m_hat = m / (1 - self.beta1**t)
        v_hat = v / (1 - self.beta2**t)
        update = (
            lr
            * (self.beta1 * m_hat + (1 - self.beta1) * grad / (1 - self.beta1**t))
            / (jnp.sqrt(v_hat) + self.epsilon)
        )
        return update, {"m": m, "v": v}


@dataclasses.dataclass(frozen=True)
class AmsGrad(Updater):
    """AMSGradUpdater: Adam with max-tracked second moment."""

    learning_rate: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {
            "m": jnp.zeros_like(param),
            "v": jnp.zeros_like(param),
            "vhat": jnp.zeros_like(param),
        }

    def apply(self, grad, state, lr, step):
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        vhat = jnp.maximum(state["vhat"], v)
        alpha = lr * jnp.sqrt(1 - self.beta2**t) / (1 - self.beta1**t)
        update = alpha * m / (jnp.sqrt(vhat) + self.epsilon)
        return update, {"m": m, "v": v, "vhat": vhat}


UPDATERS = {
    c.__name__: c
    for c in [Sgd, NoOp, Frozen, Nesterovs, AdaGrad, RmsProp, AdaDelta, Adam,
              AdaMax, Nadam, AmsGrad]
}


def get_updater(spec) -> Updater:
    """Resolve an updater from an Updater, name, or dict."""
    if isinstance(spec, Updater):
        return spec
    if isinstance(spec, str):
        return UPDATERS[spec]()
    if isinstance(spec, dict):
        return Updater.from_dict(spec)
    raise TypeError(f"cannot resolve updater from {spec!r}")


def as_optax(updater: Updater):
    """Adapter: wrap an Updater as an optax.GradientTransformation."""
    import optax

    def init_fn(params):
        return {
            "state": jax.tree.map(updater.init_state, params),
            "step": jnp.zeros((), jnp.int32),
        }

    def update_fn(grads, opt_state, params=None):
        step = opt_state["step"]
        lr = updater.lr(step)
        flat_g, treedef = jax.tree.flatten(grads)
        flat_s = treedef.flatten_up_to(opt_state["state"])
        ups, news = [], []
        for g, s in zip(flat_g, flat_s):
            u, ns = updater.apply(g, s, lr, step)
            ups.append(-u)
            news.append(ns)
        return treedef.unflatten(ups), {
            "state": treedef.unflatten(news),
            "step": step + 1,
        }

    return optax.GradientTransformation(init_fn, update_fn)
