"""Transfer learning — graft/freeze/replace layers on an existing model.

Reference parity:
  * org/deeplearning4j/nn/transferlearning/TransferLearning.java (Builder:
    fineTuneConfiguration, setFeatureExtractor (freeze up to layer),
    removeOutputLayer/removeLayersFromOutput, addLayer,
    nOutReplace), FineTuneConfiguration.java, TransferLearningHelper.java
    (featurize: run frozen part once, train only the head).

TPU-native realization: frozen layers get a Frozen updater (zero update) (their params stay
bit-identical — the FrozenLayer effect) while remaining in the single jitted
step; TransferLearningHelper precomputes frozen-prefix activations with a
jitted forward so head-only epochs skip the backbone entirely.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, List, Optional

import numpy as np

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Frozen, get_updater
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator


@dataclasses.dataclass
class FineTuneConfiguration:
    """FineTuneConfiguration.java: overrides applied to non-frozen layers."""

    updater: Any = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    seed: Optional[int] = None


def _apply_fine_tune(conf, ftc: Optional[FineTuneConfiguration]) -> None:
    """Apply FineTuneConfiguration overrides to a network conf (shared by
    the MultiLayerNetwork and ComputationGraph builders)."""
    if ftc is None:
        return
    if ftc.updater is not None:
        conf.updater = get_updater(ftc.updater)
    if ftc.l1 is not None:
        conf.l1 = ftc.l1
    if ftc.l2 is not None:
        conf.l2 = ftc.l2
    if ftc.weight_decay is not None:
        conf.weight_decay = ftc.weight_decay
    if ftc.seed is not None:
        conf.seed = ftc.seed


class TransferLearningBuilder:
    """TransferLearning.Builder analog for MultiLayerNetwork."""

    def __init__(self, net: MultiLayerNetwork):
        self._src = net
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        self._remove_from: Optional[int] = None
        self._added: List[C.LayerConf] = []
        self._n_out_replace: dict = {}

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, layer_idx: int):
        """Freeze layers [0..layer_idx] (inclusive) — setFeatureExtractor."""
        self._freeze_until = layer_idx
        return self

    def remove_output_layer(self):
        self._remove_from = len(self._src.conf.layers) - 1
        return self

    def remove_layers_from_output(self, n: int):
        self._remove_from = len(self._src.conf.layers) - n
        return self

    def n_out_replace(self, layer_idx: int, n_out: int, weight_init: str = "xavier"):
        """Replace layer's n_out (re-initializing it and the next layer's
        n_in) — nOutReplace."""
        self._n_out_replace[layer_idx] = (n_out, weight_init)
        return self

    def add_layer(self, lc: C.LayerConf):
        self._added.append(lc)
        return self

    def build(self) -> MultiLayerNetwork:
        src = self._src
        old_conf = src.conf
        keep_n = self._remove_from if self._remove_from is not None else len(old_conf.layers)
        new_layers = [copy.deepcopy(lc) for lc in old_conf.layers[:keep_n]]
        reinit = set()  # layer indices whose params must be re-initialized

        # n_out replacement (and downstream n_in fix-up)
        for idx, (n_out, winit) in self._n_out_replace.items():
            new_layers[idx] = dataclasses.replace(new_layers[idx], n_out=n_out,
                                                  weight_init=winit)
            reinit.add(idx)
            if idx + 1 < len(new_layers) and hasattr(new_layers[idx + 1], "n_in"):
                new_layers[idx + 1] = dataclasses.replace(new_layers[idx + 1], n_in=n_out)
                reinit.add(idx + 1)

        # frozen layers: Frozen updater (zero update — the FrozenLayer effect)
        if self._freeze_until is not None:
            for i in range(min(self._freeze_until + 1, len(new_layers))):
                new_layers[i] = dataclasses.replace(new_layers[i], updater=Frozen())

        # fine-tune overrides on non-frozen kept layers
        new_conf = copy.deepcopy(old_conf)
        _apply_fine_tune(new_conf, self._fine_tune)

        # appended layers: infer n_in from the previous output type
        for lc in self._added:
            if hasattr(lc, "n_in") and getattr(lc, "n_in") == 0 and new_layers:
                itype = None
                # recompute shapes through the kept stack
                it = new_conf.input_type
                for i, kept in enumerate(new_layers):
                    pre = new_conf.preprocessors.get(i)
                    if pre is not None and isinstance(pre, C.FeedForwardToCnnPreProcessor):
                        it = C.InputType.convolutional(pre.height, pre.width, pre.channels)
                    elif pre is not None and isinstance(pre, C.CnnToFeedForwardPreProcessor):
                        it = C.InputType.feed_forward(pre.height * pre.width * pre.channels)
                    it = kept.output_type(it)
                size = it.size if it.kind in ("feedforward", "recurrent") else it.flat_size()
                lc = dataclasses.replace(lc, n_in=size)
            new_layers.append(lc)
            reinit.add(len(new_layers) - 1)

        new_conf.layers = new_layers
        # drop preprocessors beyond the kept stack
        new_conf.preprocessors = {i: p for i, p in new_conf.preprocessors.items()
                                  if i < len(new_layers)}
        out = MultiLayerNetwork(new_conf).init()
        # copy params for kept, non-reinitialized layers
        for i in range(len(new_layers)):
            if i < keep_n and i not in reinit and i < len(src.params):
                out.params[i] = copy.deepcopy(src.params[i])
                out.net_state[i] = copy.deepcopy(src.net_state[i])
        return out


class TransferLearning:
    """Entry point: TransferLearning.builder(net)...build()."""

    @staticmethod
    def builder(net: MultiLayerNetwork) -> TransferLearningBuilder:
        return TransferLearningBuilder(net)


class TransferLearningHelper:
    """TransferLearningHelper.java: featurize-then-train-head.

    Runs the frozen prefix ONCE per dataset (jitted forward) and trains only
    the unfrozen tail on the cached activations — the big fine-tune speedup
    when the backbone dominates compute.
    """

    def __init__(self, net: MultiLayerNetwork, frozen_until: int):
        self.net = net
        self.frozen_until = frozen_until
        import jax

        @jax.jit
        def prefix_forward(params, net_state, x, mask):
            from deeplearning4j_tpu.nn.layers import apply_preprocessor

            for i, layer in enumerate(net.layers[: frozen_until + 1]):
                x = apply_preprocessor(net.conf.preprocessors.get(i), x)
                x, _, mask = layer.apply(params[i], x, net_state[i],
                                         train=False, rng=None, mask=mask)
            return x, mask

        self._prefix = prefix_forward
        # head net: layers after the frozen prefix
        head_conf = copy.deepcopy(net.conf)
        head_conf.layers = [copy.deepcopy(lc) for lc in net.conf.layers[frozen_until + 1 :]]
        head_conf.preprocessors = {
            i - (frozen_until + 1): p for i, p in net.conf.preprocessors.items()
            if i > frozen_until}
        self.head = MultiLayerNetwork(head_conf)
        self.head.init(params=[copy.deepcopy(p) for p in net.params[frozen_until + 1 :]])

    def featurize(self, ds: DataSet) -> DataSet:
        import numpy as np

        from deeplearning4j_tpu import observe

        fm = None if ds.features_mask is None else np.asarray(ds.features_mask,
                                                              np.float32)
        x = np.asarray(ds.features, np.float32)
        # ledger the frozen-prefix forward: featurize runs once per dataset,
        # so a distinct dataset shape is an HONEST new_shape event here
        observe.note_jit_signature(
            self._prefix, graph="transfer", key="prefix_forward",
            signature=observe.signature_of(x=x, mask=fm))
        feats, out_mask = self._prefix(
            self.net.params, self.net.net_state, x, fm)
        return DataSet(np.asarray(feats), ds.labels,
                       None if out_mask is None else np.asarray(out_mask),
                       ds.labels_mask)

    def fit_featurized(self, ds_or_iter, epochs: int = 1, batch_size: int = 32):
        if isinstance(ds_or_iter, DataSet):
            self.head.fit(ListDataSetIterator(ds_or_iter, batch_size=batch_size),
                          epochs=epochs)
        else:
            self.head.fit(ds_or_iter, epochs=epochs)
        # sync head params AND state (BN running stats) back into the full net
        for j, p in enumerate(self.head.params):
            self.net.params[self.frozen_until + 1 + j] = p
            self.net.net_state[self.frozen_until + 1 + j] = self.head.net_state[j]

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self.head


class GraphTransferLearningBuilder:
    """TransferLearning.GraphBuilder analog for ComputationGraph: freeze a
    feature extractor by vertex name, remove/replace heads, graft new
    layers/vertices, and keep the source's params for untouched layers."""

    def __init__(self, net):
        from deeplearning4j_tpu.nn import graph as G

        self._G = G
        self._src = net
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_at: List[str] = []
        self._removed: List[str] = []
        self._added: List[Any] = []  # _GraphNode
        self._n_out_replace: dict = {}
        self._outputs: Optional[List[str]] = None

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, *names: str):
        """Freeze the named vertices and every ancestor feeding them."""
        self._freeze_at.extend(names)
        return self

    def remove_vertex_and_connections(self, name: str):
        self._removed.append(name)
        return self

    def n_out_replace(self, layer_name: str, n_out: int,
                      weight_init: str = "xavier"):
        self._n_out_replace[layer_name] = (n_out, weight_init)
        return self

    def add_layer(self, name: str, lc: C.LayerConf, *inputs: str):
        self._added.append(self._G._GraphNode(name=name, kind="layer",
                                              layer=lc, inputs=list(inputs)))
        return self

    def add_vertex(self, name: str, vertex, *inputs: str):
        if isinstance(vertex, C.LayerConf):
            return self.add_layer(name, vertex, *inputs)
        self._added.append(self._G._GraphNode(name=name, kind="vertex",
                                              vertex=vertex,
                                              inputs=list(inputs)))
        return self

    def set_outputs(self, *names: str):
        self._outputs = list(names)
        return self

    def build(self):
        G = self._G
        src = self._src
        conf = copy.deepcopy(src.conf)

        # removals: the final produced-set validation below catches any
        # kept node left consuming a removed name (re-added names satisfy
        # it), and ComputationGraph.__init__ re-toposorts, so no ordering
        # pass is needed here
        removed = set(self._removed)
        kept = [n for n in conf.nodes if n.name not in removed]

        reinit = set()
        # n_out replacement + consumer n_in fix-up (by graph edges)
        by_name = {n.name: n for n in kept}
        for lname, (n_out, winit) in self._n_out_replace.items():
            node = by_name[lname]
            node.layer = dataclasses.replace(node.layer, n_out=n_out,
                                             weight_init=winit)
            reinit.add(lname)
            for n in kept:
                if lname in n.inputs and n.layer is not None and hasattr(n.layer, "n_in"):
                    n.layer = dataclasses.replace(n.layer, n_in=n_out)
                    reinit.add(n.name)

        # freeze: named vertices + all ancestors
        if self._freeze_at:
            frozen = set()
            stack = list(self._freeze_at)
            while stack:
                cur = stack.pop()
                if cur in frozen or cur in conf.network_inputs:
                    continue
                frozen.add(cur)
                if cur in by_name:
                    stack.extend(by_name[cur].inputs)
            for n in kept:
                if n.name in frozen and n.layer is not None:
                    n.layer = dataclasses.replace(n.layer, updater=Frozen())

        _apply_fine_tune(conf, self._fine_tune)

        conf.nodes = kept + list(self._added)
        for a in self._added:
            reinit.add(a.name)
        if self._outputs is not None:
            conf.network_outputs = self._outputs
        produced = ({n.name for n in conf.nodes} | set(conf.network_inputs))
        for n in conf.nodes:
            for i in n.inputs:
                if i not in produced:
                    raise ValueError(
                        f"vertex '{n.name}' consumes '{i}', which no longer "
                        f"exists — re-add it or remove '{n.name}' too")
        for o in conf.network_outputs:
            if o not in produced:
                raise ValueError(
                    f"network output '{o}' no longer exists — call "
                    f"set_outputs() with the new head name(s)")

        out = G.ComputationGraph(conf).init()
        # copy params for kept, untouched layers
        for n in kept:
            if (n.kind == "layer" and n.name not in reinit
                    and src.params is not None and n.name in src.params):
                out.params[n.name] = copy.deepcopy(src.params[n.name])
                out.net_state[n.name] = copy.deepcopy(src.net_state[n.name])
        return out


def graph_transfer_builder(net) -> GraphTransferLearningBuilder:
    """TransferLearning.GraphBuilder(net) entry point."""
    return GraphTransferLearningBuilder(net)
