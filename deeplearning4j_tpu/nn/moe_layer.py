"""Mixture-of-Experts FFN layer (conf.MoELayer runtime twin).

GShard/Switch dispatch written as dense einsums over an explicit expert
axis, so that under ``ParallelWrapper`` with a mesh carrying an ``expert``
dimension and ``moe_ep_rules()`` param sharding, GSPMD partitions the
expert axis and inserts the all-to-all collectives itself — the TPU-native
expert-parallel recipe (scaling-book; no hand-written shard_map).

Routing: top-k (k=1 Switch, k=2 GShard default) with capacity
C = ceil(cf·S·k/E); assignments beyond capacity are dropped. A token whose
every assignment is dropped passes through as IDENTITY (the layer adds
``(1 - min(1, Σ dispatch)) · x``), never as zeros; combine weights
renormalize over the surviving assignments. Two scalars ride the layer
state:

* ``_aux_loss``   — Switch load-balance loss E·Σ f_e·P_e times aux_weight;
  the network step functions add every state ``_aux_loss`` to the training
  loss (gradient flows — state is computed inside the loss closure).
* ``_dropped_frac`` — fraction of token→expert assignments dropped at
  capacity (stop-gradient; a routing-health metric for listeners/UI).

Param names are expert-prefixed (Weg/We1/be1/We2/be2) so the data-parallel
TP rules never mis-match them; ``parallel.mesh.moe_ep_rules()`` maps them
onto the ``expert`` mesh axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers import Layer
from deeplearning4j_tpu.ops.weight_init import init_weights

_F32 = jnp.float32


class MoELayerImpl(Layer):
    def init(self, key):
        lc = self.lc
        d, h, e = lc.n_in, lc.d_hidden, lc.n_experts
        k1, k2, k3 = jax.random.split(key, 3)
        dt = self.dtype
        return {
            "Weg": init_weights(k1, (d, e), self.winit, dtype=dt),
            "We1": init_weights(k2, (e, d, h), self.winit, dtype=dt),
            "be1": jnp.zeros((e, h), dt),
            "We2": init_weights(k3, (e, h, d), self.winit, dtype=dt),
            "be2": jnp.zeros((e, d), dt),
        }

    def init_state(self):
        return {"_aux_loss": jnp.zeros((), _F32),
                "_dropped_frac": jnp.zeros((), _F32)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        e, k = lc.n_experts, int(lc.top_k)
        orig_shape = x.shape
        d = orig_shape[-1]
        xt = x.reshape(-1, d)                       # (S, d) tokens
        s = xt.shape[0]
        cap = max(1, int(-(-lc.capacity_factor * s * k // e)))

        logits = (xt @ params["Weg"]).astype(_F32)  # (S, E)
        gates = jax.nn.softmax(logits, axis=-1)

        # ---- top-k assignment with capacity (GShard positions) ----------
        dispatch = jnp.zeros((s, e, cap), _F32)
        combine = jnp.zeros((s, e, cap), _F32)
        remaining = gates
        chosen_masks = []
        weights = []
        counts = jnp.zeros((e,), _F32)              # tokens already placed
        kept = jnp.zeros((), _F32)
        for _ in range(k):
            idx = jnp.argmax(remaining, axis=-1)            # (S,)
            onehot = jax.nn.one_hot(idx, e, dtype=_F32)     # (S, E)
            w = jnp.sum(gates * onehot, axis=-1)            # (S,)
            # position of each token within its expert, priority = token
            # order (cumsum), offset by earlier-k placements
            pos = jnp.cumsum(onehot, axis=0) - onehot + counts  # (S, E)
            pos_t = jnp.sum(pos * onehot, axis=-1)              # (S,)
            fits = pos_t < cap
            kept = kept + jnp.sum(fits.astype(_F32))
            sel = onehot * fits[:, None].astype(_F32)           # (S, E)
            posh = jax.nn.one_hot(pos_t.astype(jnp.int32), cap,
                                  dtype=_F32)                   # (S, C)
            dispatch = dispatch + sel[:, :, None] * posh[:, None, :]
            combine = combine + (w[:, None, None] * sel[:, :, None]
                                 * posh[:, None, :])
            chosen_masks.append(onehot)
            weights.append(w)
            counts = counts + jnp.sum(sel, axis=0)
            remaining = remaining * (1.0 - onehot)
        # renormalize combine weights over the surviving assignments
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)

        # ---- expert FFN (dense over the expert axis; GSPMD partitions) --
        cd = x.dtype
        xin = jnp.einsum("sec,sd->ecd", dispatch.astype(cd), xt)   # (E,C,d)
        hdn = jnp.einsum("ecd,edh->ech", xin, params["We1"])
        hdn = self.activation(hdn + params["be1"][:, None, :])
        out_e = jnp.einsum("ech,ehd->ecd", hdn, params["We2"])
        out_e = out_e + params["be2"][:, None, :]
        y = jnp.einsum("sec,ecd->sd", combine.astype(cd), out_e)   # (S, d)

        # identity passthrough for fully-dropped tokens: a token whose every
        # top-k assignment fell past capacity has an all-zero dispatch row;
        # without this it would emit zeros and silently kill activations
        # under load (round-5 advice). kept_tok ∈ {0..k}; the clip makes the
        # passthrough exactly 1 for dropped tokens and 0 once any
        # assignment survived.
        kept_tok = jnp.sum(dispatch, axis=(1, 2))                  # (S,)
        y = y + jnp.clip(1.0 - kept_tok, 0.0, 1.0).astype(cd)[:, None] * xt

        # ---- aux loss + routing health ---------------------------------
        f_e = jnp.mean(chosen_masks[0], axis=0)        # top-1 token fraction
        p_e = jnp.mean(gates, axis=0)                  # mean gate prob
        aux = lc.aux_weight * e * jnp.sum(f_e * p_e)
        dropped = 1.0 - kept / (s * k)
        new_state = {"_aux_loss": aux if train else jnp.zeros((), _F32),
                     "_dropped_frac": lax.stop_gradient(dropped)}
        return y.reshape(orig_shape), new_state, mask
