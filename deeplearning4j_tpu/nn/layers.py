"""Runtime layers: pure ``init``/``apply`` functions per layer config.

Reference parity:
  * org/deeplearning4j/nn/layers/** — each reference layer hand-implements
    ``activate()`` (forward) and ``backpropGradient()`` (hand-written
    backward) against ND4J ops.
  * TPU-native realization: only the forward is written; the backward comes
    from jax.grad over the whole network (the reference's per-layer
    hand-written backprop dissolves — SURVEY §8.1). Layers are pure:
    ``apply(params, x, state, *, train, rng, mask) -> (y, new_state, mask)``.
    ``state`` carries non-trainable buffers (BatchNormalization running
    stats — the reference stores them as params excluded from updates).

Param naming matches the reference's param keys where they exist
("W", "b", "gamma", "beta", "mean", "var", "RW" for recurrent weights) so
flat-param export (params_flat) lines up for parity checks.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.ops import nn_ops
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.weight_init import init_weights

Params = Dict[str, jax.Array]
State = Dict[str, jax.Array]


class Layer:
    """Runtime twin of one LayerConf (org.deeplearning4j.nn.layers.BaseLayer)."""

    def __init__(self, net_conf: C.MultiLayerConfiguration, lc: C.LayerConf, itype: C.InputType):
        self.net_conf = net_conf
        self.lc = lc
        self.itype = itype  # input type AFTER preprocessor
        self.otype = lc.output_type(itype)
        self.activation = get_activation(net_conf.layer_activation(lc))
        self.winit = net_conf.layer_weight_init(lc)
        from deeplearning4j_tpu.nn.dtype import param_dtype

        self.dtype = param_dtype(net_conf.dtype)

    # -- override points ----------------------------------------------------
    def init(self, key) -> Params:
        return {}

    def init_state(self) -> State:
        return {}

    def apply(self, params: Params, x, state: State, *, train: bool, rng, mask=None):
        raise NotImplementedError

    # -- common helpers -----------------------------------------------------
    def _maybe_dropout(self, x, *, train: bool, rng):
        """Input dropout, reference layer-level `dropOut` semantics (applied
        to the layer INPUT, as in BaseLayer.applyDropOutIfNecessary)."""
        rate = self.lc.dropout
        if not rate or not train:
            return x
        return nn_ops.dropout.fn(x, rng, rate=rate)

    def n_params(self, params: Params) -> int:
        return sum(int(v.size) for v in params.values())


class DenseLayerImpl(Layer):
    """layers/feedforward/dense/DenseLayer.java: out = act(xW + b)."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        z = x @ params["W"]
        if "b" in params:
            z = z + params["b"]
        return self.activation(z), state, mask


class OutputLayerImpl(DenseLayerImpl):
    """layers/OutputLayer.java: dense + loss (loss applied by the network)."""


class LossLayerImpl(Layer):
    """layers/LossLayer.java: activation only; loss applied by the network."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return self.activation(x), state, mask


class EmbeddingLayerImpl(Layer):
    """layers/feedforward/embedding/EmbeddingLayer.java: ids -> rows."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if getattr(lc, "has_bias", False):
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        ids = x.astype(jnp.int32)
        if ids.ndim == 2 and ids.shape[-1] == 1:
            ids = ids[:, 0]
        out = params["W"][ids]
        if "b" in params:
            out = out + params["b"]
        return self.activation(out), state, mask


class EmbeddingSequenceLayerImpl(EmbeddingLayerImpl):
    """layers/feedforward/embedding/EmbeddingSequenceLayer.java.

    Input (N, T) int ids -> (N, T, F).
    """

    def apply(self, params, x, state, *, train, rng, mask=None):
        ids = x.astype(jnp.int32)
        if ids.ndim == 3 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        out = params["W"][ids]
        return self.activation(out), state, mask


class ConvolutionLayerImpl(Layer):
    """layers/convolution/ConvolutionLayer.java.

    Internal layout NHWC, kernel HWIO (SURVEY §8.3 layout policy; reference is
    NCHW/OIHW from its cuDNN heritage — accepted at the model edge, not here).
    """

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        p = {"W": init_weights(key, (kh, kw, lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def _conv_args(self):
        lc = self.lc
        if lc.convolution_mode == "same":
            padding = "same"
        else:
            ph, pw = C._pair(lc.padding)
            padding = ((ph, ph), (pw, pw))
        return dict(stride=C._pair(lc.stride), padding=padding, dilation=C._pair(lc.dilation))

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        if getattr(self.lc, "s2d_stem", False):
            z = self._s2d_stem_conv(x, params["W"], params.get("b"))
        else:
            z = nn_ops.conv2d.fn(x, params["W"], params.get("b"), **self._conv_args())
        return self.activation(z), state, mask

    def _s2d_stem_conv(self, x, W, b):
        """7×7/2 'same' conv lowered as 4×4/1 over a 2×2 space-to-depth input.

        Exact rewrite (MLPerf ResNet stem trick): pad the kernel to 8×8 with
        zeros on the high edge, regroup Wp[2α+da, 2β+db, c, f] into
        W2[α, β, (da·2+db)·C+c, f] (matching space_to_depth's channel order),
        and the stride-2 'same' conv becomes a stride-1 conv with pad (1,2).
        Gradients flow only into the canonical 7×7 entries (the pad is a
        constant), so training is bit-for-bit the same model.
        """
        lc = self.lc
        if (tuple(C._pair(lc.kernel)) != (7, 7) or tuple(C._pair(lc.stride)) != (2, 2)
                or tuple(C._pair(lc.dilation)) != (1, 1)
                or lc.convolution_mode != "same"
                or x.shape[1] % 2 or x.shape[2] % 2):
            return nn_ops.conv2d.fn(x, W, b, **self._conv_args())
        c_in, f = W.shape[2], W.shape[3]
        Wp = jnp.pad(W, ((0, 1), (0, 1), (0, 0), (0, 0)))
        W2 = (Wp.reshape(4, 2, 4, 2, c_in, f).transpose(0, 2, 1, 3, 4, 5)
              .reshape(4, 4, 4 * c_in, f))
        from deeplearning4j_tpu.ops import exec_op
        x2 = exec_op("space_to_depth", x, block_size=2)
        return nn_ops.conv2d.fn(x2, W2, b, stride=(1, 1), padding=((1, 2), (1, 2)))


class Deconvolution2DImpl(ConvolutionLayerImpl):
    """layers/convolution/Deconvolution2DLayer.java (transposed conv)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        if lc.convolution_mode == "same":
            pad = "same"
        else:
            # explicit pad must match output_type: oh = s*(h-1) + k - 2p
            pad = C._pair(lc.padding)
        z = nn_ops.deconv2d.fn(x, params["W"], params.get("b"), stride=C._pair(lc.stride), padding=pad)
        return self.activation(z), state, mask


class DepthwiseConvolution2DImpl(Layer):
    """layers/convolution/DepthwiseConvolution2DLayer.java."""

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        mult = getattr(lc, "depth_multiplier", 1)
        p = {"W": init_weights(key, (kh, kw, lc.n_in, mult), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_in * mult,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        pad = "same" if lc.convolution_mode == "same" else "valid"
        z = nn_ops.depthwise_conv2d.fn(
            x, params["W"], params.get("b"), stride=C._pair(lc.stride), padding=pad,
            dilation=C._pair(lc.dilation))
        return self.activation(z), state, mask


class SeparableConvolution2DImpl(Layer):
    """layers/convolution/SeparableConvolution2DLayer.java."""

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        mult = getattr(lc, "depth_multiplier", 1)
        k1, k2 = jax.random.split(key)
        p = {
            "dW": init_weights(k1, (kh, kw, lc.n_in, mult), self.winit, dtype=self.dtype),
            "pW": init_weights(k2, (1, 1, lc.n_in * mult, lc.n_out), self.winit, dtype=self.dtype),
        }
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        pad = "same" if lc.convolution_mode == "same" else "valid"
        z = nn_ops.separable_conv2d.fn(
            x, params["dW"], params["pW"], params.get("b"),
            stride=C._pair(lc.stride), padding=pad)
        return self.activation(z), state, mask


class SubsamplingLayerImpl(Layer):
    """layers/convolution/subsampling/SubsamplingLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        if lc.convolution_mode == "same":
            pad = "same"
        else:
            ph, pw = C._pair(lc.padding)
            pad = ((ph, ph), (pw, pw))
        kw = dict(kernel=C._pair(lc.kernel), stride=C._pair(lc.stride), padding=pad)
        if lc.pooling_type == "max":
            y = nn_ops.maxpool2d.fn(x, **kw)
        elif lc.pooling_type == "avg":
            y = nn_ops.avgpool2d.fn(x, **kw)
        elif lc.pooling_type == "pnorm":
            y = nn_ops.pnormpool2d.fn(x, p=lc.pnorm, **kw)
        else:
            raise ValueError(f"unknown pooling type {lc.pooling_type}")
        return y, state, mask


class Upsampling2DImpl(Layer):
    """layers/convolution/upsampling/Upsampling2D.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return nn_ops.upsampling2d.fn(x, size=C._pair(self.lc.size)), state, mask


class GlobalPoolingLayerImpl(Layer):
    """layers/pooling/GlobalPoolingLayer.java — conv NHWC (axes 1,2) or
    recurrent (axis 1 = time, mask-aware)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        pt = self.lc.pooling_type
        if x.ndim == 5:  # NDHWC
            axes = (1, 2, 3)
            m = None
        elif x.ndim == 4:  # NHWC
            axes = (1, 2)
            m = None
        else:  # (N, T, F)
            axes = (1,)
            m = mask
        if m is not None:
            m3 = m[..., None].astype(x.dtype)
            if pt == "avg":
                y = (x * m3).sum(axes) / jnp.maximum(m3.sum(axes), 1e-8)
            elif pt == "sum":
                y = (x * m3).sum(axes)
            elif pt == "max":
                y = jnp.where(m3 > 0, x, -jnp.inf).max(axes)
            else:
                y = ((jnp.abs(x) ** self.lc_pnorm()) * m3).sum(axes) ** (1.0 / self.lc_pnorm())
        else:
            if pt == "avg":
                y = x.mean(axes)
            elif pt == "sum":
                y = x.sum(axes)
            elif pt == "max":
                y = x.max(axes)
            else:
                y = (jnp.abs(x) ** self.lc_pnorm()).sum(axes) ** (1.0 / self.lc_pnorm())
        return y, state, None

    def lc_pnorm(self):
        return getattr(self.lc, "pnorm", 2)


class DiscretizationLayerImpl(Layer):
    """conf.DiscretizationLayer runtime: bucketize by static boundaries
    (keras semantics: index = number of boundaries <= x)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        bounds = jnp.asarray(self.lc.bin_boundaries, jnp.float32)
        idx = jnp.searchsorted(bounds, x.astype(jnp.float32), side="right")
        return idx.astype(jnp.int32), state, mask


class CategoryEncodingLayerImpl(Layer):
    """conf.CategoryEncodingLayer runtime: one_hot / multi_hot / count."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        oh = jax.nn.one_hot(x.astype(jnp.int32), lc.num_tokens,
                            dtype=jnp.float32)
        if lc.output_mode == "one_hot":
            # keras requires a trailing size-1 feature axis for one_hot and
            # squeezes it: (N, 1) -> (N, num_tokens)
            if oh.ndim >= 3 and oh.shape[-2] == 1:
                oh = oh.squeeze(-2)
            return oh, state, mask
        agg = jnp.sum(oh, axis=-2) if oh.ndim >= 2 else oh
        if lc.output_mode == "count":
            return agg, state, mask
        return jnp.minimum(agg, 1.0), state, mask  # multi_hot


class EinsumDenseLayerImpl(Layer):
    """conf.EinsumDenseLayer runtime (Keras EinsumDense parity): the
    weight shape is the equation's rhs operand dims; bias broadcasts on
    the declared bias shape."""

    def init(self, key):
        lc = self.lc
        # rhs operand dims come from the equation's second input spec sized
        # by (input feature dims, out_shape); Keras stores the built kernel
        # shape — we derive it the same way from equation + out_shape
        eq = lc.equation.replace(" ", "")
        ins_, out = eq.split("->")
        a_spec, b_spec = ins_.split(",")
        sizes = {}
        for ax, n in zip(reversed(out.replace("...", "")),
                         reversed(lc.out_shape)):
            sizes[ax] = int(n)
        # input labels size from the ACTUAL input dims, right-aligned:
        # recurrent → (timesteps, size), feedforward → (flat,); without
        # '...' the leading a_spec label is the batch axis
        if self.itype.kind == "recurrent":
            in_dims = (self.itype.timesteps, self.itype.size)
        else:
            in_dims = (self.itype.flat_size(),)
        labels_in = a_spec.replace("...", "")
        if "..." not in a_spec:
            labels_in = labels_in[1:]  # drop the explicit batch label
        for ax, n in zip(reversed(labels_in), reversed(in_dims)):
            sizes.setdefault(ax, int(n))
        missing = [ax for ax in b_spec.replace("...", "") if ax not in sizes]
        if missing:
            raise ValueError(
                f"EinsumDenseLayer: cannot size kernel labels {missing} "
                f"from equation '{lc.equation}', out_shape {lc.out_shape} "
                f"and input {self.itype} — give a fully-specified "
                f"out_shape (every kernel-only label must appear in the "
                f"output spec)")
        w_shape = tuple(sizes[ax] for ax in b_spec.replace("...", ""))
        p = {"W": init_weights(key, w_shape, self.winit, dtype=self.dtype)}
        if lc.bias_shape:
            p["b"] = jnp.zeros(tuple(lc.bias_shape), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        y = jnp.einsum(self.lc.equation, x, params["W"])
        if "b" in params:
            y = y + params["b"]
        return self.activation(y), state, mask


class DuelingQLayerImpl(Layer):
    """conf.DuelingQLayer runtime: Q = V + A − mean(A) (Wang et al.
    aggregation, the RL4J dueling head)."""

    def init(self, key):
        lc = self.lc
        k1, k2 = jax.random.split(key)
        return {"Wv": init_weights(k1, (lc.n_in, 1), self.winit, dtype=self.dtype),
                "bv": jnp.zeros((1,), self.dtype),
                "Wa": init_weights(k2, (lc.n_in, lc.n_actions), self.winit,
                                   dtype=self.dtype),
                "ba": jnp.zeros((lc.n_actions,), self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        v = x @ params["Wv"] + params["bv"]
        a = x @ params["Wa"] + params["ba"]
        q = v + a - jnp.mean(a, axis=-1, keepdims=True)
        return self.activation(q), state, mask


class BatchNormalizationImpl(Layer):
    """layers/normalization/BatchNormalization.java.

    gamma/beta trainable; running mean/var live in layer STATE (the reference
    keeps them in the param buffer but excludes them from updates — state is
    the functional equivalent). Reference decay semantics:
    running = decay * running + (1-decay) * batch.
    """

    def init(self, key) -> Params:
        n = self.lc.n_out
        if self.lc.lock_gamma_beta:
            return {}
        return {"gamma": jnp.ones((n,), self.dtype), "beta": jnp.zeros((n,), self.dtype)}

    def init_state(self) -> State:
        n = self.lc.n_out
        return {"mean": jnp.zeros((n,), self.dtype), "var": jnp.ones((n,), self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        gamma = params.get("gamma")
        beta = params.get("beta")
        if train:
            axes = tuple(range(x.ndim - 1))  # all but channel/feature
            y, new_mean, new_var = nn_ops.batch_norm_train(
                x, gamma, beta, state["mean"], state["var"],
                axis=axes, eps=lc.eps, momentum=lc.decay)
            return self.activation(y), {"mean": new_mean, "var": new_var}, mask
        y = nn_ops.batchnorm.fn(x, state["mean"], state["var"], gamma, beta, eps=lc.eps)
        return self.activation(y), state, mask


class LocalResponseNormalizationImpl(Layer):
    """layers/normalization/LocalResponseNormalization.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        y = nn_ops.local_response_normalization.fn(
            x, depth=lc.n, bias=lc.k, alpha=lc.alpha, beta=lc.beta)
        return y, state, mask


class ActivationLayerImpl(Layer):
    """layers/ActivationLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return self.activation(x), state, mask


class DropoutLayerImpl(Layer):
    """layers/DropoutLayer.java + conf/dropout/{Spatial,Alpha,Gaussian}Dropout.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        if not train or self.lc.rate <= 0.0:
            return x, state, mask
        rate = self.lc.rate
        mode = getattr(self.lc, "mode", "elementwise")
        if mode == "elementwise":
            return nn_ops.dropout.fn(x, rng, rate=rate), state, mask
        if mode == "spatial":
            # drop whole feature maps: bernoulli over (N, 1, ..., 1, C)
            keep = 1.0 - rate
            mshape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
            m = jax.random.bernoulli(rng, keep, mshape)
            return jnp.where(m, x / keep, 0.0), state, mask
        if mode == "alpha":
            # Klambauer et al. 2017 §3: keeps SELU self-normalisation
            keep = 1.0 - rate
            alpha_p = -1.7580993408473766
            a = (keep + alpha_p ** 2 * keep * rate) ** -0.5
            b = -a * rate * alpha_p
            m = jax.random.bernoulli(rng, keep, x.shape)
            return a * jnp.where(m, x, alpha_p) + b, state, mask
        if mode == "gaussian":
            std = (rate / (1.0 - rate)) ** 0.5
            noise = 1.0 + std * jax.random.normal(rng, x.shape, x.dtype)
            return x * noise, state, mask
        raise ValueError(f"unknown dropout mode {mode!r}")


# ---------------------------------------------------------------------------
# Recurrent layers — lax.scan over time (layers/recurrent/*)
# ---------------------------------------------------------------------------


def _lstm_scan(params, x0, h0, c0, mask, *, gate_act, cell_act, reverse=False):
    """Scan an LSTM over (N, T, F). Gate math per LSTMHelpers.java:
    gates = x·Wih + h·Whh + b, order [i, f, o, g]; c' = f*c + i*g;
    h = o * cell_act(c') — the layer's configured activation IS the
    cell-output activation (reference default tanh), not a post-transform.

    The whole loop is one lax.scan — XLA unrolls/pipelines it; the per-step
    matmuls hit the MXU batched over N.
    """
    w_ih, w_hh, b = params["W"], params["RW"], params["b"]

    masked = mask is not None

    def step(carry, xm):
        h, c = carry
        xt, mt = xm
        gates = xt @ w_ih + h @ w_hh + b
        i, f, o, g = jnp.split(gates, 4, axis=-1)
        i, f, o = gate_act(i), gate_act(f), gate_act(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * cell_act(c_new)
        if masked:
            m = mt[:, None]
            h_new = jnp.where(m > 0, h_new, h)
            c_new = jnp.where(m > 0, c_new, c)
        return (h_new, c_new), h_new

    xs = jnp.swapaxes(x0, 0, 1)  # (T, N, F)
    ms = jnp.swapaxes(mask, 0, 1) if masked else jnp.zeros((xs.shape[0], 0))
    (h_last, c_last), hs = jax.lax.scan(step, (h0, c0), (xs, ms), reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), h_last, c_last


class LSTMImpl(Layer):
    """layers/recurrent/LSTM.java — scan-based, mask-aware, stateful-capable.

    The configured ``activation`` is the cell-output activation inside the
    scan (reference default tanh). Stateful rnnTimeStep() support passes
    ``initial=(h0, c0)`` and consumes the returned last state (wired by the
    network's rnn_time_step path).
    """

    reverse = False

    def init(self, key) -> Params:
        lc = self.lc
        k1, k2 = jax.random.split(key)
        b = jnp.zeros((4 * lc.n_out,), self.dtype)
        # forget-gate bias init (reference forgetGateBiasInit): gate order [i,f,o,g]
        b = b.at[lc.n_out : 2 * lc.n_out].set(lc.forget_gate_bias_init)
        return {
            "W": init_weights(k1, (lc.n_in, 4 * lc.n_out), self.winit, dtype=self.dtype),
            "RW": init_weights(k2, (lc.n_out, 4 * lc.n_out), self.winit, dtype=self.dtype),
            "b": b,
        }

    def apply(self, params, x, state, *, train, rng, mask=None, initial=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        n = x.shape[0]
        if initial is not None:
            h0, c0 = initial
        else:
            h0 = jnp.zeros((n, lc.n_out), x.dtype)
            c0 = jnp.zeros((n, lc.n_out), x.dtype)
        gate_act = get_activation(lc.gate_activation)
        hs, h_last, c_last = _lstm_scan(
            params, x, h0, c0, mask, gate_act=gate_act, cell_act=self.activation,
            reverse=self.reverse)
        return hs, state, mask

    def zero_state(self, batch: int, dtype=jnp.float32):
        n = self.lc.n_out
        return (jnp.zeros((batch, n), dtype), jnp.zeros((batch, n), dtype))

    def apply_with_state(self, params, x, *, mask=None, initial=None):
        """Stateful forward for rnn_time_step: returns (out, (h_last, c_last))."""
        lc = self.lc
        n = x.shape[0]
        if initial is not None:
            h0, c0 = initial
        else:
            h0 = jnp.zeros((n, lc.n_out), x.dtype)
            c0 = jnp.zeros((n, lc.n_out), x.dtype)
        hs, h_last, c_last = _lstm_scan(
            params, x, h0, c0, mask, gate_act=get_activation(lc.gate_activation),
            cell_act=self.activation, reverse=self.reverse)
        return hs, (h_last, c_last)


class GRUImpl(Layer):
    """GRU over the gru_cell declarable op, scanned across time — the same
    shared-recurrence shape as SimpleRnn/LSTM (training forward, tBPTT, and
    rnn_time_step all route through apply_with_state)."""

    def __init__(self, net_conf, lc, itype):
        super().__init__(net_conf, lc, itype)
        # the gru_cell ABI hardcodes tanh/sigmoid; an EXPLICIT per-layer
        # activation would be silently ignored — refuse instead
        # (LSTM/SimpleRnn honor theirs, so silence here would diverge; the
        # net-wide default activation is not treated as a GRU request)
        if lc.activation not in (None, "tanh"):
            raise ValueError(
                f"GRU uses the gru_cell op's fixed tanh/sigmoid gates; "
                f"activation={lc.activation!r} cannot apply")

    def init(self, key) -> Params:
        lc = self.lc
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weights(k1, (lc.n_in, 3 * lc.n_out), self.winit,
                              dtype=self.dtype),
            "RW": init_weights(k2, (lc.n_out, 3 * lc.n_out), self.winit,
                               dtype=self.dtype),
            "b": jnp.zeros((3 * lc.n_out,), self.dtype),
            "rb": jnp.zeros((3 * lc.n_out,), self.dtype),
        }

    def zero_state(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.lc.n_out), dtype)

    def apply(self, params, x, state, *, train, rng, mask=None, initial=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        hs, _ = self.apply_with_state(params, x, mask=mask, initial=initial)
        return hs, state, mask

    def apply_with_state(self, params, x, *, mask=None, initial=None):
        from deeplearning4j_tpu.ops.registry import registry

        cell = registry().get("gru_cell").fn
        lc = self.lc
        n = x.shape[0]
        h0 = initial if initial is not None else jnp.zeros((n, lc.n_out), x.dtype)
        masked = mask is not None

        def step(h, xm):
            xt, mt = xm
            h_new = cell(xt, h, params["W"], params["RW"], params["b"],
                         params["rb"])
            if masked:
                h_new = jnp.where(mt[:, None] > 0, h_new, h)
            return h_new, h_new

        xs = jnp.swapaxes(x, 0, 1)
        ms = (jnp.swapaxes(mask, 0, 1) if masked
              else jnp.zeros((xs.shape[0], 0), x.dtype))  # unmasked sentinel
        h_last, hs = jax.lax.scan(step, h0, (xs, ms))
        return jnp.swapaxes(hs, 0, 1), h_last


class SimpleRnnImpl(Layer):
    """layers/recurrent/SimpleRnn.java: h' = act(x·W + h·RW + b)."""

    def init(self, key) -> Params:
        lc = self.lc
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weights(k1, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype),
            "RW": init_weights(k2, (lc.n_out, lc.n_out), self.winit, dtype=self.dtype),
            "b": jnp.zeros((lc.n_out,), self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None, initial=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        hs, _ = self.apply_with_state(params, x, mask=mask, initial=initial)
        return hs, state, mask

    def zero_state(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.lc.n_out), dtype)

    def apply_with_state(self, params, x, *, mask=None, initial=None):
        """Shared scan; returns (out, h_last) — the single recurrence impl
        for both training forward and stateful rnn_time_step."""
        lc = self.lc
        n = x.shape[0]
        h0 = initial if initial is not None else jnp.zeros((n, lc.n_out), x.dtype)
        act = self.activation
        masked = mask is not None

        def step(h, xm):
            xt, mt = xm
            h_new = act(xt @ params["W"] + h @ params["RW"] + params["b"])
            if masked:
                h_new = jnp.where(mt[:, None] > 0, h_new, h)
            return h_new, h_new

        xs = jnp.swapaxes(x, 0, 1)
        ms = jnp.swapaxes(mask, 0, 1) if masked else jnp.zeros((xs.shape[0], 0))
        h_last, hs = jax.lax.scan(step, h0, (xs, ms))
        return jnp.swapaxes(hs, 0, 1), h_last


class BidirectionalImpl(Layer):
    """layers/recurrent/BidirectionalLayer.java: fwd + bwd inner RNN, merged."""

    def __init__(self, net_conf, lc, itype):
        super().__init__(net_conf, lc, itype)
        inner = lc.inner()
        self.fwd_layer = build_layer(net_conf, inner, itype)
        self.bwd_layer = build_layer(net_conf, inner, itype)
        if isinstance(self.bwd_layer, LSTMImpl):
            self.bwd_layer.reverse = True

    def init(self, key) -> Params:
        k1, k2 = jax.random.split(key)
        return {"fwd": self.fwd_layer.init(k1), "bwd": self.bwd_layer.init(k2)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        yf, _, _ = self.fwd_layer.apply(params["fwd"], x, {}, train=train, rng=rng, mask=mask)
        if isinstance(self.bwd_layer, LSTMImpl):
            yb, _, _ = self.bwd_layer.apply(params["bwd"], x, {}, train=train, rng=rng, mask=mask)
        else:
            xr = jnp.flip(x, axis=1)
            mr = None if mask is None else jnp.flip(mask, axis=1)
            yb, _, _ = self.bwd_layer.apply(params["bwd"], xr, {}, train=train, rng=rng, mask=mr)
            if yb.ndim == x.ndim:
                # sequence output: restore original time order. A collapsed
                # output (LastTimeStep-wrapped, keras return_sequences=False)
                # is ALREADY the backward pass's final step — flipping it
                # would scramble the FEATURE axis (round-4 bidirectional
                # regression)
                yb = jnp.flip(yb, axis=1)
        mode = self.lc.mode
        if mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif mode == "add":
            y = yf + yb
        elif mode == "mul":
            y = yf * yb
        elif mode == "average":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(f"unknown Bidirectional mode {mode}")
        return y, state, mask


class RnnOutputLayerImpl(Layer):
    """layers/recurrent/RnnOutputLayer.java: time-distributed dense + loss."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        z = x @ params["W"]
        if "b" in params:
            z = z + params["b"]
        return self.activation(z), state, mask


class LastTimeStepImpl(Layer):
    """layers/recurrent/LastTimeStepLayer.java: inner RNN -> last unmasked step."""

    def __init__(self, net_conf, lc, itype):
        super().__init__(net_conf, lc, itype)
        self.inner_layer = build_layer(net_conf, lc.inner(), itype)

    def init(self, key) -> Params:
        return {"inner": self.inner_layer.init(key)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        y, _, _ = self.inner_layer.apply(params["inner"], x, {}, train=train, rng=rng, mask=mask)
        if mask is None:
            out = y[:, -1]
        else:
            idx = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
            out = y[jnp.arange(y.shape[0]), idx]
        return out, state, None


class SelfAttentionLayerImpl(Layer):
    """layers/SelfAttentionLayer.java — MHA with Q=K=V=input sequence.

    Lowers to the registry's multi_head_dot_product_attention (which the
    platform-helper table may override with a Pallas flash-attention kernel
    on TPU — the cuDNN-helper analog)."""

    def init(self, key) -> Params:
        lc = self.lc
        ks = jax.random.split(key, 4)
        d = lc.n_out
        return {
            "Wq": init_weights(ks[0], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wk": init_weights(ks[1], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wv": init_weights(ks[2], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wo": init_weights(ks[3], (d, d), self.winit, dtype=self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        h = self.lc.n_heads
        q = x @ params["Wq"]
        k = x @ params["Wk"]
        v = x @ params["Wv"]
        n, t, d = q.shape
        dh = d // h

        def split(a):
            return a.reshape(n, t, h, dh).transpose(0, 2, 1, 3)

        qh, kh, vh = split(q), split(k), split(v)
        scores = (qh @ jnp.swapaxes(kh, -1, -2)) / jnp.sqrt(jnp.asarray(dh, x.dtype))
        if mask is not None:
            am = mask[:, None, None, :]
            scores = jnp.where(am > 0, scores, -1e9)
        attn = jax.nn.softmax(scores, axis=-1)
        out = (attn @ vh).transpose(0, 2, 1, 3).reshape(n, t, d)
        return out @ params["Wo"], state, mask


class LearnedSelfAttentionLayerImpl(Layer):
    """layers/LearnedSelfAttentionLayer.java: learned query matrix attends
    over the input sequence → fixed n_queries output timesteps. Routes the
    attention through the op registry so the Pallas flash helper fires on
    TPU for long sequences."""

    def init(self, key) -> Params:
        lc = self.lc
        ks = jax.random.split(key, 4)
        d = lc.n_out
        return {
            "Q": init_weights(ks[0], (lc.n_queries, d), self.winit, dtype=self.dtype),
            "Wk": init_weights(ks[1], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wv": init_weights(ks[2], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wo": init_weights(ks[3], (d, d), self.winit, dtype=self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        from deeplearning4j_tpu.ops import exec_op

        h = self.lc.n_heads
        n, t, _ = x.shape
        d = self.lc.n_out
        dh = d // h
        q = jnp.broadcast_to(params["Q"][None], (n,) + params["Q"].shape)
        k = x @ params["Wk"]
        v = x @ params["Wv"]

        def split(a):
            return a.reshape(n, a.shape[1], h, dh).transpose(0, 2, 1, 3)

        m = None if mask is None else mask[:, None, None, :]
        out = exec_op("dot_product_attention", split(q), split(k), split(v),
                      m, scaled=True)
        out = out.transpose(0, 2, 1, 3).reshape(n, self.lc.n_queries, d)
        return out @ params["Wo"], state, None  # fixed-length output: no mask


class RecurrentAttentionLayerImpl(Layer):
    """layers/RecurrentAttentionLayer.java: out_t = act(Wx·x_t + Wr·attn_t
    + b) where attn_t attends over the WHOLE input sequence queried by the
    previous output — a lax.scan over timesteps (TPU-compilable; the
    reference loops in Java)."""

    def init(self, key) -> Params:
        lc = self.lc
        ks = jax.random.split(key, 5)
        return {
            "Wx": init_weights(ks[0], (lc.n_in, lc.n_out), self.winit, dtype=self.dtype),
            "Wr": init_weights(ks[1], (lc.n_in, lc.n_out), self.winit, dtype=self.dtype),
            "Wq": init_weights(ks[2], (lc.n_out, lc.n_in), self.winit, dtype=self.dtype),
            "b": jnp.zeros((lc.n_out,), self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        n, t, d_in = x.shape
        heads = max(1, self.lc.n_heads)
        if d_in % heads:
            raise ValueError(
                f"RecurrentAttentionLayer: n_in={d_in} not divisible by "
                f"n_heads={heads}")
        dh = d_in // heads
        scale = 1.0 / float(dh) ** 0.5
        key_mask = None if mask is None else (mask > 0)
        xh = x.reshape(n, t, heads, dh)  # keys/values per head

        def step(h, x_t):
            q = (h @ params["Wq"]).reshape(n, heads, dh)
            s = jnp.einsum("nhd,nthd->nht", q, xh) * scale
            if key_mask is not None:
                s = jnp.where(key_mask[:, None, :], s, -1e9)
            a = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("nht,nthd->nhd", a, xh).reshape(n, d_in)
            h_new = self.activation(x_t @ params["Wx"] + attn @ params["Wr"]
                                    + params["b"])
            return h_new, h_new

        h0 = jnp.zeros((n, self.lc.n_out), x.dtype)
        _, ys = jax.lax.scan(step, h0, jnp.swapaxes(x, 0, 1))
        return jnp.swapaxes(ys, 0, 1), state, mask


class AttentionVertexImpl(Layer):
    """graph/vertex AttentionVertex: parameterized multi-input attention.
    Routed through the op registry → Pallas flash helper on TPU."""

    def init(self, key) -> Params:
        lc = self.lc
        ks = jax.random.split(key, 4)
        d = lc.n_out
        d_out = getattr(lc, "d_out", 0) or d
        nq = lc.n_in_queries or lc.n_in_keys
        nk = lc.n_in_keys or nq
        nv = lc.n_in_values or nk
        p = {
            "Wq": init_weights(ks[0], (nq, d), self.winit, dtype=self.dtype),
            "Wk": init_weights(ks[1], (nk, d), self.winit, dtype=self.dtype),
            "Wv": init_weights(ks[2], (nv, d), self.winit, dtype=self.dtype),
            "Wo": init_weights(ks[3], (d, d_out), self.winit, dtype=self.dtype),
        }
        if getattr(lc, "has_bias", False):
            p.update({"bq": jnp.zeros((d,), self.dtype),
                      "bk": jnp.zeros((d,), self.dtype),
                      "bv": jnp.zeros((d,), self.dtype),
                      "bo": jnp.zeros((d_out,), self.dtype)})
        return p

    def apply_multi(self, params, xs, state, *, train, rng, mask=None):
        from deeplearning4j_tpu.ops import exec_op

        if getattr(self.lc, "keras_order", False) and len(xs) >= 2:
            # Keras MultiHeadAttention call order: (query, VALUE[, key])
            queries = xs[0]
            values = xs[1]
            keys = xs[2] if len(xs) > 2 else values
        else:
            queries = xs[0]
            keys = xs[1] if len(xs) > 1 else xs[0]
            values = xs[2] if len(xs) > 2 else keys
        out = exec_op("multi_head_dot_product_attention",
                      queries, keys, values,
                      params["Wq"], params["Wk"], params["Wv"], params["Wo"],
                      mask, num_heads=self.lc.n_heads,
                      bq=params.get("bq"), bk=params.get("bk"),
                      bv=params.get("bv"), bo=params.get("bo"))
        return out, state, mask

    def apply(self, params, x, state, *, train, rng, mask=None):
        return self.apply_multi(params, [x], state, train=train, rng=rng,
                                mask=mask)


class Convolution1DImpl(Layer):
    """layers/convolution/Convolution1DLayer.java over (N, T, C)."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.kernel, lc.n_in, lc.n_out),
                               self.winit, dtype=self.dtype)}
        p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        z = nn_ops.conv1d.fn(x, params["W"], params.get("b"),
                             stride=lc.stride,
                             padding=lc.convolution_mode,
                             dilation=lc.dilation)
        if mask is not None and z.shape[1] != mask.shape[1]:
            # subsample the mask with the conv (reference Conv1D semantics:
            # a timestep survives if its window START was valid)
            mask = mask[:, ::lc.stride][:, :z.shape[1]]
        return self.activation(z), state, mask


class Convolution3DImpl(Layer):
    """layers/convolution/Convolution3DLayer.java over (N, D, H, W, C)."""

    def init(self, key) -> Params:
        lc = self.lc
        kd, kh, kw = lc.kernel
        return {
            "W": init_weights(key, (kd, kh, kw, lc.n_in, lc.n_out),
                              self.winit, dtype=self.dtype),
            "b": jnp.zeros((lc.n_out,), self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        z = nn_ops.conv3d.fn(x, params["W"], params.get("b"),
                             stride=lc.stride,
                             padding=lc.convolution_mode)
        return self.activation(z), state, mask


class Subsampling3DLayerImpl(Layer):
    """layers/convolution/Subsampling3DLayer.java (NDHWC pooling)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        k = (1,) + tuple(lc.kernel) + (1,)
        s = (1,) + tuple(lc.stride) + (1,)
        if lc.pooling_type == "max":
            z = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, k, s, "VALID")
        else:
            z = jax.lax.reduce_window(x, 0.0, jax.lax.add, k, s, "VALID") \
                / float(lc.kernel[0] * lc.kernel[1] * lc.kernel[2])
        return z, state, mask


class LocallyConnected2DImpl(Layer):
    """layers/convolution/LocallyConnected2DLayer.java: per-position
    (unshared) conv weights — patches × per-position kernels as ONE einsum,
    which XLA maps onto the MXU as a batched matmul."""

    def _out_hw(self):
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        sh, sw = C._pair(lc.stride)
        ih, iw = lc.input_size
        return (ih - kh) // sh + 1, (iw - kw) // sw + 1

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        oh, ow = self._out_hw()
        return {
            "W": init_weights(key, (oh * ow, kh * kw * lc.n_in, lc.n_out),
                              self.winit, dtype=self.dtype),
            "b": jnp.zeros((oh, ow, lc.n_out), self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        sh, sw = C._pair(lc.stride)
        oh, ow = self._out_hw()
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # patches feature order is (C, kh, kw); align W accordingly at init?
        # no — keep W in patch order: reshape to (N, oh*ow, feat)
        n = x.shape[0]
        p = patches.reshape(n, oh * ow, -1)
        z = jnp.einsum("npf,pfo->npo", p, params["W"])
        z = z.reshape(n, oh, ow, lc.n_out) + params["b"]
        return self.activation(z), state, mask


class LocallyConnected1DImpl(Layer):
    """layers/convolution/LocallyConnected1DLayer.java over (N, T, C)."""

    def _out_t(self):
        lc = self.lc
        return (lc.input_size - lc.kernel) // lc.stride + 1

    def init(self, key) -> Params:
        lc = self.lc
        ot = self._out_t()
        return {
            "W": init_weights(key, (ot, lc.kernel * lc.n_in, lc.n_out),
                              self.winit, dtype=self.dtype),
            "b": jnp.zeros((ot, lc.n_out), self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        ot = self._out_t()
        starts = jnp.arange(ot) * lc.stride
        idx = starts[:, None] + jnp.arange(lc.kernel)[None, :]  # (ot, k)
        windows = x[:, idx, :]  # (N, ot, k, C)
        n = x.shape[0]
        p = windows.reshape(n, ot, -1)
        z = jnp.einsum("npf,pfo->npo", p, params["W"]) + params["b"]
        if mask is not None and z.shape[1] != mask.shape[1]:
            mask = None
        return self.activation(z), state, mask


class PReLULayerImpl(Layer):
    """layers/feedforward/PReLULayer.java: learned per-feature slope."""

    def init(self, key) -> Params:
        return {"alpha": jnp.full((self.lc.n_in,), 0.25, self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        a = params["alpha"]
        return jnp.maximum(x, 0) + a * jnp.minimum(x, 0), state, mask


class VariationalAutoencoderImpl(Layer):
    """layers/variational/VariationalAutoencoder.java.

    Supervised forward = encoder → latent mean (reference activate()
    semantics). ``elbo_loss(params, x, rng)`` gives the pretrain objective
    (reparameterized ELBO) for unsupervised fit — the reference's
    pretrain-layer role."""

    def init(self, key) -> Params:
        lc = self.lc
        sizes_e = (lc.n_in,) + tuple(lc.encoder_layer_sizes)
        sizes_d = (lc.n_out,) + tuple(lc.decoder_layer_sizes)
        ks = jax.random.split(key, 2 * (len(sizes_e) + len(sizes_d)) + 3)
        ki = iter(range(len(ks)))
        p: Dict[str, Any] = {"enc": [], "dec": []}
        for i in range(len(sizes_e) - 1):
            p["enc"].append({
                "W": init_weights(ks[next(ki)], (sizes_e[i], sizes_e[i + 1]),
                                  self.winit, dtype=self.dtype),
                "b": jnp.zeros((sizes_e[i + 1],), self.dtype)})
        h = sizes_e[-1]
        p["mean"] = {"W": init_weights(ks[next(ki)], (h, lc.n_out),
                                       self.winit, dtype=self.dtype),
                     "b": jnp.zeros((lc.n_out,), self.dtype)}
        p["logvar"] = {"W": init_weights(ks[next(ki)], (h, lc.n_out),
                                         self.winit, dtype=self.dtype),
                       "b": jnp.zeros((lc.n_out,), self.dtype)}
        for i in range(len(sizes_d) - 1):
            p["dec"].append({
                "W": init_weights(ks[next(ki)], (sizes_d[i], sizes_d[i + 1]),
                                  self.winit, dtype=self.dtype),
                "b": jnp.zeros((sizes_d[i + 1],), self.dtype)})
        p["recon"] = {"W": init_weights(ks[next(ki)],
                                        (sizes_d[-1], lc.n_in),
                                        self.winit, dtype=self.dtype),
                      "b": jnp.zeros((lc.n_in,), self.dtype)}
        return p

    def _encode(self, params, x):
        h = x
        for lp in params["enc"]:
            h = self.activation(h @ lp["W"] + lp["b"])
        mean = h @ params["mean"]["W"] + params["mean"]["b"]
        logvar = h @ params["logvar"]["W"] + params["logvar"]["b"]
        return mean, logvar

    def _decode(self, params, z):
        h = z
        for lp in params["dec"]:
            h = self.activation(h @ lp["W"] + lp["b"])
        return h @ params["recon"]["W"] + params["recon"]["b"]

    def apply(self, params, x, state, *, train, rng, mask=None):
        mean, _ = self._encode(params, x)
        return mean, state, mask

    def elbo_loss(self, params, x, rng):
        mean, logvar = self._encode(params, x)
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        z = mean + jnp.exp(0.5 * logvar) * eps
        recon = self._decode(params, z)
        if self.lc.reconstruction_distribution == "bernoulli":
            p = jax.nn.sigmoid(recon)
            rec = -jnp.sum(x * jnp.log(p + 1e-8)
                           + (1 - x) * jnp.log(1 - p + 1e-8), axis=-1)
        else:
            rec = 0.5 * jnp.sum((x - recon) ** 2, axis=-1)
        kl = -0.5 * jnp.sum(1 + logvar - mean ** 2 - jnp.exp(logvar), axis=-1)
        return jnp.mean(rec + kl)


class ZeroPadding1DLayerImpl(Layer):
    """layers/convolution/ZeroPadding1DLayer.java: pad time axis of (N,T,C)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        a, b = C._pair(self.lc.padding)
        y = jnp.pad(x, ((0, 0), (a, b), (0, 0)))
        if mask is not None:
            mask = jnp.pad(mask, ((0, 0), (a, b)))
        return y, state, mask


class ZeroPaddingLayerImpl(Layer):
    """layers/convolution/ZeroPaddingLayer.java: NHWC spatial pad."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        t, b, l, r = self.lc.padding
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state, mask


class ZeroPadding3DLayerImpl(Layer):
    """layers/convolution/ZeroPadding3DLayer.java: NDHWC pad."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        p = self.lc.padding
        return jnp.pad(x, ((0, 0), (p[0], p[1]), (p[2], p[3]),
                           (p[4], p[5]), (0, 0))), state, mask


class Cropping1DImpl(Layer):
    """layers/convolution/Cropping1DLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        a, b = C._pair(self.lc.cropping)
        t = x.shape[1]
        y = x[:, a:t - b, :]
        if mask is not None:
            mask = mask[:, a:t - b]
        return y, state, mask


class Cropping2DImpl(Layer):
    """layers/convolution/Cropping2DLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        t, b, l, r = self.lc.cropping
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b, l:w - r, :], state, mask


class Cropping3DImpl(Layer):
    """layers/convolution/Cropping3DLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        c = self.lc.cropping
        d, h, w = x.shape[1], x.shape[2], x.shape[3]
        return x[:, c[0]:d - c[1], c[2]:h - c[3], c[4]:w - c[5], :], state, mask


class Upsampling1DImpl(Layer):
    """layers/convolution/upsampling/Upsampling1D.java: repeat timesteps."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        y = jnp.repeat(x, self.lc.size, axis=1)
        if mask is not None:
            mask = jnp.repeat(mask, self.lc.size, axis=1)
        return y, state, mask


class Upsampling3DImpl(Layer):
    """layers/convolution/upsampling/Upsampling3D.java: NN-upsample NDHWC."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        s = self.lc.size
        y = jnp.repeat(jnp.repeat(jnp.repeat(x, s[0], axis=1), s[1], axis=2),
                       s[2], axis=3)
        return y, state, mask


class Subsampling1DLayerImpl(Layer):
    """layers/convolution/subsampling/Subsampling1DLayer.java: temporal pool."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        k, s = int(lc.kernel), int(lc.stride)
        pad = "SAME" if lc.convolution_mode == "same" else "VALID"
        if lc.pooling_type == "max":
            y = jax.lax.reduce_window(
                x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                jax.lax.max, (1, k, 1), (1, s, 1), pad)
        else:
            ones = jnp.ones_like(x)
            tot = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, k, 1), (1, s, 1), pad)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, (1, k, 1), (1, s, 1), pad)
            y = tot / cnt
        if mask is not None:
            mask = jax.lax.reduce_window(
                mask.astype(x.dtype), 0.0, jax.lax.max, (1, k), (1, s), pad)
        return y, state, mask


class Deconvolution3DImpl(Layer):
    """layers/convolution/Deconvolution3DLayer.java: transposed 3-D conv."""

    def init(self, key) -> Params:
        lc = self.lc
        kd, kh, kw = lc.kernel
        p = {"W": init_weights(key, (kd, kh, kw, lc.n_in, lc.n_out),
                               self.winit, dtype=self.dtype)}
        p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        pad = "SAME" if lc.convolution_mode == "same" else "VALID"
        y = jax.lax.conv_transpose(
            x, params["W"], strides=tuple(lc.stride), padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        y = y + params["b"]
        return self.activation(y), state, mask


class CnnLossLayerImpl(Layer):
    """layers/convolution/CnnLossLayer.java: activation only — per-position
    loss applied by the network against (N, H, W, C) labels."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return self.activation(x), state, mask


class RnnLossLayerImpl(CnnLossLayerImpl):
    """layers/recurrent/RnnLossLayer.java: per-timestep loss (N, T, C)."""


class MaskLayerImpl(Layer):
    """layers/util/MaskLayer.java: zero masked timesteps explicitly."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        if mask is not None:
            m = mask.astype(x.dtype)
            while m.ndim < x.ndim:
                m = m[..., None]
            x = x * m
        return x, state, mask


class MaskZeroLayerImpl(Layer):
    """layers/recurrent/MaskZeroLayer.java: derive the timestep mask from
    the input values, then run the wrapped layer under it."""

    def __init__(self, net_conf, lc, itype):
        super().__init__(net_conf, lc, itype)
        self.inner_layer = build_layer(net_conf, lc.inner(), itype)

    def init(self, key) -> Params:
        return {"inner": self.inner_layer.init(key)}

    def init_state(self) -> State:
        return self.inner_layer.init_state()

    def apply(self, params, x, state, *, train, rng, mask=None):
        derived = jnp.any(x != self.lc.mask_value, axis=-1).astype(x.dtype)
        if mask is not None:
            derived = derived * mask.astype(x.dtype)
        x = x * derived[..., None]
        y, st, _ = self.inner_layer.apply(params["inner"], x, state,
                                          train=train, rng=rng, mask=derived)
        return y, st, derived


class RepeatVectorImpl(Layer):
    """layers/RepeatVector.java: (N, F) -> (N, n, F)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], self.lc.n, x.shape[-1])), state, None


class ElementWiseMultiplicationLayerImpl(Layer):
    """layers/feedforward/elementwise/ElementWiseMultiplicationLayer.java."""

    def init(self, key) -> Params:
        n = self.lc.n_out or self.lc.n_in
        return {"W": jnp.ones((n,), self.dtype), "b": jnp.zeros((n,), self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        return self.activation(x * params["W"] + params["b"]), state, mask


class FrozenLayerWithBackpropImpl(Layer):
    """layers/FrozenLayerWithBackprop.java: stop-gradient on the wrapped
    layer's PARAMS (they never update) while activations and upstream
    gradients flow normally."""

    def __init__(self, net_conf, lc, itype):
        super().__init__(net_conf, lc, itype)
        self.inner_layer = build_layer(net_conf, lc.inner(), itype)

    def init(self, key) -> Params:
        return {"inner": self.inner_layer.init(key)}

    def init_state(self) -> State:
        return self.inner_layer.init_state()

    def apply(self, params, x, state, *, train, rng, mask=None):
        frozen = jax.tree.map(jax.lax.stop_gradient, params["inner"])
        return self.inner_layer.apply(frozen, x, state, train=train, rng=rng,
                                      mask=mask)


class CenterLossOutputLayerImpl(DenseLayerImpl):
    """layers/training/CenterLossOutputLayer.java: dense+softmax forward;
    per-class centers live in params["centers"] and enter through the loss
    (the network adds λ·½‖features − c_y‖² — see MultiLayerNetwork)."""

    def init(self, key) -> Params:
        p = super().init(key)
        p["centers"] = jnp.zeros((self.lc.n_out, self.lc.n_in), self.dtype)
        return p


class Yolo2OutputLayerImpl(Layer):
    """layers/objdetect/Yolo2OutputLayer.java: identity forward — the raw
    head output is decoded inside the 'yolo2' loss."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return x, state, mask


def _squash(v, axis=-1, eps=1e-8):
    """CapsNet squash: (‖v‖²/(1+‖v‖²)) · v/‖v‖ (Sabour et al. 2017)."""
    sq = jnp.sum(jnp.square(v), axis=axis, keepdims=True)
    return (sq / (1.0 + sq)) * v * jax.lax.rsqrt(sq + eps)


class PrimaryCapsulesImpl(Layer):
    """layers/PrimaryCapsules.java: conv → capsule channels → squash."""

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = lc.kernel
        out_ch = lc.capsules * lc.capsule_dim
        p = {"W": init_weights(key, (kh, kw, self.itype.channels, out_ch),
                               self.winit, dtype=self.dtype),
             "b": jnp.zeros((out_ch,), self.dtype)}
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        y = jax.lax.conv_general_dilated(
            x, params["W"], tuple(lc.stride), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + params["b"]
        n = y.shape[0]
        y = y.reshape(n, -1, lc.capsule_dim)
        return _squash(y), state, None


class CapsuleLayerImpl(Layer):
    """layers/CapsuleLayer.java: dynamic routing between capsule layers."""

    def init(self, key) -> Params:
        lc = self.lc
        in_caps, in_dim = self.itype.timesteps, self.itype.size
        return {"W": init_weights(key, (in_caps, lc.capsules,
                                        lc.capsule_dim, in_dim),
                                  self.winit, dtype=self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        # u_hat[n,i,j,k] = W[i,j,k,:] · x[n,i,:]
        u_hat = jnp.einsum("nid,ijkd->nijk", x, params["W"])
        b = jnp.zeros(u_hat.shape[:3], u_hat.dtype)
        v = None
        for it in range(max(int(lc.routings), 1)):
            c = jax.nn.softmax(b, axis=2)
            s = jnp.sum(c[..., None] * u_hat, axis=1)
            v = _squash(s)
            if it + 1 < lc.routings:
                # routing agreement uses detached predictions (standard
                # CapsNet practice: gradients flow only through the last pass)
                b = b + jnp.einsum("njk,nijk->nij",
                                   jax.lax.stop_gradient(v), u_hat)
        return v, state, None


class CapsuleStrengthLayerImpl(Layer):
    """layers/CapsuleStrengthLayer.java: per-capsule L2 norm."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=-1) + 1e-12), state, mask



class PermuteLayerImpl(Layer):
    """Keras Permute parity: reorder non-batch axes (dims are 1-indexed)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        perm = (0,) + tuple(int(d) for d in self.lc.dims)
        return jnp.transpose(x, perm), state, mask


class ReshapeLayerImpl(Layer):
    """Keras Reshape parity: batch-preserving reshape with -1 inference."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return x.reshape((x.shape[0],) + tuple(int(s) for s in
                                               self.lc.target_shape)), \
            state, mask


class LayerNormalizationImpl(Layer):
    """Trailing-axis layer norm with learned gain/bias (layer_norm op)."""

    def init(self, key) -> Params:
        n = self.lc.n_out
        return {"gain": jnp.ones((n,), self.dtype),
                "b": jnp.zeros((n,), self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        y = nn_ops.layer_norm.fn(x, params["gain"], params["b"],
                                 axis=-1, eps=self.lc.eps)
        return self.activation(y), state, mask


class GroupNormalizationImpl(Layer):
    """Group norm: normalize per (sample, group) over spatial dims +
    in-group channels, then per-channel scale/shift."""

    def init(self, key) -> Params:
        n = self.lc.n_out
        return {"gamma": jnp.ones((n,), self.dtype),
                "beta": jnp.zeros((n,), self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        c = x.shape[-1]
        g = lc.groups if lc.groups > 0 else c
        xg = x.reshape(x.shape[:-1] + (g, c // g))
        # per (sample, group): reduce spatial dims + in-group channels,
        # NOT across groups (keras GroupNormalization semantics)
        axes = tuple(i for i in range(1, xg.ndim) if i != xg.ndim - 2)
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        y = ((xg - mean) * jax.lax.rsqrt(var + lc.eps)).reshape(x.shape)
        y = y * params["gamma"] + params["beta"]
        return self.activation(y), state, mask


class RescaleLayerImpl(Layer):
    """out = x * scale + offset (Keras Rescaling / adapted Normalization)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        scale = jnp.asarray(self.lc.scale, x.dtype)
        offset = jnp.asarray(self.lc.offset, x.dtype)
        return x * scale + offset, state, mask


class UnitNormLayerImpl(Layer):
    """L2-normalize along the trailing axis (Keras UnitNormalization)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        return x / jnp.maximum(norm, self.lc.eps), state, mask


class ConvLSTM2DImpl(Layer):
    """Convolutional LSTM over (N, T, H, W, C): gate pre-activations are
    conv2d(x_t, W) + conv2d(h, RW) + b, one lax.scan over time so each step
    is a batched MXU conv (KerasConvLSTM2D parity; gate order i, f, o, g
    after import re-packing)."""

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = lc.kernel
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weights(k1, (kh, kw, lc.n_in, 4 * lc.filters),
                              self.winit, dtype=self.dtype),
            "RW": init_weights(k2, (kh, kw, lc.filters, 4 * lc.filters),
                               self.winit, dtype=self.dtype),
            "b": jnp.zeros((4 * lc.filters,), self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        gate_act = get_activation(lc.gate_activation)
        pad = "same" if lc.padding == "same" else "valid"

        def conv(a, w, p):
            return jax.lax.conv_general_dilated(
                a, w, (1, 1), p,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        # input convs for ALL timesteps in one batched conv: (N*T, H, W, C)
        n, t = x.shape[0], x.shape[1]
        zx = conv(x.reshape((n * t,) + x.shape[2:]), params["W"], pad.upper())
        zx = zx.reshape((n, t) + zx.shape[1:]) + params["b"]
        h0 = jnp.zeros((n,) + zx.shape[2:-1] + (lc.filters,), x.dtype)

        def step(carry, zt):
            h, c = carry
            # the recurrent conv is ALWAYS 'same' — the carried state must
            # keep its spatial shape (keras ConvLSTM2D semantics)
            gates = zt + conv(h, params["RW"], "SAME")
            i, f, o, g = jnp.split(gates, 4, axis=-1)
            # keras applies `activation` to BOTH candidate and cell output
            c_new = gate_act(f) * c + gate_act(i) * self.activation(g)
            h_new = gate_act(o) * self.activation(c_new)
            return (h_new, c_new), h_new

        (h_last, _), hs = jax.lax.scan(step, (h0, h0),
                                       jnp.swapaxes(zx, 0, 1))
        if lc.return_sequences:
            return jnp.swapaxes(hs, 0, 1), state, mask
        return h_last, state, None



class DotAttentionLayerImpl(Layer):
    """Param-free Keras Attention / AdditiveAttention: inputs in KERAS
    order (query, value[, key]); key defaults to value."""

    def apply_multi(self, params, xs, state, *, train, rng, mask=None):
        q = xs[0]
        v = xs[1] if len(xs) > 1 else xs[0]
        k = xs[2] if len(xs) > 2 else v
        lc = self.lc
        if lc.additive:
            # Bahdanau: score[b,i,j] = sum(scale * tanh(q_i + k_j))
            t = jnp.tanh(q[:, :, None, :] + k[:, None, :, :])
            if lc.use_scale and lc.scale is not None:
                t = t * jnp.asarray(lc.scale, t.dtype)
            scores = jnp.sum(t, axis=-1)
        else:
            scores = jnp.einsum("bqd,bkd->bqk", q, k)
            if lc.use_scale and lc.scale is not None:
                scores = scores * jnp.asarray(lc.scale, scores.dtype)
        if mask is not None and mask.shape[-1] == k.shape[1]:
            # key-padding mask: padded positions get no attention weight
            scores = jnp.where(mask[:, None, :] > 0, scores,
                               jnp.asarray(-1e9, scores.dtype))
        w = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bqk,bkd->bqd", w, v), state, mask

    def apply(self, params, x, state, *, train, rng, mask=None):
        return self.apply_multi(params, [x], state, train=train, rng=rng,
                                mask=mask)



class SeparableConvolution1DImpl(Layer):
    """Depthwise (grouped) + pointwise conv over (N, T, C)."""

    def init(self, key) -> Params:
        lc = self.lc
        k1, k2 = jax.random.split(key)
        mult = lc.depth_multiplier
        p = {"dW": init_weights(k1, (lc.kernel, 1, lc.n_in * mult),
                                self.winit, dtype=self.dtype),
             "pW": init_weights(k2, (1, lc.n_in * mult, lc.n_out),
                                self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        pad = "SAME" if lc.convolution_mode == "same" else "VALID"
        dn = ("NWC", "WIO", "NWC")
        z = jax.lax.conv_general_dilated(
            x, params["dW"], (lc.stride,), pad, dimension_numbers=dn,
            feature_group_count=lc.n_in)
        z = jax.lax.conv_general_dilated(
            z, params["pW"], (1,), "VALID", dimension_numbers=dn)
        if "b" in params:
            z = z + params["b"]
        if mask is not None and z.shape[1] != mask.shape[1]:
            mask = mask[:, ::lc.stride][:, :z.shape[1]]
        return self.activation(z), state, mask



class Deconvolution1DImpl(Layer):
    """Transposed temporal conv over (N, T, C)."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.kernel, lc.n_in, lc.n_out),
                               self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        pad = "SAME" if lc.convolution_mode == "same" else "VALID"
        # transpose_kernel=True = TF conv1d_transpose semantics (exact at
        # every stride); W stored (k, in, out) like the 2D convention
        z = jax.lax.conv_transpose(
            x, jnp.swapaxes(params["W"], 1, 2), (lc.stride,), pad,
            dimension_numbers=("NWC", "WIO", "NWC"), transpose_kernel=True)
        if "b" in params:
            z = z + params["b"]
        return self.activation(z), state, None



class SpaceToDepthLayerImpl(Layer):
    """layers/convolution/SpaceToDepthLayer.java (YOLOv2 reorg)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        from deeplearning4j_tpu.ops import exec_op

        return exec_op("space_to_depth", x,
                       block_size=self.lc.block_size), state, mask



class SameDiffLayerImpl(Layer):
    """layers/samediff/SameDiffLayer.java runtime: the user's define()
    records into a private SameDiff once; apply interprets that graph with
    the live params/input under the outer trace, so jax.grad of the whole
    network differentiates straight through the block."""

    def _graph(self):
        if not hasattr(self, "_sd"):
            from deeplearning4j_tpu.autodiff.samediff import SameDiff

            sd = SameDiff.create()
            x = sd.placeholder("sdl_x", shape=None)
            pvars = {name: sd.placeholder(f"sdl_p_{name}", shape=tuple(shape))
                     for name, shape in (self.lc.param_shapes or {}).items()}
            out = self.lc.define(sd, x, pvars)
            self._sd = sd
            self._out_name = out.name
        return self._sd, self._out_name

    def init(self, key) -> Params:
        shapes = self.lc.param_shapes or {}
        ks = jax.random.split(key, max(len(shapes), 1))
        params = {}
        for k_, (name, shape) in zip(ks, sorted(shapes.items())):
            if len(shape) >= 2:
                params[name] = init_weights(k_, tuple(shape), self.winit,
                                            dtype=self.dtype)
            else:
                params[name] = jnp.zeros(tuple(shape), self.dtype)
        return params

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        sd, out_name = self._graph()
        env = dict(sd._arrays)
        env["sdl_x"] = x
        for name, arr in params.items():
            env[f"sdl_p_{name}"] = arr
        out = sd._interpret(env, [out_name])[out_name]
        # the block's output IS define()'s result — the net-wide default
        # activation must NOT double-activate it (reference SameDiffLayer
        # semantics); an explicit per-layer activation still applies
        if self.lc.activation is not None:
            out = self.activation(out)
        return out, state, mask



class ResizeLayerImpl(Layer):
    """Keras Resizing: NHWC resize via the registry resize ops (half-pixel
    centers — the TF2/keras convention)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        from deeplearning4j_tpu.ops import exec_op

        op = {"bilinear": "resize_bilinear",
              "nearest": "resize_nearest_neighbor",
              "bicubic": "resize_bicubic"}[self.lc.method]
        return exec_op(op, x, size=(self.lc.height, self.lc.width)), \
            state, mask


class CenterCropLayerImpl(Layer):
    """Keras CenterCrop: static center window (keras floor convention:
    start = (in - out) // 2)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        h, w = x.shape[1], x.shape[2]
        th, tw = self.lc.height, self.lc.width
        if h < th or w < tw:
            # keras falls back to smart_resize here; our declared output
            # shape cannot flex, so fail loudly rather than mis-shape
            raise ValueError(
                f"CenterCropLayer: input {h}x{w} smaller than target "
                f"{th}x{tw} (keras would resize; use ResizeLayer instead)")
        y0, x0 = (h - th) // 2, (w - tw) // 2
        return x[:, y0:y0 + th, x0:x0 + tw, :], state, mask


LAYER_IMPLS: Dict[Type[C.LayerConf], Type[Layer]] = {
    C.DenseLayer: DenseLayerImpl,
    C.OutputLayer: OutputLayerImpl,
    C.LossLayer: LossLayerImpl,
    C.EmbeddingLayer: EmbeddingLayerImpl,
    C.EmbeddingSequenceLayer: EmbeddingSequenceLayerImpl,
    C.ConvolutionLayer: ConvolutionLayerImpl,
    C.Deconvolution2D: Deconvolution2DImpl,
    C.DepthwiseConvolution2D: DepthwiseConvolution2DImpl,
    C.SeparableConvolution2D: SeparableConvolution2DImpl,
    C.SubsamplingLayer: SubsamplingLayerImpl,
    C.Upsampling2D: Upsampling2DImpl,
    C.GlobalPoolingLayer: GlobalPoolingLayerImpl,
    C.BatchNormalization: BatchNormalizationImpl,
    C.DuelingQLayer: DuelingQLayerImpl,
    C.EinsumDenseLayer: EinsumDenseLayerImpl,
    C.DiscretizationLayer: DiscretizationLayerImpl,
    C.CategoryEncodingLayer: CategoryEncodingLayerImpl,
    C.LocalResponseNormalization: LocalResponseNormalizationImpl,
    C.ActivationLayer: ActivationLayerImpl,
    C.DropoutLayer: DropoutLayerImpl,
    C.LSTM: LSTMImpl,
    C.GravesLSTM: LSTMImpl,
    C.GRU: GRUImpl,
    C.SimpleRnn: SimpleRnnImpl,
    C.Bidirectional: BidirectionalImpl,
    C.RnnOutputLayer: RnnOutputLayerImpl,
    C.LastTimeStep: LastTimeStepImpl,
    C.SelfAttentionLayer: SelfAttentionLayerImpl,
    C.AttentionVertex: AttentionVertexImpl,
    C.LearnedSelfAttentionLayer: LearnedSelfAttentionLayerImpl,
    C.RecurrentAttentionLayer: RecurrentAttentionLayerImpl,
    C.Convolution1D: Convolution1DImpl,
    C.Convolution3D: Convolution3DImpl,
    C.Subsampling3DLayer: Subsampling3DLayerImpl,
    C.LocallyConnected2D: LocallyConnected2DImpl,
    C.LocallyConnected1D: LocallyConnected1DImpl,
    C.PReLULayer: PReLULayerImpl,
    C.VariationalAutoencoder: VariationalAutoencoderImpl,
    C.ZeroPadding1DLayer: ZeroPadding1DLayerImpl,
    C.ZeroPaddingLayer: ZeroPaddingLayerImpl,
    C.ZeroPadding3DLayer: ZeroPadding3DLayerImpl,
    C.Cropping1D: Cropping1DImpl,
    C.Cropping2D: Cropping2DImpl,
    C.Cropping3D: Cropping3DImpl,
    C.Upsampling1D: Upsampling1DImpl,
    C.Upsampling3D: Upsampling3DImpl,
    C.Subsampling1DLayer: Subsampling1DLayerImpl,
    C.Deconvolution3D: Deconvolution3DImpl,
    C.CnnLossLayer: CnnLossLayerImpl,
    C.RnnLossLayer: RnnLossLayerImpl,
    C.MaskLayer: MaskLayerImpl,
    C.MaskZeroLayer: MaskZeroLayerImpl,
    C.RepeatVector: RepeatVectorImpl,
    C.ResizeLayer: ResizeLayerImpl,
    C.CenterCropLayer: CenterCropLayerImpl,
    C.SameDiffLayer: SameDiffLayerImpl,
    C.SpaceToDepthLayer: SpaceToDepthLayerImpl,
    C.Deconvolution1D: Deconvolution1DImpl,
    C.SeparableConvolution1D: SeparableConvolution1DImpl,
    C.DotAttentionLayer: DotAttentionLayerImpl,
    C.PermuteLayer: PermuteLayerImpl,
    C.ReshapeLayer: ReshapeLayerImpl,
    C.LayerNormalization: LayerNormalizationImpl,
    C.GroupNormalization: GroupNormalizationImpl,
    C.RescaleLayer: RescaleLayerImpl,
    C.UnitNormLayer: UnitNormLayerImpl,
    C.ConvLSTM2D: ConvLSTM2DImpl,
    C.ElementWiseMultiplicationLayer: ElementWiseMultiplicationLayerImpl,
    C.FrozenLayerWithBackprop: FrozenLayerWithBackpropImpl,
    C.CenterLossOutputLayer: CenterLossOutputLayerImpl,
    C.Yolo2OutputLayer: Yolo2OutputLayerImpl,
    C.PrimaryCapsules: PrimaryCapsulesImpl,
    C.CapsuleLayer: CapsuleLayerImpl,
    C.CapsuleStrengthLayer: CapsuleStrengthLayerImpl,
}


def build_layer(net_conf: C.MultiLayerConfiguration, lc: C.LayerConf, itype: C.InputType) -> Layer:
    impl = LAYER_IMPLS.get(type(lc))
    if impl is None and type(lc) is C.FusedBottleneck:
        # registered lazily: fused_blocks imports Layer from this module
        from deeplearning4j_tpu.nn.fused_blocks import FusedBottleneckImpl
        LAYER_IMPLS[C.FusedBottleneck] = FusedBottleneckImpl
        impl = FusedBottleneckImpl
    if impl is None and type(lc) is C.MoELayer:
        from deeplearning4j_tpu.nn.moe_layer import MoELayerImpl
        LAYER_IMPLS[C.MoELayer] = MoELayerImpl
        impl = MoELayerImpl
    if impl is None:
        raise ValueError(f"no runtime impl for layer config {type(lc).__name__}")
    return impl(net_conf, lc, itype)


def apply_preprocessor(p: Optional[C.InputPreProcessor], x):
    """conf/preprocessor/* forward application."""
    if p is None:
        return x
    if isinstance(p, C.FeedForwardToCnnPreProcessor):
        # reference flattening is NCHW C-major; our runtime layout is NHWC
        return x.reshape(x.shape[0], p.channels, p.height, p.width).transpose(0, 2, 3, 1)
    if isinstance(p, C.CnnToFeedForwardPreProcessor):
        # inverse: NHWC -> NCHW-major flatten to match reference flat ordering
        return x.transpose(0, 3, 1, 2).reshape(x.shape[0], -1)
    if isinstance(p, C.Cnn3DToFeedForwardPreProcessor):
        # NDHWC -> channel-major flatten (reference NCDHW ordering)
        return x.transpose(0, 4, 1, 2, 3).reshape(x.shape[0], -1)
    if isinstance(p, C.RnnToFeedForwardPreProcessor):
        return x.reshape(-1, x.shape[-1])
    if isinstance(p, C.FeedForwardToRnnPreProcessor):
        raise ValueError("FeedForwardToRnnPreProcessor needs batch size context; unsupported standalone")
    return x
