"""Runtime layers: pure ``init``/``apply`` functions per layer config.

Reference parity:
  * org/deeplearning4j/nn/layers/** — each reference layer hand-implements
    ``activate()`` (forward) and ``backpropGradient()`` (hand-written
    backward) against ND4J ops.
  * TPU-native realization: only the forward is written; the backward comes
    from jax.grad over the whole network (the reference's per-layer
    hand-written backprop dissolves — SURVEY §8.1). Layers are pure:
    ``apply(params, x, state, *, train, rng, mask) -> (y, new_state, mask)``.
    ``state`` carries non-trainable buffers (BatchNormalization running
    stats — the reference stores them as params excluded from updates).

Param naming matches the reference's param keys where they exist
("W", "b", "gamma", "beta", "mean", "var", "RW" for recurrent weights) so
flat-param export (params_flat) lines up for parity checks.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.ops import nn_ops
from deeplearning4j_tpu.ops.activations import get_activation
from deeplearning4j_tpu.ops.weight_init import init_weights

Params = Dict[str, jax.Array]
State = Dict[str, jax.Array]


class Layer:
    """Runtime twin of one LayerConf (org.deeplearning4j.nn.layers.BaseLayer)."""

    def __init__(self, net_conf: C.MultiLayerConfiguration, lc: C.LayerConf, itype: C.InputType):
        self.net_conf = net_conf
        self.lc = lc
        self.itype = itype  # input type AFTER preprocessor
        self.otype = lc.output_type(itype)
        self.activation = get_activation(net_conf.layer_activation(lc))
        self.winit = net_conf.layer_weight_init(lc)
        from deeplearning4j_tpu.nn.dtype import param_dtype

        self.dtype = param_dtype(net_conf.dtype)

    # -- override points ----------------------------------------------------
    def init(self, key) -> Params:
        return {}

    def init_state(self) -> State:
        return {}

    def apply(self, params: Params, x, state: State, *, train: bool, rng, mask=None):
        raise NotImplementedError

    # -- common helpers -----------------------------------------------------
    def _maybe_dropout(self, x, *, train: bool, rng):
        """Input dropout, reference layer-level `dropOut` semantics (applied
        to the layer INPUT, as in BaseLayer.applyDropOutIfNecessary)."""
        rate = self.lc.dropout
        if not rate or not train:
            return x
        return nn_ops.dropout.fn(x, rng, rate=rate)

    def n_params(self, params: Params) -> int:
        return sum(int(v.size) for v in params.values())


class DenseLayerImpl(Layer):
    """layers/feedforward/dense/DenseLayer.java: out = act(xW + b)."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        z = x @ params["W"]
        if "b" in params:
            z = z + params["b"]
        return self.activation(z), state, mask


class OutputLayerImpl(DenseLayerImpl):
    """layers/OutputLayer.java: dense + loss (loss applied by the network)."""


class LossLayerImpl(Layer):
    """layers/LossLayer.java: activation only; loss applied by the network."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return self.activation(x), state, mask


class EmbeddingLayerImpl(Layer):
    """layers/feedforward/embedding/EmbeddingLayer.java: ids -> rows."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if getattr(lc, "has_bias", False):
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        ids = x.astype(jnp.int32)
        if ids.ndim == 2 and ids.shape[-1] == 1:
            ids = ids[:, 0]
        out = params["W"][ids]
        if "b" in params:
            out = out + params["b"]
        return self.activation(out), state, mask


class EmbeddingSequenceLayerImpl(EmbeddingLayerImpl):
    """layers/feedforward/embedding/EmbeddingSequenceLayer.java.

    Input (N, T) int ids -> (N, T, F).
    """

    def apply(self, params, x, state, *, train, rng, mask=None):
        ids = x.astype(jnp.int32)
        if ids.ndim == 3 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        out = params["W"][ids]
        return self.activation(out), state, mask


class ConvolutionLayerImpl(Layer):
    """layers/convolution/ConvolutionLayer.java.

    Internal layout NHWC, kernel HWIO (SURVEY §8.3 layout policy; reference is
    NCHW/OIHW from its cuDNN heritage — accepted at the model edge, not here).
    """

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        p = {"W": init_weights(key, (kh, kw, lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def _conv_args(self):
        lc = self.lc
        if lc.convolution_mode == "same":
            padding = "same"
        else:
            ph, pw = C._pair(lc.padding)
            padding = ((ph, ph), (pw, pw))
        return dict(stride=C._pair(lc.stride), padding=padding, dilation=C._pair(lc.dilation))

    def apply(self, params, x, state, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        z = nn_ops.conv2d.fn(x, params["W"], params.get("b"), **self._conv_args())
        return self.activation(z), state, mask


class Deconvolution2DImpl(ConvolutionLayerImpl):
    """layers/convolution/Deconvolution2DLayer.java (transposed conv)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        if lc.convolution_mode == "same":
            pad = "same"
        else:
            # explicit pad must match output_type: oh = s*(h-1) + k - 2p
            pad = C._pair(lc.padding)
        z = nn_ops.deconv2d.fn(x, params["W"], params.get("b"), stride=C._pair(lc.stride), padding=pad)
        return self.activation(z), state, mask


class DepthwiseConvolution2DImpl(Layer):
    """layers/convolution/DepthwiseConvolution2DLayer.java."""

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        mult = getattr(lc, "depth_multiplier", 1)
        p = {"W": init_weights(key, (kh, kw, lc.n_in, mult), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_in * mult,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        pad = "same" if lc.convolution_mode == "same" else "valid"
        z = nn_ops.depthwise_conv2d.fn(
            x, params["W"], params.get("b"), stride=C._pair(lc.stride), padding=pad,
            dilation=C._pair(lc.dilation))
        return self.activation(z), state, mask


class SeparableConvolution2DImpl(Layer):
    """layers/convolution/SeparableConvolution2DLayer.java."""

    def init(self, key) -> Params:
        lc = self.lc
        kh, kw = C._pair(lc.kernel)
        mult = getattr(lc, "depth_multiplier", 1)
        k1, k2 = jax.random.split(key)
        p = {
            "dW": init_weights(k1, (kh, kw, lc.n_in, mult), self.winit, dtype=self.dtype),
            "pW": init_weights(k2, (1, 1, lc.n_in * mult, lc.n_out), self.winit, dtype=self.dtype),
        }
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        pad = "same" if lc.convolution_mode == "same" else "valid"
        z = nn_ops.separable_conv2d.fn(
            x, params["dW"], params["pW"], params.get("b"),
            stride=C._pair(lc.stride), padding=pad)
        return self.activation(z), state, mask


class SubsamplingLayerImpl(Layer):
    """layers/convolution/subsampling/SubsamplingLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        if lc.convolution_mode == "same":
            pad = "same"
        else:
            ph, pw = C._pair(lc.padding)
            pad = ((ph, ph), (pw, pw))
        kw = dict(kernel=C._pair(lc.kernel), stride=C._pair(lc.stride), padding=pad)
        if lc.pooling_type == "max":
            y = nn_ops.maxpool2d.fn(x, **kw)
        elif lc.pooling_type == "avg":
            y = nn_ops.avgpool2d.fn(x, **kw)
        elif lc.pooling_type == "pnorm":
            y = nn_ops.pnormpool2d.fn(x, p=lc.pnorm, **kw)
        else:
            raise ValueError(f"unknown pooling type {lc.pooling_type}")
        return y, state, mask


class Upsampling2DImpl(Layer):
    """layers/convolution/upsampling/Upsampling2D.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return nn_ops.upsampling2d.fn(x, size=C._pair(self.lc.size)), state, mask


class GlobalPoolingLayerImpl(Layer):
    """layers/pooling/GlobalPoolingLayer.java — conv NHWC (axes 1,2) or
    recurrent (axis 1 = time, mask-aware)."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        pt = self.lc.pooling_type
        if x.ndim == 4:  # NHWC
            axes = (1, 2)
            m = None
        else:  # (N, T, F)
            axes = (1,)
            m = mask
        if m is not None:
            m3 = m[..., None].astype(x.dtype)
            if pt == "avg":
                y = (x * m3).sum(axes) / jnp.maximum(m3.sum(axes), 1e-8)
            elif pt == "sum":
                y = (x * m3).sum(axes)
            elif pt == "max":
                y = jnp.where(m3 > 0, x, -jnp.inf).max(axes)
            else:
                y = ((jnp.abs(x) ** self.lc_pnorm()) * m3).sum(axes) ** (1.0 / self.lc_pnorm())
        else:
            if pt == "avg":
                y = x.mean(axes)
            elif pt == "sum":
                y = x.sum(axes)
            elif pt == "max":
                y = x.max(axes)
            else:
                y = (jnp.abs(x) ** self.lc_pnorm()).sum(axes) ** (1.0 / self.lc_pnorm())
        return y, state, None

    def lc_pnorm(self):
        return getattr(self.lc, "pnorm", 2)


class BatchNormalizationImpl(Layer):
    """layers/normalization/BatchNormalization.java.

    gamma/beta trainable; running mean/var live in layer STATE (the reference
    keeps them in the param buffer but excludes them from updates — state is
    the functional equivalent). Reference decay semantics:
    running = decay * running + (1-decay) * batch.
    """

    def init(self, key) -> Params:
        n = self.lc.n_out
        if self.lc.lock_gamma_beta:
            return {}
        return {"gamma": jnp.ones((n,), self.dtype), "beta": jnp.zeros((n,), self.dtype)}

    def init_state(self) -> State:
        n = self.lc.n_out
        return {"mean": jnp.zeros((n,), self.dtype), "var": jnp.ones((n,), self.dtype)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        gamma = params.get("gamma")
        beta = params.get("beta")
        if train:
            axes = tuple(range(x.ndim - 1))  # all but channel/feature
            y, new_mean, new_var = nn_ops.batch_norm_train(
                x, gamma, beta, state["mean"], state["var"],
                axis=axes, eps=lc.eps, momentum=lc.decay)
            return self.activation(y), {"mean": new_mean, "var": new_var}, mask
        y = nn_ops.batchnorm.fn(x, state["mean"], state["var"], gamma, beta, eps=lc.eps)
        return self.activation(y), state, mask


class LocalResponseNormalizationImpl(Layer):
    """layers/normalization/LocalResponseNormalization.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        lc = self.lc
        y = nn_ops.local_response_normalization.fn(
            x, depth=lc.n, bias=lc.k, alpha=lc.alpha, beta=lc.beta)
        return y, state, mask


class ActivationLayerImpl(Layer):
    """layers/ActivationLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        return self.activation(x), state, mask


class DropoutLayerImpl(Layer):
    """layers/DropoutLayer.java."""

    def apply(self, params, x, state, *, train, rng, mask=None):
        if not train:
            return x, state, mask
        return nn_ops.dropout.fn(x, rng, rate=self.lc.rate), state, mask


# ---------------------------------------------------------------------------
# Recurrent layers — lax.scan over time (layers/recurrent/*)
# ---------------------------------------------------------------------------


def _lstm_scan(params, x0, h0, c0, mask, *, gate_act, cell_act, reverse=False):
    """Scan an LSTM over (N, T, F). Gate math per LSTMHelpers.java:
    gates = x·Wih + h·Whh + b, order [i, f, o, g]; c' = f*c + i*g;
    h = o * cell_act(c') — the layer's configured activation IS the
    cell-output activation (reference default tanh), not a post-transform.

    The whole loop is one lax.scan — XLA unrolls/pipelines it; the per-step
    matmuls hit the MXU batched over N.
    """
    w_ih, w_hh, b = params["W"], params["RW"], params["b"]

    masked = mask is not None

    def step(carry, xm):
        h, c = carry
        xt, mt = xm
        gates = xt @ w_ih + h @ w_hh + b
        i, f, o, g = jnp.split(gates, 4, axis=-1)
        i, f, o = gate_act(i), gate_act(f), gate_act(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * cell_act(c_new)
        if masked:
            m = mt[:, None]
            h_new = jnp.where(m > 0, h_new, h)
            c_new = jnp.where(m > 0, c_new, c)
        return (h_new, c_new), h_new

    xs = jnp.swapaxes(x0, 0, 1)  # (T, N, F)
    ms = jnp.swapaxes(mask, 0, 1) if masked else jnp.zeros((xs.shape[0], 0))
    (h_last, c_last), hs = jax.lax.scan(step, (h0, c0), (xs, ms), reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), h_last, c_last


class LSTMImpl(Layer):
    """layers/recurrent/LSTM.java — scan-based, mask-aware, stateful-capable.

    The configured ``activation`` is the cell-output activation inside the
    scan (reference default tanh). Stateful rnnTimeStep() support passes
    ``initial=(h0, c0)`` and consumes the returned last state (wired by the
    network's rnn_time_step path).
    """

    reverse = False

    def init(self, key) -> Params:
        lc = self.lc
        k1, k2 = jax.random.split(key)
        b = jnp.zeros((4 * lc.n_out,), self.dtype)
        # forget-gate bias init (reference forgetGateBiasInit): gate order [i,f,o,g]
        b = b.at[lc.n_out : 2 * lc.n_out].set(lc.forget_gate_bias_init)
        return {
            "W": init_weights(k1, (lc.n_in, 4 * lc.n_out), self.winit, dtype=self.dtype),
            "RW": init_weights(k2, (lc.n_out, 4 * lc.n_out), self.winit, dtype=self.dtype),
            "b": b,
        }

    def apply(self, params, x, state, *, train, rng, mask=None, initial=None):
        lc = self.lc
        x = self._maybe_dropout(x, train=train, rng=rng)
        n = x.shape[0]
        if initial is not None:
            h0, c0 = initial
        else:
            h0 = jnp.zeros((n, lc.n_out), x.dtype)
            c0 = jnp.zeros((n, lc.n_out), x.dtype)
        gate_act = get_activation(lc.gate_activation)
        hs, h_last, c_last = _lstm_scan(
            params, x, h0, c0, mask, gate_act=gate_act, cell_act=self.activation,
            reverse=self.reverse)
        return hs, state, mask

    def zero_state(self, batch: int, dtype=jnp.float32):
        n = self.lc.n_out
        return (jnp.zeros((batch, n), dtype), jnp.zeros((batch, n), dtype))

    def apply_with_state(self, params, x, *, mask=None, initial=None):
        """Stateful forward for rnn_time_step: returns (out, (h_last, c_last))."""
        lc = self.lc
        n = x.shape[0]
        if initial is not None:
            h0, c0 = initial
        else:
            h0 = jnp.zeros((n, lc.n_out), x.dtype)
            c0 = jnp.zeros((n, lc.n_out), x.dtype)
        hs, h_last, c_last = _lstm_scan(
            params, x, h0, c0, mask, gate_act=get_activation(lc.gate_activation),
            cell_act=self.activation, reverse=self.reverse)
        return hs, (h_last, c_last)


class SimpleRnnImpl(Layer):
    """layers/recurrent/SimpleRnn.java: h' = act(x·W + h·RW + b)."""

    def init(self, key) -> Params:
        lc = self.lc
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weights(k1, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype),
            "RW": init_weights(k2, (lc.n_out, lc.n_out), self.winit, dtype=self.dtype),
            "b": jnp.zeros((lc.n_out,), self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None, initial=None):
        x = self._maybe_dropout(x, train=train, rng=rng)
        hs, _ = self.apply_with_state(params, x, mask=mask, initial=initial)
        return hs, state, mask

    def zero_state(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.lc.n_out), dtype)

    def apply_with_state(self, params, x, *, mask=None, initial=None):
        """Shared scan; returns (out, h_last) — the single recurrence impl
        for both training forward and stateful rnn_time_step."""
        lc = self.lc
        n = x.shape[0]
        h0 = initial if initial is not None else jnp.zeros((n, lc.n_out), x.dtype)
        act = self.activation
        masked = mask is not None

        def step(h, xm):
            xt, mt = xm
            h_new = act(xt @ params["W"] + h @ params["RW"] + params["b"])
            if masked:
                h_new = jnp.where(mt[:, None] > 0, h_new, h)
            return h_new, h_new

        xs = jnp.swapaxes(x, 0, 1)
        ms = jnp.swapaxes(mask, 0, 1) if masked else jnp.zeros((xs.shape[0], 0))
        h_last, hs = jax.lax.scan(step, h0, (xs, ms))
        return jnp.swapaxes(hs, 0, 1), h_last


class BidirectionalImpl(Layer):
    """layers/recurrent/BidirectionalLayer.java: fwd + bwd inner RNN, merged."""

    def __init__(self, net_conf, lc, itype):
        super().__init__(net_conf, lc, itype)
        inner = lc.inner()
        self.fwd_layer = build_layer(net_conf, inner, itype)
        self.bwd_layer = build_layer(net_conf, inner, itype)
        if isinstance(self.bwd_layer, LSTMImpl):
            self.bwd_layer.reverse = True

    def init(self, key) -> Params:
        k1, k2 = jax.random.split(key)
        return {"fwd": self.fwd_layer.init(k1), "bwd": self.bwd_layer.init(k2)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        yf, _, _ = self.fwd_layer.apply(params["fwd"], x, {}, train=train, rng=rng, mask=mask)
        if isinstance(self.bwd_layer, LSTMImpl):
            yb, _, _ = self.bwd_layer.apply(params["bwd"], x, {}, train=train, rng=rng, mask=mask)
        else:
            xr = jnp.flip(x, axis=1)
            mr = None if mask is None else jnp.flip(mask, axis=1)
            yb, _, _ = self.bwd_layer.apply(params["bwd"], xr, {}, train=train, rng=rng, mask=mr)
            yb = jnp.flip(yb, axis=1)
        mode = self.lc.mode
        if mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif mode == "add":
            y = yf + yb
        elif mode == "mul":
            y = yf * yb
        elif mode == "average":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(f"unknown Bidirectional mode {mode}")
        return y, state, mask


class RnnOutputLayerImpl(Layer):
    """layers/recurrent/RnnOutputLayer.java: time-distributed dense + loss."""

    def init(self, key) -> Params:
        lc = self.lc
        p = {"W": init_weights(key, (lc.n_in, lc.n_out), self.winit, dtype=self.dtype)}
        if lc.has_bias:
            p["b"] = jnp.zeros((lc.n_out,), self.dtype)
        return p

    def apply(self, params, x, state, *, train, rng, mask=None):
        z = x @ params["W"]
        if "b" in params:
            z = z + params["b"]
        return self.activation(z), state, mask


class LastTimeStepImpl(Layer):
    """layers/recurrent/LastTimeStepLayer.java: inner RNN -> last unmasked step."""

    def __init__(self, net_conf, lc, itype):
        super().__init__(net_conf, lc, itype)
        self.inner_layer = build_layer(net_conf, lc.inner(), itype)

    def init(self, key) -> Params:
        return {"inner": self.inner_layer.init(key)}

    def apply(self, params, x, state, *, train, rng, mask=None):
        y, _, _ = self.inner_layer.apply(params["inner"], x, {}, train=train, rng=rng, mask=mask)
        if mask is None:
            out = y[:, -1]
        else:
            idx = jnp.maximum(mask.sum(axis=1).astype(jnp.int32) - 1, 0)
            out = y[jnp.arange(y.shape[0]), idx]
        return out, state, None


class SelfAttentionLayerImpl(Layer):
    """layers/SelfAttentionLayer.java — MHA with Q=K=V=input sequence.

    Lowers to the registry's multi_head_dot_product_attention (which the
    platform-helper table may override with a Pallas flash-attention kernel
    on TPU — the cuDNN-helper analog)."""

    def init(self, key) -> Params:
        lc = self.lc
        ks = jax.random.split(key, 4)
        d = lc.n_out
        return {
            "Wq": init_weights(ks[0], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wk": init_weights(ks[1], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wv": init_weights(ks[2], (lc.n_in, d), self.winit, dtype=self.dtype),
            "Wo": init_weights(ks[3], (d, d), self.winit, dtype=self.dtype),
        }

    def apply(self, params, x, state, *, train, rng, mask=None):
        h = self.lc.n_heads
        q = x @ params["Wq"]
        k = x @ params["Wk"]
        v = x @ params["Wv"]
        n, t, d = q.shape
        dh = d // h

        def split(a):
            return a.reshape(n, t, h, dh).transpose(0, 2, 1, 3)

        qh, kh, vh = split(q), split(k), split(v)
        scores = (qh @ jnp.swapaxes(kh, -1, -2)) / jnp.sqrt(jnp.asarray(dh, x.dtype))
        if mask is not None:
            am = mask[:, None, None, :]
            scores = jnp.where(am > 0, scores, -1e9)
        attn = jax.nn.softmax(scores, axis=-1)
        out = (attn @ vh).transpose(0, 2, 1, 3).reshape(n, t, d)
        return out @ params["Wo"], state, mask


LAYER_IMPLS: Dict[Type[C.LayerConf], Type[Layer]] = {
    C.DenseLayer: DenseLayerImpl,
    C.OutputLayer: OutputLayerImpl,
    C.LossLayer: LossLayerImpl,
    C.EmbeddingLayer: EmbeddingLayerImpl,
    C.EmbeddingSequenceLayer: EmbeddingSequenceLayerImpl,
    C.ConvolutionLayer: ConvolutionLayerImpl,
    C.Deconvolution2D: Deconvolution2DImpl,
    C.DepthwiseConvolution2D: DepthwiseConvolution2DImpl,
    C.SeparableConvolution2D: SeparableConvolution2DImpl,
    C.SubsamplingLayer: SubsamplingLayerImpl,
    C.Upsampling2D: Upsampling2DImpl,
    C.GlobalPoolingLayer: GlobalPoolingLayerImpl,
    C.BatchNormalization: BatchNormalizationImpl,
    C.LocalResponseNormalization: LocalResponseNormalizationImpl,
    C.ActivationLayer: ActivationLayerImpl,
    C.DropoutLayer: DropoutLayerImpl,
    C.LSTM: LSTMImpl,
    C.GravesLSTM: LSTMImpl,
    C.SimpleRnn: SimpleRnnImpl,
    C.Bidirectional: BidirectionalImpl,
    C.RnnOutputLayer: RnnOutputLayerImpl,
    C.LastTimeStep: LastTimeStepImpl,
    C.SelfAttentionLayer: SelfAttentionLayerImpl,
}


def build_layer(net_conf: C.MultiLayerConfiguration, lc: C.LayerConf, itype: C.InputType) -> Layer:
    impl = LAYER_IMPLS.get(type(lc))
    if impl is None:
        raise ValueError(f"no runtime impl for layer config {type(lc).__name__}")
    return impl(net_conf, lc, itype)


def apply_preprocessor(p: Optional[C.InputPreProcessor], x):
    """conf/preprocessor/* forward application."""
    if p is None:
        return x
    if isinstance(p, C.FeedForwardToCnnPreProcessor):
        # reference flattening is NCHW C-major; our runtime layout is NHWC
        return x.reshape(x.shape[0], p.channels, p.height, p.width).transpose(0, 2, 3, 1)
    if isinstance(p, C.CnnToFeedForwardPreProcessor):
        # inverse: NHWC -> NCHW-major flatten to match reference flat ordering
        return x.transpose(0, 3, 1, 2).reshape(x.shape[0], -1)
    if isinstance(p, C.RnnToFeedForwardPreProcessor):
        return x.reshape(-1, x.shape[-1])
    if isinstance(p, C.FeedForwardToRnnPreProcessor):
        raise ValueError("FeedForwardToRnnPreProcessor needs batch size context; unsupported standalone")
    return x
