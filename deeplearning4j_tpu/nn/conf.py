"""Declarative layer/network configuration with JSON round-trip.

Reference parity:
  * org/deeplearning4j/nn/conf/NeuralNetConfiguration.java (builder),
    MultiLayerConfiguration.java, conf/layers/* (DenseLayer, ConvolutionLayer,
    SubsamplingLayer, BatchNormalization, LSTM, EmbeddingLayer, OutputLayer,
    ...), conf/inputs/InputType.java (shape inference between layers),
    conf/preprocessor/* (shape adapters).
  * Jackson-polymorphic JSON serialization — the property that makes
    ModelSerializer zips self-describing — is reproduced with an "@type"
    discriminator and dataclass round-trip.

TPU-native realization: configs are frozen dataclasses; ``build()`` produces a
``MultiLayerConfiguration`` whose layers know how to (a) infer their output
InputType, (b) initialize a param pytree leaf-dict, and (c) apply as a pure
function (see layers.py). The runtime model (multilayer.py) compiles the whole
stack into one XLA program.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from deeplearning4j_tpu.nn.updater import Updater, Adam, get_updater

# ---------------------------------------------------------------------------
# InputType — shape inference tokens (conf/inputs/InputType.java)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class InputType:
    """Shape token flowing between layer configs at build time.

    kind: 'feedforward' (size,), 'recurrent' (size, timesteps),
    'convolutional' (height, width, channels — stored NHWC internally per
    SURVEY §8.3 layout policy; the NCHW reference order is accepted at the API
    edge), 'convolutionalflat'.
    """

    kind: str
    size: int = 0
    height: int = 0
    width: int = 0
    channels: int = 0
    depth: int = 0  # convolutional3d only
    timesteps: int = -1  # -1: variable

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("feedforward", size=size)

    @staticmethod
    def recurrent(size: int, timesteps: int = -1) -> "InputType":
        return InputType("recurrent", size=size, timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("convolutional", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int,
                        channels: int) -> "InputType":
        """NDHWC volumetric input (InputType.InputTypeConvolutional3D)."""
        return InputType("convolutional3d", depth=depth, height=height,
                         width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(
            "convolutionalflat",
            size=height * width * channels,
            height=height,
            width=width,
            channels=channels,
        )

    def flat_size(self) -> int:
        if self.kind in ("feedforward", "convolutionalflat", "recurrent"):
            return self.size if self.size else self.height * self.width * self.channels
        if self.kind == "convolutional3d":
            return self.depth * self.height * self.width * self.channels
        return self.height * self.width * self.channels

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        return InputType(**d)


# ---------------------------------------------------------------------------
# Layer configs
# ---------------------------------------------------------------------------


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def dl4j_drop_out(retain_prob: float) -> float:
    """Convert the reference's ``dropOut(x)`` retain-probability argument
    (conf/layers/Layer.java — x = probability an activation is KEPT) to this
    framework's ``dropout`` drop rate. dropOut(0.8) → dropout=0.2."""
    if retain_prob == 0.0:
        return 0.0  # reference sentinel: dropOut(0.0) means dropout disabled
    if not 0.0 < retain_prob <= 1.0:
        raise ValueError(f"retain probability must be in [0, 1], got {retain_prob}")
    return 1.0 - retain_prob


@dataclasses.dataclass(frozen=True)
class LayerConf:
    """Base layer config (conf/layers/Layer.java analog).

    Per-layer overrides of the net-wide defaults (updater/lr/regularization/
    weight init) mirror the reference's layer-level overrides.
    """

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    weight_decay: Optional[float] = None
    # DROP RATE (fraction zeroed), NOT the reference's dropOut(x) retain
    # probability. Porting a DL4J config? Use dl4j_drop_out(retain_prob) to
    # convert — dropOut(0.8) in the reference means keep-80%, i.e. dropout=0.2.
    dropout: Optional[float] = None
    updater: Optional[Any] = None

    # --- overridden by subclasses ---
    def output_type(self, itype: InputType) -> InputType:
        return itype

    def has_params(self) -> bool:
        return False

    # JSON
    def to_dict(self) -> Dict[str, Any]:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Updater):
                v = {"__updater__": v.to_dict()}
            d[f.name] = v
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "LayerConf":
        def tuplify(v):
            return tuple(tuplify(x) for x in v) if isinstance(v, list) else v

        d = dict(d)
        cls = LAYER_TYPES[d.pop("@type")]
        for k, v in list(d.items()):
            if isinstance(v, dict) and "__updater__" in v:
                d[k] = Updater.from_dict(v["__updater__"])
            elif isinstance(v, list):
                d[k] = tuplify(v)
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class DenseLayer(LayerConf):
    """conf/layers/DenseLayer.java: fully connected, W (nIn,nOut) + b."""

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class OutputLayer(DenseLayer):
    """conf/layers/OutputLayer.java: dense + loss function."""

    loss: str = "mcxent"


@dataclasses.dataclass(frozen=True)
class LossLayer(LayerConf):
    """conf/layers/LossLayer.java: loss without params (identity transform)."""

    loss: str = "mcxent"


@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(LayerConf):
    """conf/layers/EmbeddingLayer.java: int ids -> embedding rows."""

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = False

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class EmbeddingSequenceLayer(LayerConf):
    """conf/layers/EmbeddingSequenceLayer.java: id sequence -> vec sequence."""

    n_in: int = 0
    n_out: int = 0
    input_length: int = -1

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, self.input_length)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(LayerConf):
    """conf/layers/ConvolutionLayer.java.

    NCHW at the API edge (reference default, `hasBias`, `convolutionMode`);
    NHWC internally (SURVEY §8.3). kernel/stride/dilation are (h, w) pairs.
    convolution_mode: 'truncate' (reference Truncate ≙ VALID-with-truncation)
    or 'same'.
    """

    n_in: int = 0
    n_out: int = 0
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"
    has_bias: bool = True
    # TPU stem optimization: lower a 7x7/stride-2/'same' conv as a 4x4/stride-1
    # conv over a 2x2 space-to-depth input (MLPerf ResNet trick). Mathematically
    # exact — the canonical (7,7,C,F) kernel is kept in params and zero-padded/
    # regrouped at apply time, so checkpoints and gradients are identical; only
    # the XLA lowering changes (C=3 convs waste the MXU's 128-wide lanes).
    s2d_stem: bool = False

    def output_type(self, itype):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        ph, pw = _pair(self.padding)
        ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
        if self.convolution_mode == "same":
            oh = -(-itype.height // sh)
            ow = -(-itype.width // sw)
        else:
            oh = (itype.height + 2 * ph - ekh) // sh + 1
            ow = (itype.width + 2 * pw - ekw) // sw + 1
        return InputType.convolutional(oh, ow, self.n_out)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class Deconvolution2D(ConvolutionLayer):
    """conf/layers/Deconvolution2D.java: transposed convolution."""

    def output_type(self, itype):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == "same":
            oh, ow = itype.height * sh, itype.width * sw
        else:
            oh = sh * (itype.height - 1) + kh - 2 * ph
            ow = sw * (itype.width - 1) + kw - 2 * pw
        return InputType.convolutional(oh, ow, self.n_out)


@dataclasses.dataclass(frozen=True)
class DepthwiseConvolution2D(ConvolutionLayer):
    """conf/layers/DepthwiseConvolution2D.java (depth_multiplier folded into n_out)."""

    depth_multiplier: int = 1

    def output_type(self, itype):
        base = super().output_type(itype)
        return InputType.convolutional(base.height, base.width, itype.channels * self.depth_multiplier)


@dataclasses.dataclass(frozen=True)
class SeparableConvolution2D(ConvolutionLayer):
    """conf/layers/SeparableConvolution2D.java."""

    depth_multiplier: int = 1


@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(LayerConf):
    """conf/layers/SubsamplingLayer.java: pooling (MAX/AVG/PNORM)."""

    pooling_type: str = "max"  # max | avg | pnorm
    kernel: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def output_type(self, itype):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == "same":
            oh = -(-itype.height // sh)
            ow = -(-itype.width // sw)
        else:
            oh = (itype.height + 2 * ph - kh) // sh + 1
            ow = (itype.width + 2 * pw - kw) // sw + 1
        return InputType.convolutional(oh, ow, itype.channels)


@dataclasses.dataclass(frozen=True)
class Upsampling2D(LayerConf):
    """conf/layers/Upsampling2D.java."""

    size: Tuple[int, int] = (2, 2)

    def output_type(self, itype):
        sh, sw = _pair(self.size)
        return InputType.convolutional(itype.height * sh, itype.width * sw, itype.channels)


@dataclasses.dataclass(frozen=True)
class GlobalPoolingLayer(LayerConf):
    """conf/layers/GlobalPoolingLayer.java: conv/recurrent -> feedforward."""

    pooling_type: str = "avg"  # avg | max | sum | pnorm

    def output_type(self, itype):
        if itype.kind == "recurrent":
            return InputType.feed_forward(itype.size)
        return InputType.feed_forward(itype.channels)


@dataclasses.dataclass(frozen=True)
class BatchNormalization(LayerConf):
    """conf/layers/BatchNormalization.java: gamma/beta + running stats."""

    n_out: int = 0  # inferred if 0
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False

    def output_type(self, itype):
        return itype

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class DuelingQLayer(LayerConf):
    """Dueling-DQN head (reference RL4J QLearning dueling configuration):
    value stream V(s) (scalar) + advantage stream A(s,·), combined with the
    standard identifiable aggregation Q = V + A − mean(A)."""

    n_in: int = 0
    n_actions: int = 0

    def output_type(self, itype):
        return InputType.feed_forward(self.n_actions)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class MoELayer(LayerConf):
    """Mixture-of-Experts FFN layer (GShard/Switch recipe) as a standard
    LayerConf — usable in MultiLayerNetwork/ComputationGraph and composing
    with ParallelWrapper(mesh={'data':…, 'expert':…}) + ``moe_ep_rules()``:
    the dispatch/combine einsums are written dense so GSPMD partitions the
    expert axis and inserts the all-to-alls (no hand shard_map).

    top_k=1 is Switch routing, top_k=2 the GShard default. Assignments past
    capacity C = ceil(cf·S·k/E) are dropped; a token whose EVERY assignment
    is dropped passes through as identity (never zeros). The load-balance
    aux loss rides the layer STATE under ``_aux_loss`` (summed into the
    training loss by the step functions); ``_dropped_frac`` reports the
    fraction of token→expert assignments dropped at capacity — surfaced to
    listeners/UI as a routing-health diagnostic.

    Exceeds-reference axis (SURVEY §6.7): the reference has no MoE; recipe
    per the public GShard/Switch papers.
    """

    n_in: int = 0
    d_hidden: int = 0
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_weight: float = 1e-2

    def output_type(self, itype):
        return itype

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class FusedBottleneck(LayerConf):
    """TPU-fused ResNet v1 bottleneck block: 1×1 → BN+relu → 3×3 → BN+relu
    → 1×1 → BN → (+shortcut) → relu as ONE layer, so the 1×1 convs can run
    the Pallas conv+BN-fusion kernel (ops/pallas_convbn.py). Identical math
    to the composed layers (zoo ResNet50's _bottleneck expansion); a pure
    performance arrangement for HBM-bound conv/BN stacks.
    """

    n_in: int = 0
    filters: int = 0
    stride: int = 1
    project: bool = False
    decay: float = 0.9
    eps: float = 1e-5

    def output_type(self, itype):
        s = self.stride
        return InputType.convolutional(
            -(-itype.height // s), -(-itype.width // s), 4 * self.filters)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(LayerConf):
    """conf/layers/LocalResponseNormalization.java."""

    n: int = 5
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75


@dataclasses.dataclass(frozen=True)
class ActivationLayer(LayerConf):
    """conf/layers/ActivationLayer.java: standalone activation."""


@dataclasses.dataclass(frozen=True)
class DropoutLayer(LayerConf):
    """conf/layers/DropoutLayer.java: standalone dropout.

    ``mode`` selects the IDropout variant (conf/dropout/*.java):
    "elementwise" (Dropout), "spatial" (SpatialDropout — drops whole
    feature maps along the trailing channel axis), "alpha"
    (AlphaDropout — SELU-preserving), "gaussian" (GaussianDropout —
    multiplicative N(1, rate/(1-rate)) noise).
    """

    rate: float = 0.5
    mode: str = "elementwise"


@dataclasses.dataclass(frozen=True)
class LSTM(LayerConf):
    """conf/layers/LSTM.java: scan-based LSTM over the time axis.

    Gate order and math follow the reference LSTMHelpers.java
    (input/forget/output/cell-gate with optional forget-gate bias init).
    """

    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.timesteps)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """conf/layers/GravesLSTM.java (legacy peephole variant — math matches
    plain LSTM here; peepholes omitted, documented divergence)."""


@dataclasses.dataclass(frozen=True)
class GRU(LayerConf):
    """GRU recurrent layer over the catalog's ``gru_cell`` declarable op
    (libnd4j gruCell.cpp — the reference exposes the CELL op but never grew
    a layer around it; this closes that gap). Gate order r, z, n with
    separate input/recurrent biases (the Keras reset_after=True / PyTorch
    convention, so imported weights drop straight in)."""

    n_in: int = 0
    n_out: int = 0

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.timesteps)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class SimpleRnn(LayerConf):
    """conf/layers/recurrent/SimpleRnn.java."""

    n_in: int = 0
    n_out: int = 0

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.timesteps)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class Bidirectional(LayerConf):
    """conf/layers/recurrent/Bidirectional.java: wraps an RNN layer config.

    mode: CONCAT | ADD | MUL | AVERAGE (reference Bidirectional.Mode).
    """

    fwd: Optional[Dict[str, Any]] = None  # serialized inner LayerConf
    mode: str = "concat"

    def inner(self) -> LayerConf:
        return LayerConf.from_dict(dict(self.fwd))

    def output_type(self, itype):
        out = self.inner().output_type(itype)
        if self.mode == "concat":
            return InputType.recurrent(out.size * 2, out.timesteps)
        return out

    def has_params(self):
        return True

    @staticmethod
    def wrap(inner: LayerConf, mode: str = "concat", name=None) -> "Bidirectional":
        return Bidirectional(fwd=inner.to_dict(), mode=mode, name=name)


@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(LayerConf):
    """conf/layers/RnnOutputLayer.java: per-timestep dense + loss."""

    n_in: int = 0
    n_out: int = 0
    loss: str = "mcxent"
    has_bias: bool = True

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.timesteps)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class LastTimeStep(LayerConf):
    """conf/layers/recurrent/LastTimeStep.java: wraps an RNN, emits last step
    (mask-aware)."""

    fwd: Optional[Dict[str, Any]] = None
    mode: str = "last"

    def inner(self) -> LayerConf:
        return LayerConf.from_dict(dict(self.fwd))

    def output_type(self, itype):
        out = self.inner().output_type(itype)
        return InputType.feed_forward(out.size)

    def has_params(self):
        return True

    @staticmethod
    def wrap(inner: LayerConf, name=None) -> "LastTimeStep":
        return LastTimeStep(fwd=inner.to_dict(), name=name)


@dataclasses.dataclass(frozen=True)
class SelfAttentionLayer(LayerConf):
    """conf/layers/SelfAttentionLayer.java: MHA over a sequence, Q=K=V=input."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    project_input: bool = True

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.timesteps)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class LearnedSelfAttentionLayer(LayerConf):
    """conf/layers/LearnedSelfAttentionLayer.java: a fixed set of LEARNED
    query vectors attends over the input sequence — output has n_queries
    timesteps regardless of input length (the reference's fixed-size
    sequence summarizer)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    n_queries: int = 1

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, self.n_queries)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class RecurrentAttentionLayer(LayerConf):
    """conf/layers/RecurrentAttentionLayer.java: RNN whose step input is
    augmented with single-head attention over the whole input sequence,
    queried by the previous hidden state — out_t = act(Wx·x_t + Wr·attn_t
    + b)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    activation: str = "tanh"

    def output_type(self, itype):
        return InputType.recurrent(self.n_out, itype.timesteps)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class AttentionVertex(LayerConf):
    """conf/graph/AttentionVertex.java: multi-head attention as a GRAPH
    vertex with PARAMS — inputs (queries, keys, values) or (queries,
    keys=values). Registered through GraphBuilder.add_vertex (which routes
    parameterized vertices onto the layer path)."""

    n_out: int = 0
    n_heads: int = 1
    n_in_queries: int = 0
    n_in_keys: int = 0
    n_in_values: int = 0
    # Keras MultiHeadAttention call order is (query, VALUE, key) — set by
    # the importer so 3-input wiring lands on (q, k, v) internally
    keras_order: bool = False
    has_bias: bool = False
    d_out: int = 0  # output projection width when != n_out (keras MHA)

    def output_type(self, itype):
        return InputType.recurrent(self.d_out or self.n_out, itype.timesteps)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class Convolution1D(LayerConf):
    """conf/layers/Convolution1DLayer.java: temporal conv over (N, T, C)."""

    n_in: int = 0
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    convolution_mode: str = "same"  # same | valid (truncate)
    dilation: int = 1

    def output_type(self, itype):
        t = itype.timesteps
        if t and t > 0:
            if self.convolution_mode == "same":
                t = -(-t // self.stride)
            else:
                eff = (self.kernel - 1) * self.dilation + 1
                t = (t - eff) // self.stride + 1
        return InputType.recurrent(self.n_out, t)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class Convolution3D(LayerConf):
    """conf/layers/Convolution3D.java: volumetric conv over (N, D, H, W, C)
    (NDHWC — the TPU-friendly channels-last 3-D layout)."""

    n_in: int = 0
    n_out: int = 0
    kernel: Tuple[int, int, int] = (3, 3, 3)
    stride: Tuple[int, int, int] = (1, 1, 1)
    convolution_mode: str = "same"

    def output_type(self, itype):
        def out(sz, k, s):
            return -(-sz // s) if self.convolution_mode == "same" \
                else (sz - k) // s + 1

        k, s = self.kernel, self.stride
        return InputType.convolutional3d(
            out(itype.depth, k[0], s[0]), out(itype.height, k[1], s[1]),
            out(itype.width, k[2], s[2]), self.n_out)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class Subsampling3DLayer(LayerConf):
    """conf/layers/Subsampling3DLayer.java: 3-D pooling (NDHWC)."""

    kernel: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (2, 2, 2)
    pooling_type: str = "max"

    def output_type(self, itype):
        k, s = self.kernel, self.stride
        return InputType.convolutional3d(
            (itype.depth - k[0]) // s[0] + 1,
            (itype.height - k[1]) // s[1] + 1,
            (itype.width - k[2]) // s[2] + 1, itype.channels)


@dataclasses.dataclass(frozen=True)
class LocallyConnected2D(LayerConf):
    """conf/layers/LocallyConnected2D.java: conv topology with UNSHARED
    per-position weights."""

    n_in: int = 0
    n_out: int = 0
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    input_size: Tuple[int, int] = (0, 0)  # inferred at build when 0

    def output_type(self, itype):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        return InputType.convolutional(
            (itype.height - kh) // sh + 1, (itype.width - kw) // sw + 1,
            self.n_out)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class LocallyConnected1D(LayerConf):
    """conf/layers/LocallyConnected1D.java: temporal locally-connected."""

    n_in: int = 0
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    input_size: int = 0

    def output_type(self, itype):
        t = (itype.timesteps - self.kernel) // self.stride + 1 \
            if itype.timesteps and itype.timesteps > 0 else itype.timesteps
        return InputType.recurrent(self.n_out, t)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class PReLULayer(LayerConf):
    """conf/layers/PReLULayer.java: y = max(0,x) + alpha·min(0,x) with a
    LEARNED per-feature alpha."""

    n_in: int = 0  # feature count (last-axis size)

    def output_type(self, itype):
        return itype

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(LayerConf):
    """conf/layers/variational/VariationalAutoencoder.java: pretrainable
    VAE layer. Supervised forward emits the latent MEAN (the reference's
    activate() semantics); reconstruction_log_prob / pretrain losses live
    on the impl."""

    n_in: int = 0
    n_out: int = 0  # latent size
    encoder_layer_sizes: Tuple[int, ...] = (256,)
    decoder_layer_sizes: Tuple[int, ...] = (256,)
    activation: str = "leakyrelu"
    reconstruction_distribution: str = "gaussian"  # gaussian | bernoulli

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class ZeroPadding1DLayer(LayerConf):
    """conf/layers/ZeroPadding1DLayer.java: pad the time axis of (N, T, C)."""

    padding: Tuple[int, int] = (1, 1)

    def output_type(self, itype):
        t = itype.timesteps
        p = _pair(self.padding)
        return InputType.recurrent(itype.size, t + p[0] + p[1] if t and t > 0 else t)


@dataclasses.dataclass(frozen=True)
class ZeroPaddingLayer(LayerConf):
    """conf/layers/ZeroPaddingLayer.java: spatial zero-pad, NHWC.
    ``padding`` = (top, bottom, left, right)."""

    padding: Tuple[int, int, int, int] = (1, 1, 1, 1)

    def output_type(self, itype):
        t, b, l, r = self.padding
        return InputType.convolutional(itype.height + t + b,
                                       itype.width + l + r, itype.channels)


@dataclasses.dataclass(frozen=True)
class ZeroPadding3DLayer(LayerConf):
    """conf/layers/ZeroPadding3DLayer.java: NDHWC zero-pad.
    ``padding`` = (d_lo, d_hi, h_lo, h_hi, w_lo, w_hi)."""

    padding: Tuple[int, int, int, int, int, int] = (1, 1, 1, 1, 1, 1)

    def output_type(self, itype):
        p = self.padding
        return InputType.convolutional3d(
            itype.depth + p[0] + p[1], itype.height + p[2] + p[3],
            itype.width + p[4] + p[5], itype.channels)


@dataclasses.dataclass(frozen=True)
class Cropping1D(LayerConf):
    """conf/layers/convolutional/Cropping1D.java: crop the time axis."""

    cropping: Tuple[int, int] = (1, 1)

    def output_type(self, itype):
        t = itype.timesteps
        c = _pair(self.cropping)
        return InputType.recurrent(itype.size, t - c[0] - c[1] if t and t > 0 else t)


@dataclasses.dataclass(frozen=True)
class Cropping2D(LayerConf):
    """conf/layers/convolutional/Cropping2D.java: spatial crop, NHWC.
    ``cropping`` = (top, bottom, left, right)."""

    cropping: Tuple[int, int, int, int] = (1, 1, 1, 1)

    def output_type(self, itype):
        t, b, l, r = self.cropping
        return InputType.convolutional(itype.height - t - b,
                                       itype.width - l - r, itype.channels)


@dataclasses.dataclass(frozen=True)
class Cropping3D(LayerConf):
    """conf/layers/convolutional/Cropping3D.java: NDHWC crop.
    ``cropping`` = (d_lo, d_hi, h_lo, h_hi, w_lo, w_hi)."""

    cropping: Tuple[int, int, int, int, int, int] = (1, 1, 1, 1, 1, 1)

    def output_type(self, itype):
        c = self.cropping
        return InputType.convolutional3d(
            itype.depth - c[0] - c[1], itype.height - c[2] - c[3],
            itype.width - c[4] - c[5], itype.channels)


@dataclasses.dataclass(frozen=True)
class Upsampling1D(LayerConf):
    """conf/layers/Upsampling1D.java: repeat each timestep ``size`` times."""

    size: int = 2

    def output_type(self, itype):
        t = itype.timesteps
        return InputType.recurrent(itype.size, t * self.size if t and t > 0 else t)


@dataclasses.dataclass(frozen=True)
class Upsampling3D(LayerConf):
    """conf/layers/Upsampling3D.java: nearest-neighbour ×size, NDHWC."""

    size: Tuple[int, int, int] = (2, 2, 2)

    def output_type(self, itype):
        s = self.size
        return InputType.convolutional3d(itype.depth * s[0], itype.height * s[1],
                                         itype.width * s[2], itype.channels)


@dataclasses.dataclass(frozen=True)
class Subsampling1DLayer(LayerConf):
    """conf/layers/Subsampling1DLayer.java: temporal pooling over (N, T, C)."""

    kernel: int = 2
    stride: int = 2
    pooling_type: str = "max"  # max | avg
    convolution_mode: str = "valid"

    def output_type(self, itype):
        t = itype.timesteps
        if t and t > 0:
            if self.convolution_mode == "same":
                t = -(-t // self.stride)
            else:
                t = (t - self.kernel) // self.stride + 1
        return InputType.recurrent(itype.size, t)


@dataclasses.dataclass(frozen=True)
class Deconvolution3D(LayerConf):
    """conf/layers/Deconvolution3D.java: transposed volumetric conv, NDHWC."""

    n_in: int = 0
    n_out: int = 0
    kernel: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (2, 2, 2)
    convolution_mode: str = "valid"

    def output_type(self, itype):
        def out(sz, k, s):
            return sz * s if self.convolution_mode == "same" else (sz - 1) * s + k

        k, s = self.kernel, self.stride
        return InputType.convolutional3d(
            out(itype.depth, k[0], s[0]), out(itype.height, k[1], s[1]),
            out(itype.width, k[2], s[2]), self.n_out)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class CnnLossLayer(LayerConf):
    """conf/layers/CnnLossLayer.java: per-position 2-D loss (segmentation).
    No params; activation applied; labels shaped (N, H, W, C)."""

    loss: str = "mcxent"


@dataclasses.dataclass(frozen=True)
class RnnLossLayer(LayerConf):
    """conf/layers/RnnLossLayer.java: per-timestep loss over (N, T, C)."""

    loss: str = "mcxent"


@dataclasses.dataclass(frozen=True)
class MaskLayer(LayerConf):
    """conf/layers/util/MaskLayer.java: apply the current mask to the
    activations (zero masked timesteps), pass everything else through."""


@dataclasses.dataclass(frozen=True)
class MaskZeroLayer(LayerConf):
    """conf/layers/recurrent/MaskZeroLayer.java: derive a timestep mask from
    the input (steps where ALL features == mask_value are masked) before
    running the wrapped recurrent layer."""

    underlying: Optional[Any] = None  # LayerConf
    mask_value: float = 0.0

    def inner(self) -> "LayerConf":
        u = self.underlying
        return LayerConf.from_dict(u) if isinstance(u, dict) else u

    def output_type(self, itype):
        return self.inner().output_type(itype)

    def has_params(self):
        return self.inner().has_params()

    def to_dict(self):
        d = super().to_dict()
        if isinstance(d.get("underlying"), LayerConf):
            d["underlying"] = d["underlying"].to_dict()
        return d


@dataclasses.dataclass(frozen=True)
class RepeatVector(LayerConf):
    """conf/layers/misc/RepeatVector.java: (N, F) -> (N, n, F)."""

    n: int = 1

    def output_type(self, itype):
        return InputType.recurrent(itype.flat_size(), self.n)


@dataclasses.dataclass(frozen=True)
class ElementWiseMultiplicationLayer(LayerConf):
    """conf/layers/misc/ElementWiseMultiplicationLayer.java:
    out = act(x ⊙ w + b) with a learned per-feature scale."""

    n_in: int = 0
    n_out: int = 0

    def output_type(self, itype):
        return InputType.feed_forward(self.n_out or itype.flat_size())

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class FrozenLayerWithBackprop(LayerConf):
    """conf/layers/misc/FrozenLayerWithBackprop.java: wrapped layer gets NO
    parameter updates but still backprops gradients to earlier layers
    (FrozenLayer, by contrast, also blocks the flow — that variant lives in
    nn/transfer.py as the TransferLearning freeze mechanism)."""

    underlying: Optional[Any] = None

    def inner(self) -> "LayerConf":
        u = self.underlying
        return LayerConf.from_dict(u) if isinstance(u, dict) else u

    def output_type(self, itype):
        return self.inner().output_type(itype)

    def has_params(self):
        return self.inner().has_params()

    def to_dict(self):
        d = super().to_dict()
        if isinstance(d.get("underlying"), LayerConf):
            d["underlying"] = d["underlying"].to_dict()
        return d


@dataclasses.dataclass(frozen=True)
class CenterLossOutputLayer(DenseLayer):
    """conf/layers/CenterLossOutputLayer.java: softmax classification plus
    a decoupled center loss — λ·½‖f − sg(c_y)‖² pulls FEATURES toward their
    class center, α·½‖sg(f) − c_y‖² pulls CENTERS toward the batch features
    (its gradient α(c_y − f̄) is the reference's moving-average center
    update c ← c − α(c − f̄), realized through the optimizer)."""

    loss: str = "mcxent"
    alpha: float = 0.05     # center pull rate (reference `alpha`)
    lambda_: float = 2e-4   # feature-pull weight (reference `lambda`)


@dataclasses.dataclass(frozen=True)
class Yolo2OutputLayer(LayerConf):
    """conf/layers/objdetect/Yolo2OutputLayer.java: YOLOv2 anchor-box output.
    Forward is identity (activations are decoded inside the loss); the loss
    is the multi-part sum-squared objective (models/zoo.py TinyYOLO
    yolo_loss). Labels: (N, H, W, B, 5 + C) matching the prediction grid."""

    anchors: Tuple[Tuple[float, float], ...] = ()
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5
    loss: str = "yolo2"

    def loss_fn(self):
        """Bind THIS conf's lambdas/anchors into the shared yolo2 loss —
        networks check for a conf-provided loss_fn before get_loss(name)."""
        import functools

        from deeplearning4j_tpu.ops.losses import yolo2

        return functools.partial(
            yolo2, lambda_coord=self.lambda_coord,
            lambda_noobj=self.lambda_noobj,
            anchors=[list(a) for a in self.anchors] or None)

    def to_dict(self):
        d = super().to_dict()
        d["anchors"] = [list(a) for a in self.anchors]
        return d


@dataclasses.dataclass(frozen=True)
class PrimaryCapsules(LayerConf):
    """conf/layers/PrimaryCapsules.java (CapsNet): conv into
    (N, capsules, capsule_dim) with squash nonlinearity."""

    capsules: int = 8          # number of capsule CHANNELS (per spatial pos)
    capsule_dim: int = 8
    kernel: Tuple[int, int] = (9, 9)
    stride: Tuple[int, int] = (2, 2)

    def output_type(self, itype):
        kh, kw = self.kernel
        sh, sw = self.stride
        oh = (itype.height - kh) // sh + 1
        ow = (itype.width - kw) // sw + 1
        return InputType.recurrent(self.capsule_dim, oh * ow * self.capsules)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class CapsuleLayer(LayerConf):
    """conf/layers/CapsuleLayer.java: dynamic-routing capsules.
    Input (N, in_caps, in_dim) -> (N, capsules, capsule_dim)."""

    capsules: int = 10
    capsule_dim: int = 16
    routings: int = 3

    def output_type(self, itype):
        return InputType.recurrent(self.capsule_dim, self.capsules)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class CapsuleStrengthLayer(LayerConf):
    """conf/layers/CapsuleStrengthLayer.java: ‖capsule‖₂ per capsule —
    (N, caps, dim) -> (N, caps)."""

    def output_type(self, itype):
        return InputType.feed_forward(itype.timesteps if itype.timesteps > 0
                                      else itype.size)


# ---------------------------------------------------------------------------
# Preprocessors (conf/preprocessor/*) — shape adapters between layers
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class InputPreProcessor:
    """Base preprocessor. Applied to the activations flowing between layers."""

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        if d is None:
            return None
        d = dict(d)
        return PREPROCESSORS[d.pop("@type")](**d)


@dataclasses.dataclass(frozen=True)
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """(N, H*W*C) -> (N, H, W, C) [reference: -> NCHW; NHWC internally]."""

    height: int = 0
    width: int = 0
    channels: int = 0


@dataclasses.dataclass(frozen=True)
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """(N, H, W, C) -> (N, H*W*C); flatten order matches reference NCHW
    flattening (C-major) so exported flat params/activations line up."""

    height: int = 0
    width: int = 0
    channels: int = 0


@dataclasses.dataclass(frozen=True)
class Cnn3DToFeedForwardPreProcessor(InputPreProcessor):
    """(N, D, H, W, C) -> (N, D·H·W·C) (Cnn3DToFeedForwardPreProcessor.java;
    C-major flatten matching the reference NCDHW ordering)."""

    depth: int = 0
    height: int = 0
    width: int = 0
    channels: int = 0


@dataclasses.dataclass(frozen=True)
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """(N, T, F) -> (N*T, F)."""


@dataclasses.dataclass(frozen=True)
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """(N*T, F) -> (N, T, F)."""


PREPROCESSORS = {
    c.__name__: c
    for c in [
        FeedForwardToCnnPreProcessor,
        CnnToFeedForwardPreProcessor,
        Cnn3DToFeedForwardPreProcessor,
        RnnToFeedForwardPreProcessor,
        FeedForwardToRnnPreProcessor,
    ]
}



@dataclasses.dataclass(frozen=True)
class PermuteLayer(LayerConf):
    """Axis permutation of the non-batch dims (Keras Permute parity; the
    reference maps it through KerasPermute -> PermutePreprocessor).
    ``dims`` are 1-indexed non-batch axes, Keras convention."""

    dims: tuple = ()

    def output_type(self, itype):
        if itype.kind == "recurrent" and tuple(self.dims) == (2, 1):
            return InputType.recurrent(itype.timesteps, itype.size)
        if itype.kind == "convolutional" and len(self.dims) == 3:
            hwc = (itype.height, itype.width, itype.channels)
            ph, pw, pc = (hwc[d - 1] for d in self.dims)
            return InputType.convolutional(ph, pw, pc)
        if itype.kind == "feedforward":
            return itype
        raise ValueError(
            f"PermuteLayer: cannot infer the permuted shape for dims "
            f"{self.dims} on a {itype.kind} input")


@dataclasses.dataclass(frozen=True)
class ReshapeLayer(LayerConf):
    """Batch-preserving reshape (KerasReshape -> ReshapePreprocessor
    parity). ``target_shape`` excludes the batch dim; -1 infers."""

    target_shape: tuple = ()

    def output_type(self, itype):
        flat = itype.flat_size()
        shape = list(self.target_shape)
        if -1 in shape:
            known = 1
            for s in shape:
                if s != -1:
                    known *= int(s)
            shape[shape.index(-1)] = flat // max(known, 1)
        if len(shape) == 1:
            return InputType.feed_forward(shape[0])
        if len(shape) == 2:
            return InputType.recurrent(shape[1], shape[0])
        if len(shape) == 3:
            return InputType.convolutional(shape[0], shape[1], shape[2])
        return InputType.feed_forward(flat)


@dataclasses.dataclass(frozen=True)
class LayerNormalization(LayerConf):
    """Trailing-axis layer norm with learned gain/bias — the Keras
    LayerNormalization surface (the reference's samediff layer_norm op,
    libnd4j ops/declarable/generic/nn/layer_norm.cpp, as a layer)."""

    n_out: int = 0
    eps: float = 1e-3

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class GroupNormalization(LayerConf):
    """Group norm over the channel axis (Keras GroupNormalization parity);
    groups=-1 degenerates to instance norm, groups=1 to layer norm over
    spatial+channel."""

    n_out: int = 0
    groups: int = 32
    eps: float = 1e-3

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class RescaleLayer(LayerConf):
    """out = x * scale + offset with per-feature broadcast — the Keras
    Rescaling / adapted-Normalization preprocessing surface."""

    scale: Any = 1.0
    offset: Any = 0.0


@dataclasses.dataclass(frozen=True)
class DiscretizationLayer(LayerConf):
    """Keras Discretization surface: values → bin indices (int32) by the
    given boundaries; pairs with CategoryEncodingLayer for tabular nets."""

    bin_boundaries: Tuple[float, ...] = ()

    def output_type(self, itype):
        return itype


@dataclasses.dataclass(frozen=True)
class CategoryEncodingLayer(LayerConf):
    """Keras CategoryEncoding surface: int ids → one_hot / multi_hot /
    count vectors of width num_tokens."""

    num_tokens: int = 0
    output_mode: str = "multi_hot"

    def output_type(self, itype):
        return InputType.feed_forward(self.num_tokens)


@dataclasses.dataclass(frozen=True)
class EinsumDenseLayer(LayerConf):
    """Keras EinsumDense surface: out = einsum(equation, x, W) (+ bias on
    ``bias_axes``). The workhorse projection of keras-nlp transformer
    blocks; equation uses '...' for batch dims (e.g. '...d,de->...e')."""

    equation: str = ""
    out_shape: Tuple[int, ...] = ()      # W/output dims (no batch dims)
    bias_shape: Tuple[int, ...] = ()     # () = no bias

    def output_type(self, itype):
        import math

        eq = self.equation.replace(" ", "")
        out_spec = eq.split("->")[1]
        if itype.kind == "recurrent":
            # '...' preserves the (batch, time) prefix; explicit specs keep
            # recurrent shape only when the output is still rank-3
            if "..." in out_spec or len(out_spec) >= 3:
                return InputType.recurrent(int(self.out_shape[-1]),
                                           itype.timesteps)
            return InputType.feed_forward(int(self.out_shape[-1]))
        return InputType.feed_forward(int(math.prod(self.out_shape))
                                      if self.out_shape else itype.flat_size())

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class UnitNormLayer(LayerConf):
    """L2-normalize along the trailing axis (Keras UnitNormalization)."""

    eps: float = 1e-12


@dataclasses.dataclass(frozen=True)
class ConvLSTM2D(LayerConf):
    """Convolutional LSTM over (N, T, H, W, C) — KerasConvLSTM2D parity
    (the reference maps it onto its ConvLSTM; here gates are conv2d ops
    inside one lax.scan, so the MXU sees batched convs per step).

    Keras gate order i, f, c, o re-packs to our i, f, o, g at import."""

    n_in: int = 0
    filters: int = 0
    kernel: tuple = (3, 3)
    padding: str = "same"
    return_sequences: bool = False
    gate_activation: str = "sigmoid"

    def has_params(self):
        return True

    def output_type(self, itype):
        if self.padding not in ("same", "truncate", "valid"):
            raise ValueError(f"ConvLSTM2D padding {self.padding!r}")
        h, w = itype.height, itype.width
        if self.padding in ("truncate", "valid"):
            h = h - self.kernel[0] + 1
            w = w - self.kernel[1] + 1
        if self.return_sequences:
            return InputType("convolutional3d", depth=itype.depth or -1,
                             height=h, width=w, channels=self.filters)
        return InputType.convolutional(h, w, self.filters)



@dataclasses.dataclass(frozen=True)
class DotAttentionLayer(LayerConf):
    """Param-free Keras Attention / AdditiveAttention surface: multi-input
    (query, value[, key]) in KERAS order. ``additive`` picks Bahdanau
    scoring (tanh(q+k) reduced by ``scale`` when use_scale)."""

    use_scale: bool = False
    additive: bool = False
    scale: Any = None  # adapted scale vector (AdditiveAttention weights)

    def output_type(self, itype):
        return itype


@dataclasses.dataclass(frozen=True)
class SeparableConvolution1D(LayerConf):
    """Depthwise + pointwise temporal conv over (N, T, C) — the Keras
    SeparableConv1D surface (reference SeparableConvolution2D.java family,
    one dim down)."""

    n_in: int = 0
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    convolution_mode: str = "truncate"
    depth_multiplier: int = 1
    has_bias: bool = True

    def output_type(self, itype):
        t = itype.timesteps
        if t and t > 0:
            if self.convolution_mode == "same":
                t = -(-t // self.stride)
            else:
                t = (t - self.kernel) // self.stride + 1
        return InputType.recurrent(self.n_out, t)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class Deconvolution1D(LayerConf):
    """Transposed temporal conv over (N, T, C) — Keras Conv1DTranspose
    surface (Deconvolution2D.java family, one dim down)."""

    n_in: int = 0
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def output_type(self, itype):
        t = itype.timesteps
        if t and t > 0:
            if self.convolution_mode == "same":
                t = t * self.stride
            else:
                t = (t - 1) * self.stride + self.kernel
        return InputType.recurrent(self.n_out, t)

    def has_params(self):
        return True


@dataclasses.dataclass(frozen=True)
class SpaceToDepthLayer(LayerConf):
    """conf/layers/SpaceToDepthLayer.java: (N,H,W,C) -> (N,H/b,W/b,C*b*b)
    — the YOLOv2 passthrough/reorg block."""

    block_size: int = 2

    def output_type(self, itype):
        b = self.block_size
        return InputType.convolutional(itype.height // b, itype.width // b,
                                       itype.channels * b * b)


@dataclasses.dataclass(frozen=True)
class SameDiffLayer(LayerConf):
    """conf/layers/samediff/SameDiffLayer.java: a user-defined SameDiff
    block inside a MultiLayerNetwork/ComputationGraph stack.

    ``define(sd, x, params) -> SDVariable`` builds the block's op graph
    from an input SDVariable and a dict of parameter SDVariables (declared
    via ``param_shapes``); the outer network differentiates through it like
    any native layer. NOTE: holds a callable — JSON round-trip is not
    supported for this layer (the reference serializes the subclass by
    classname, which has no analog for ad-hoc Python callables)."""

    define: Any = None
    param_shapes: Any = None  # dict name -> shape tuple
    n_out: int = 0

    def output_type(self, itype):
        if self.n_out:
            if itype.kind == "recurrent":
                return InputType.recurrent(self.n_out, itype.timesteps)
            return InputType.feed_forward(self.n_out)
        return itype

    def has_params(self):
        return bool(self.param_shapes)


@dataclasses.dataclass(frozen=True)
class ResizeLayer(LayerConf):
    """Spatial resize to a fixed (height, width) — the Keras Resizing
    preprocessing surface over the registry resize ops."""

    height: int = 0
    width: int = 0
    method: str = "bilinear"  # bilinear | nearest | bicubic

    def output_type(self, itype):
        return InputType.convolutional(self.height, self.width,
                                       itype.channels)


@dataclasses.dataclass(frozen=True)
class CenterCropLayer(LayerConf):
    """Center crop to (height, width) — Keras CenterCrop parity."""

    height: int = 0
    width: int = 0

    def output_type(self, itype):
        return InputType.convolutional(self.height, self.width,
                                       itype.channels)

LAYER_TYPES = {
    c.__name__: c
    for c in [
        CategoryEncodingLayer,
        DiscretizationLayer,
        EinsumDenseLayer,
        DuelingQLayer,
        MoELayer,
        FusedBottleneck,
        ResizeLayer,
        CenterCropLayer,
        SameDiffLayer,
        SpaceToDepthLayer,
        Deconvolution1D,
        SeparableConvolution1D,
        DotAttentionLayer,
        PermuteLayer,
        ReshapeLayer,
        LayerNormalization,
        GroupNormalization,
        RescaleLayer,
        UnitNormLayer,
        ConvLSTM2D,
        DenseLayer,
        OutputLayer,
        LossLayer,
        EmbeddingLayer,
        EmbeddingSequenceLayer,
        ConvolutionLayer,
        Deconvolution2D,
        DepthwiseConvolution2D,
        SeparableConvolution2D,
        SubsamplingLayer,
        Upsampling2D,
        GlobalPoolingLayer,
        BatchNormalization,
        LocalResponseNormalization,
        ActivationLayer,
        DropoutLayer,
        LSTM,
        GravesLSTM,
        GRU,
        SimpleRnn,
        Bidirectional,
        RnnOutputLayer,
        LastTimeStep,
        SelfAttentionLayer,
        AttentionVertex,
        LearnedSelfAttentionLayer,
        RecurrentAttentionLayer,
        Convolution1D,
        Convolution3D,
        Subsampling3DLayer,
        LocallyConnected2D,
        LocallyConnected1D,
        PReLULayer,
        VariationalAutoencoder,
        ZeroPadding1DLayer,
        ZeroPaddingLayer,
        ZeroPadding3DLayer,
        Cropping1D,
        Cropping2D,
        Cropping3D,
        Upsampling1D,
        Upsampling3D,
        Subsampling1DLayer,
        Deconvolution3D,
        CnnLossLayer,
        RnnLossLayer,
        MaskLayer,
        MaskZeroLayer,
        RepeatVector,
        ElementWiseMultiplicationLayer,
        FrozenLayerWithBackprop,
        CenterLossOutputLayer,
        Yolo2OutputLayer,
        PrimaryCapsules,
        CapsuleLayer,
        CapsuleStrengthLayer,
    ]
}


# ---------------------------------------------------------------------------
# Network-level configuration + builder
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MultiLayerConfiguration:
    """MultiLayerConfiguration.java analog: ordered layers + global defaults.

    ``input_type`` drives build-time shape inference (setInputType analog):
    n_in fields left at 0 are filled in, and preprocessors are auto-inserted
    exactly where the reference's InputType logic would put them.
    """

    layers: List[LayerConf] = dataclasses.field(default_factory=list)
    preprocessors: Dict[int, InputPreProcessor] = dataclasses.field(default_factory=dict)
    input_type: Optional[InputType] = None
    seed: int = 0
    updater: Any = dataclasses.field(default_factory=Adam)
    activation: str = "identity"
    weight_init: str = "xavier"
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    dtype: str = "float32"
    gradient_normalization: Optional[str] = None  # None|clip_l2_per_layer|clip_value|clip_l2_global
    gradient_normalization_threshold: float = 1.0
    tbptt_fwd_length: int = -1
    tbptt_back_length: int = -1
    backprop_type: str = "standard"  # standard | tbptt

    # ---- JSON round trip --------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(
            {
                "layers": [l.to_dict() for l in self.layers],
                "preprocessors": {str(k): v.to_dict() for k, v in self.preprocessors.items()},
                "input_type": self.input_type.to_dict() if self.input_type else None,
                "seed": self.seed,
                "updater": {"__updater__": get_updater(self.updater).to_dict()},
                "activation": self.activation,
                "weight_init": self.weight_init,
                "l1": self.l1,
                "l2": self.l2,
                "weight_decay": self.weight_decay,
                "dtype": self.dtype,
                "gradient_normalization": self.gradient_normalization,
                "gradient_normalization_threshold": self.gradient_normalization_threshold,
                "tbptt_fwd_length": self.tbptt_fwd_length,
                "tbptt_back_length": self.tbptt_back_length,
                "backprop_type": self.backprop_type,
            },
            indent=2,
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        conf = MultiLayerConfiguration(
            layers=[LayerConf.from_dict(l) for l in d["layers"]],
            preprocessors={
                int(k): InputPreProcessor.from_dict(v)
                for k, v in d.get("preprocessors", {}).items()
            },
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            seed=d.get("seed", 0),
            updater=Updater.from_dict(d["updater"]["__updater__"]),
            activation=d.get("activation", "identity"),
            weight_init=d.get("weight_init", "xavier"),
            l1=d.get("l1", 0.0),
            l2=d.get("l2", 0.0),
            weight_decay=d.get("weight_decay", 0.0),
            dtype=d.get("dtype", "float32"),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get("gradient_normalization_threshold", 1.0),
            tbptt_fwd_length=d.get("tbptt_fwd_length", -1),
            tbptt_back_length=d.get("tbptt_back_length", -1),
            backprop_type=d.get("backprop_type", "standard"),
        )
        return conf

    # ---- defaults resolution ---------------------------------------------
    def layer_activation(self, lc: LayerConf) -> str:
        return lc.activation if lc.activation is not None else self.activation

    def layer_weight_init(self, lc: LayerConf) -> str:
        return lc.weight_init if lc.weight_init is not None else self.weight_init

    def layer_updater(self, lc: LayerConf) -> Updater:
        return get_updater(lc.updater) if lc.updater is not None else get_updater(self.updater)

    def layer_l1(self, lc: LayerConf) -> float:
        return lc.l1 if lc.l1 is not None else self.l1

    def layer_l2(self, lc: LayerConf) -> float:
        return lc.l2 if lc.l2 is not None else self.l2

    def layer_weight_decay(self, lc: LayerConf) -> float:
        return lc.weight_decay if lc.weight_decay is not None else self.weight_decay


class NeuralNetConfigurationBuilder:
    """NeuralNetConfiguration.Builder + ListBuilder in one fluent object.

    Mirrors the reference usage:
        conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-3))
                .list()
                .layer(ConvolutionLayer(...)).layer(...)
                .set_input_type(InputType.convolutional_flat(28, 28, 1))
                .build())
    """

    def __init__(self) -> None:
        self._conf = MultiLayerConfiguration()

    def seed(self, s: int):
        self._conf.seed = s
        return self

    def updater(self, u):
        self._conf.updater = u
        return self

    def activation(self, a: str):
        self._conf.activation = a
        return self

    def weight_init(self, w: str):
        self._conf.weight_init = w
        return self

    def l1(self, v: float):
        self._conf.l1 = v
        return self

    def l2(self, v: float):
        self._conf.l2 = v
        return self

    def weight_decay(self, v: float):
        self._conf.weight_decay = v
        return self

    def dtype(self, d: str):
        self._conf.dtype = d
        return self

    def gradient_normalization(self, kind: str, threshold: float = 1.0):
        self._conf.gradient_normalization = kind
        self._conf.gradient_normalization_threshold = threshold
        return self

    def tbptt(self, fwd_length: int, back_length: Optional[int] = None):
        self._conf.backprop_type = "tbptt"
        self._conf.tbptt_fwd_length = fwd_length
        self._conf.tbptt_back_length = back_length or fwd_length
        return self

    def list(self):
        return self

    def layer(self, lc: LayerConf):
        self._conf.layers.append(lc)
        return self

    def input_pre_processor(self, idx: int, p: InputPreProcessor):
        self._conf.preprocessors[idx] = p
        return self

    def set_input_type(self, itype: InputType):
        self._conf.input_type = itype
        return self

    def build(self) -> MultiLayerConfiguration:
        conf = self._conf
        if conf.input_type is not None:
            _infer_shapes(conf)
        return conf


def builder() -> NeuralNetConfigurationBuilder:
    return NeuralNetConfigurationBuilder()


def _infer_shapes(conf: MultiLayerConfiguration) -> None:
    """setInputType analog: fill n_in=0 fields, auto-insert preprocessors."""
    itype = conf.input_type
    new_layers: List[LayerConf] = []
    for i, lc in enumerate(conf.layers):
        itype, lc = _adapt(conf, i, itype, lc)
        new_layers.append(lc)
        itype = lc.output_type(itype)
    conf.layers = new_layers


def _adapt(conf, i, itype, lc) -> Tuple[InputType, LayerConf]:
    """Insert preprocessors & fill n_in for one layer (InputType.getPreProcessorForInputType)."""
    needs_ff = isinstance(lc, (DenseLayer, OutputLayer, EmbeddingLayer))
    is_conv = isinstance(lc, (ConvolutionLayer, SubsamplingLayer, Upsampling2D, LocalResponseNormalization))
    if i not in conf.preprocessors:
        if itype.kind == "convolutionalflat" and is_conv:
            conf.preprocessors[i] = FeedForwardToCnnPreProcessor(
                itype.height, itype.width, itype.channels
            )
            itype = InputType.convolutional(itype.height, itype.width, itype.channels)
        elif itype.kind == "convolutional" and needs_ff:
            conf.preprocessors[i] = CnnToFeedForwardPreProcessor(
                itype.height, itype.width, itype.channels
            )
            itype = InputType.feed_forward(itype.flat_size())
        elif itype.kind == "convolutional3d" and needs_ff:
            conf.preprocessors[i] = Cnn3DToFeedForwardPreProcessor(
                itype.depth, itype.height, itype.width, itype.channels
            )
            itype = InputType.feed_forward(itype.flat_size())
        elif itype.kind == "convolutionalflat" and needs_ff:
            itype = InputType.feed_forward(itype.size)
    else:
        p = conf.preprocessors[i]
        if isinstance(p, FeedForwardToCnnPreProcessor):
            itype = InputType.convolutional(p.height, p.width, p.channels)
        elif isinstance(p, CnnToFeedForwardPreProcessor):
            itype = InputType.feed_forward(p.height * p.width * p.channels)

    # wrapper layers: infer the INNER config's n_in, then rebuild the wrapper
    if isinstance(lc, (Bidirectional, LastTimeStep)):
        inner = lc.inner()
        if getattr(inner, "n_in", 1) == 0:
            size = itype.size if itype.kind == "recurrent" else itype.flat_size()
            inner = dataclasses.replace(inner, n_in=size)
            lc = dataclasses.replace(lc, fwd=inner.to_dict())
        return itype, lc

    # fill n_in / n_out where inferable
    updates: Dict[str, Any] = {}
    if hasattr(lc, "n_in") and getattr(lc, "n_in") == 0:
        if itype.kind in ("feedforward", "convolutionalflat"):
            updates["n_in"] = itype.flat_size()
        elif itype.kind == "recurrent":
            updates["n_in"] = itype.size
        elif itype.kind in ("convolutional", "convolutional3d"):
            updates["n_in"] = itype.channels
    if isinstance(lc, (BatchNormalization, LayerNormalization,
                       GroupNormalization)) and lc.n_out == 0:
        # all three normalize the trailing (feature/channel) axis
        updates["n_out"] = itype.channels \
            if itype.kind in ("convolutional", "convolutional3d") \
            else (itype.size if itype.kind == "recurrent"
                  else itype.flat_size())
    if isinstance(lc, LocallyConnected2D) and tuple(lc.input_size) == (0, 0):
        updates["input_size"] = (itype.height, itype.width)
    if isinstance(lc, LocallyConnected1D) and lc.input_size == 0:
        if not itype.timesteps or itype.timesteps < 0:
            raise ValueError(
                "LocallyConnected1D needs a fixed sequence length — set "
                "input_size or use InputType.recurrent(size, timesteps)")
        updates["input_size"] = itype.timesteps
    if updates:
        lc = dataclasses.replace(lc, **updates)
    return itype, lc
