"""NN layer — the DL4J-proper role: configs, layers, networks, training.

Reference parity: deeplearning4j-nn (SURVEY §3.3). Public names mirror the
reference API surface (NeuralNetConfiguration builder, MultiLayerNetwork,
layer config classes, updaters, listeners, ModelSerializer).
"""

from deeplearning4j_tpu.nn.conf import (
    InputType,
    builder,
    MultiLayerConfiguration,
    NeuralNetConfigurationBuilder,
    DenseLayer,
    OutputLayer,
    LossLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
    ConvolutionLayer,
    Deconvolution2D,
    DepthwiseConvolution2D,
    SeparableConvolution2D,
    SubsamplingLayer,
    Upsampling2D,
    GlobalPoolingLayer,
    BatchNormalization,
    LocalResponseNormalization,
    ActivationLayer,
    DropoutLayer,
    LSTM,
    GravesLSTM,
    SimpleRnn,
    Bidirectional,
    RnnOutputLayer,
    LastTimeStep,
    SelfAttentionLayer,
    AttentionVertex,
    LearnedSelfAttentionLayer,
    RecurrentAttentionLayer,
    Convolution1D,
    Convolution3D,
    Subsampling3DLayer,
    LocallyConnected2D,
    LocallyConnected1D,
    PReLULayer,
    VariationalAutoencoder,
    dl4j_drop_out,
)
from deeplearning4j_tpu.nn.updater import (
    Sgd, Adam, AdaMax, Nadam, AmsGrad, AdaGrad, AdaDelta, RmsProp, Nesterovs,
    NoOp, Frozen,
    Schedule, StepSchedule, ExponentialSchedule, InverseSchedule, PolySchedule,
    SigmoidSchedule, CycleSchedule, MapSchedule, get_updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.listeners import (
    TrainingListener, ScoreIterationListener, PerformanceListener,
    CollectScoresIterationListener, EvaluativeListener, CheckpointListener,
    TimeIterationListener,
)
from deeplearning4j_tpu.nn.serde import save_model, restore_model, restore_normalizer
