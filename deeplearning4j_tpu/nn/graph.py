"""ComputationGraph — DAG networks with multiple inputs/outputs.

Reference parity:
  * org/deeplearning4j/nn/graph/ComputationGraph.java (~5k lines) and
    conf/ComputationGraphConfiguration.java (GraphBuilder: addInputs /
    addLayer(name, conf, inputs...) / addVertex / setOutputs).
  * graph/vertex/impl/* — MergeVertex, ElementWiseVertex, SubsetVertex,
    ScaleVertex, ShiftVertex, L2NormalizeVertex, PreprocessorVertex,
    StackVertex, UnstackVertex, ReshapeVertex.

TPU-native realization: same collapse as MultiLayerNetwork — the whole DAG
(forward + losses at all output layers + backward + updaters) traces into one
jitted XLA step. Topological order is fixed at build time (config is static),
so the traced program is a straight-line fused computation.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults, observe

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn.layers import Layer, build_layer, apply_preprocessor
from deeplearning4j_tpu.nn.updater import Updater, get_updater
from deeplearning4j_tpu.nn.listeners import (
    TrainingListener, notify_fit_done, notify_preemption)
from deeplearning4j_tpu.nn.multilayer import (
    _map_weights, _tree_l1_weights, _tree_l2_sq_weights, _sorted_leaves,
    _unflatten_like, apply_layer_updates, aux_losses, reg_penalty,
)
from deeplearning4j_tpu.ops.losses import get_loss
from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator, ListDataSetIterator
from deeplearning4j_tpu.eval.evaluation import Evaluation

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Graph vertices (conf/graph/*Vertex + graph/vertex/impl/*)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GraphVertex:
    """Base non-layer vertex."""

    def apply(self, inputs: List[jax.Array]):
        raise NotImplementedError

    def output_type(self, itypes: List[C.InputType]) -> C.InputType:
        return itypes[0]

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = VERTEX_TYPES[d.pop("@type")]
        for k, v in list(d.items()):
            if isinstance(v, list):
                d[k] = tuple(v)
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """MergeVertex.java: concat along the feature/channel axis."""

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, itypes):
        t0 = itypes[0]
        if t0.kind == "convolutional":
            return C.InputType.convolutional(t0.height, t0.width,
                                             sum(t.channels for t in itypes))
        if t0.kind == "recurrent":
            return C.InputType.recurrent(sum(t.size for t in itypes), t0.timesteps)
        return C.InputType.feed_forward(sum(t.flat_size() for t in itypes))


@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    """ElementWiseVertex.java: Add | Subtract | Product | Average | Max."""

    op: str = "add"

    def apply(self, inputs):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            return inputs[0] - inputs[1]
        if op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "average":
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        if op == "min":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.minimum(out, x)
            return out
        raise ValueError(f"unknown ElementWiseVertex op {self.op}")



@dataclasses.dataclass(frozen=True)
class DotProductVertex(GraphVertex):
    """Keras Dot merge: batched contraction of two inputs along ``axes``
    (an int applied to both sides; negative allowed), optional L2
    normalization first (cosine proximity)."""

    axes: int = -1
    normalize: bool = False

    def apply(self, inputs):
        a, b = inputs
        ax = self.axes
        if self.normalize:
            a = a / jnp.maximum(jnp.linalg.norm(a, axis=ax, keepdims=True),
                                1e-12)
            b = b / jnp.maximum(jnp.linalg.norm(b, axis=ax, keepdims=True),
                                1e-12)
        axa, axb = ax % a.ndim, ax % b.ndim
        out = jax.vmap(lambda u, v: jnp.tensordot(
            u, v, axes=((axa - 1,), (axb - 1,))))(a, b)
        if out.ndim == 1:
            out = out[:, None]  # keras keeps a trailing dim for vector dots
        return out

    def output_type(self, itypes):
        a, b = itypes
        if a.kind == "feedforward" or (a.kind == "recurrent"
                                       and self.axes in (-1, 2)):
            # vector dot -> (N, 1); (N,T,F)x(N,S,F) axes=-1 -> (N,T,S)
            if a.kind == "feedforward":
                return C.InputType.feed_forward(1)
            return C.InputType.recurrent(
                b.timesteps if b.timesteps else -1, a.timesteps)
        raise NotImplementedError(
            f"DotProductVertex shape inference for {a.kind} inputs with "
            f"axes={self.axes}")


@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """SubsetVertex.java: feature-axis slice [from, to] inclusive."""

    from_idx: int = 0
    to_idx: int = 0

    def apply(self, inputs):
        return inputs[0][..., self.from_idx : self.to_idx + 1]

    def output_type(self, itypes):
        n = self.to_idx - self.from_idx + 1
        t = itypes[0]
        if t.kind == "recurrent":
            return C.InputType.recurrent(n, t.timesteps)
        return C.InputType.feed_forward(n)


@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    """ScaleVertex.java: multiply by a constant."""

    scale: float = 1.0

    def apply(self, inputs):
        return inputs[0] * self.scale


@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertex):
    """ShiftVertex.java: add a constant."""

    shift: float = 0.0

    def apply(self, inputs):
        return inputs[0] + self.shift


@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertex):
    """L2NormalizeVertex.java: x / ||x||₂ along the feature axis."""

    eps: float = 1e-8

    def apply(self, inputs):
        x = inputs[0]
        norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + self.eps)
        return x / norm


@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertex):
    """StackVertex.java: stack along batch axis (axis 0)."""

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=0)


@dataclasses.dataclass(frozen=True)
class ReshapeVertex(GraphVertex):
    """ReshapeVertex.java."""

    shape: Tuple[int, ...] = ()

    def apply(self, inputs):
        return jnp.reshape(inputs[0], self.shape)


@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertex):
    """UnstackVertex.java: inverse of StackVertex — slice subrange
    [from·size : (from+1)·size] of the batch axis (stack_size = number of
    stacked inputs the producing StackVertex concatenated)."""

    from_idx: int = 0
    stack_size: int = 1

    def apply(self, inputs):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_idx * n:(self.from_idx + 1) * n]


@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """DuplicateToTimeSeriesVertex.java: broadcast a (N, F) feed-forward
    input across the timesteps of a reference recurrent input — inputs are
    (value, time_reference)."""

    def apply(self, inputs):
        val, ref = inputs
        t = ref.shape[1]
        return jnp.broadcast_to(val[:, None, :], (val.shape[0], t, val.shape[1]))

    def output_type(self, itypes):
        return C.InputType.recurrent(itypes[0].flat_size(),
                                     itypes[1].timesteps)


@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """LastTimeStepVertex.java: (N, T, F) → (N, F) last step. NOTE: vertices
    do not receive masks in this engine; for masked sequences use the
    LastTimeStep LAYER wrapper (conf.LastTimeStep), which does."""

    def apply(self, inputs):
        return inputs[0][:, -1]

    def output_type(self, itypes):
        return C.InputType.feed_forward(itypes[0].size)


@dataclasses.dataclass(frozen=True)
class FlattenVertex(GraphVertex):
    """Batch-preserving flatten (PreprocessorVertex(CnnToFeedForward)
    analog, but feature-major order preserved — used by the Keras
    functional import where activations are already NHWC like Keras's)."""

    def apply(self, inputs):
        x = inputs[0]
        return jnp.reshape(x, (x.shape[0], -1))

    def output_type(self, itypes):
        return C.InputType.feed_forward(itypes[0].flat_size())


VERTEX_TYPES = {
    c.__name__: c
    for c in [MergeVertex, ElementWiseVertex, SubsetVertex, ScaleVertex,
              ShiftVertex, L2NormalizeVertex, StackVertex, ReshapeVertex,
              FlattenVertex, UnstackVertex, DuplicateToTimeSeriesVertex,
              LastTimeStepVertex, DotProductVertex]
}


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _GraphNode:
    name: str
    kind: str  # 'layer' | 'vertex'
    layer: Optional[C.LayerConf] = None
    vertex: Optional[GraphVertex] = None
    inputs: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ComputationGraphConfiguration:
    """ComputationGraphConfiguration.java analog."""

    network_inputs: List[str] = dataclasses.field(default_factory=list)
    network_outputs: List[str] = dataclasses.field(default_factory=list)
    nodes: List[_GraphNode] = dataclasses.field(default_factory=list)
    input_types: Dict[str, C.InputType] = dataclasses.field(default_factory=dict)
    seed: int = 0
    updater: Any = None
    activation: str = "identity"
    weight_init: str = "xavier"
    l1: float = 0.0
    l2: float = 0.0
    weight_decay: float = 0.0
    dtype: str = "float32"
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    tbptt_fwd_length: int = -1
    tbptt_back_length: int = -1
    backprop_type: str = "standard"

    # reuse MultiLayerConfiguration's per-layer default resolution
    layer_activation = C.MultiLayerConfiguration.layer_activation
    layer_weight_init = C.MultiLayerConfiguration.layer_weight_init
    layer_updater = C.MultiLayerConfiguration.layer_updater
    layer_l1 = C.MultiLayerConfiguration.layer_l1
    layer_l2 = C.MultiLayerConfiguration.layer_l2
    layer_weight_decay = C.MultiLayerConfiguration.layer_weight_decay

    def __post_init__(self):
        if self.updater is None:
            from deeplearning4j_tpu.nn.updater import Adam

            self.updater = Adam()

    def to_json(self) -> str:
        return json.dumps({
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "nodes": [
                {"name": n.name, "kind": n.kind,
                 "layer": n.layer.to_dict() if n.layer else None,
                 "vertex": n.vertex.to_dict() if n.vertex else None,
                 "inputs": n.inputs}
                for n in self.nodes
            ],
            "input_types": {k: v.to_dict() for k, v in self.input_types.items()},
            "seed": self.seed,
            "updater": {"__updater__": get_updater(self.updater).to_dict()},
            "activation": self.activation,
            "weight_init": self.weight_init,
            "l1": self.l1, "l2": self.l2, "weight_decay": self.weight_decay,
            "dtype": self.dtype,
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold": self.gradient_normalization_threshold,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)
        conf = ComputationGraphConfiguration(
            network_inputs=d["network_inputs"],
            network_outputs=d["network_outputs"],
            nodes=[
                _GraphNode(
                    name=nd["name"], kind=nd["kind"],
                    layer=C.LayerConf.from_dict(nd["layer"]) if nd["layer"] else None,
                    vertex=GraphVertex.from_dict(nd["vertex"]) if nd["vertex"] else None,
                    inputs=list(nd["inputs"]))
                for nd in d["nodes"]
            ],
            input_types={k: C.InputType.from_dict(v) for k, v in d["input_types"].items()},
            seed=d.get("seed", 0),
            updater=Updater.from_dict(d["updater"]["__updater__"]),
            activation=d.get("activation", "identity"),
            weight_init=d.get("weight_init", "xavier"),
            l1=d.get("l1", 0.0), l2=d.get("l2", 0.0),
            weight_decay=d.get("weight_decay", 0.0),
            dtype=d.get("dtype", "float32"),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get("gradient_normalization_threshold", 1.0),
        )
        return conf


class GraphBuilder:
    """ComputationGraphConfiguration.GraphBuilder analog (fluent)."""

    def __init__(self) -> None:
        self._conf = ComputationGraphConfiguration()

    def seed(self, s: int):
        self._conf.seed = s
        return self

    def updater(self, u):
        self._conf.updater = u
        return self

    def activation(self, a: str):
        self._conf.activation = a
        return self

    def weight_init(self, w: str):
        self._conf.weight_init = w
        return self

    def l1(self, v: float):
        self._conf.l1 = v
        return self

    def l2(self, v: float):
        self._conf.l2 = v
        return self

    def weight_decay(self, v: float):
        self._conf.weight_decay = v
        return self

    def dtype(self, d: str):
        self._conf.dtype = d
        return self

    def gradient_normalization(self, kind: str, threshold: float = 1.0):
        self._conf.gradient_normalization = kind
        self._conf.gradient_normalization_threshold = threshold
        return self

    def graph_builder(self):
        return self

    def add_inputs(self, *names: str):
        self._conf.network_inputs.extend(names)
        return self

    def set_input_types(self, **types: C.InputType):
        self._conf.input_types.update(types)
        return self

    def add_layer(self, name: str, layer: C.LayerConf, *inputs: str):
        self._conf.nodes.append(_GraphNode(name=name, kind="layer", layer=layer,
                                           inputs=list(inputs)))
        return self

    def add_vertex(self, name: str, vertex, *inputs: str):
        # parameterized vertices (reference AttentionVertex et al. extend
        # SameDiffVertex WITH params) are LayerConf instances here — route
        # them to the layer path, which owns params/state
        if isinstance(vertex, C.LayerConf):
            return self.add_layer(name, vertex, *inputs)
        self._conf.nodes.append(_GraphNode(name=name, kind="vertex", vertex=vertex,
                                           inputs=list(inputs)))
        return self

    def set_outputs(self, *names: str):
        self._conf.network_outputs.extend(names)
        return self

    def build(self) -> ComputationGraphConfiguration:
        return self._conf


def graph_builder() -> GraphBuilder:
    return GraphBuilder()


# ---------------------------------------------------------------------------
# Runtime
# ---------------------------------------------------------------------------


class ComputationGraph:
    """DAG network runtime (ComputationGraph.java analog)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self._order = self._toposort()
        # shape inference over the DAG (ComputationGraphConfiguration
        # addPreProcessors/getLayerActivationTypes analog)
        self._itypes: Dict[str, C.InputType] = {}
        self.layers: Dict[str, Layer] = {}
        self._net_conf_view = self._as_mlc()
        for name in conf.network_inputs:
            it = conf.input_types.get(name, C.InputType.feed_forward(0))
            if it.kind == "convolutionalflat":
                it = C.InputType.convolutional(it.height, it.width, it.channels)
            self._itypes[name] = it
        for node in self._order:
            in_types = [self._itypes[i] for i in node.inputs]
            if node.kind == "vertex":
                self._itypes[node.name] = node.vertex.output_type(in_types)
            else:
                itype, lc = self._infer_layer(node, in_types[0])
                node.layer = lc
                layer = build_layer(self._net_conf_view, lc, itype)
                self.layers[node.name] = layer
                self._itypes[node.name] = layer.otype
        self.params: Optional[Dict[str, Dict[str, Any]]] = None
        self.net_state: Optional[Dict[str, Dict[str, Any]]] = None
        self.opt_state: Optional[Dict[str, Any]] = None
        self.iteration_count = 0
        self.epoch_count = 0
        # completed batches in the CURRENT epoch — the data cursor exact
        # resume replays from (checkpointed; docs/ROBUSTNESS.md)
        self.batch_in_epoch = 0
        self.listeners: List[TrainingListener] = []
        self.last_batch_size = 0
        self._key = jax.random.key(conf.seed)
        self._jit_cache: Dict[Any, Any] = {}
        self._output_layers = [
            n for n in conf.network_outputs
            if getattr(self._node(n).layer, "loss", None) is not None
        ]

    def _as_mlc(self) -> C.MultiLayerConfiguration:
        c = self.conf
        return C.MultiLayerConfiguration(
            seed=c.seed, updater=c.updater, activation=c.activation,
            weight_init=c.weight_init, l1=c.l1, l2=c.l2,
            weight_decay=c.weight_decay, dtype=c.dtype,
            gradient_normalization=c.gradient_normalization,
            gradient_normalization_threshold=c.gradient_normalization_threshold,
        )

    def _node(self, name: str) -> _GraphNode:
        for n in self.conf.nodes:
            if n.name == name:
                return n
        raise KeyError(name)

    def _toposort(self) -> List[_GraphNode]:
        done = set(self.conf.network_inputs)
        remaining = list(self.conf.nodes)
        order = []
        while remaining:
            progress = False
            for n in list(remaining):
                if all(i in done for i in n.inputs):
                    order.append(n)
                    done.add(n.name)
                    remaining.remove(n)
                    progress = True
            if not progress:
                cycle = [n.name for n in remaining]
                raise ValueError(f"graph has a cycle or missing inputs: {cycle}")
        return order

    def _infer_layer(self, node: _GraphNode, itype: C.InputType):
        """Fill n_in and adapt conv->ff shapes, per-node (the reference's
        auto preprocessor insertion)."""
        lc = node.layer
        needs_ff = isinstance(lc, (C.DenseLayer, C.OutputLayer, C.EmbeddingLayer))
        if itype.kind in ("convolutional", "convolutional3d") and needs_ff:
            itype = C.InputType.feed_forward(itype.flat_size())
            node.kind = "layer"  # unchanged; flattening applied at runtime
            setattr(node, "_flatten_input", True)
        fake = C.MultiLayerConfiguration(layers=[lc], input_type=itype)
        itype2, lc2 = C._adapt(fake, 0, itype, lc)
        return itype2, lc2

    # ------------------------------------------------------------------ init
    def init(self, params=None) -> "ComputationGraph":
        from deeplearning4j_tpu.nn import dtype as DT

        with DT.precision_scope(self.conf.dtype):
            if params is not None:
                self.params = params
            else:
                key = jax.random.key(self.conf.seed)
                names = [n.name for n in self._order if n.kind == "layer"]
                keys = jax.random.split(key, max(len(names), 1))
                self.params = {
                    name: self.layers[name].init(k) for name, k in zip(names, keys)
                }
            self.net_state = {name: l.init_state() for name, l in self.layers.items()}
            self.opt_state = {}
            for name, l in self.layers.items():
                upd = self.conf.layer_updater(l.lc)
                self.opt_state[name] = jax.tree.map(upd.init_state, self.params[name])
        return self

    def set_listeners(self, *ls: TrainingListener) -> None:
        self.listeners = list(ls)

    # --------------------------------------------------------------- forward
    def _forward(self, params, net_state, inputs: Dict[str, Any], masks,
                 *, train: bool, rng, rnn_states: Optional[Dict[str, Any]] = None):
        """When ``rnn_states`` is given (node-name → carried RNN state, None
        for non-recurrent nodes) returns (acts, new_state, new_rnn_states) —
        the ComputationGraph rnnTimeStep / tBPTT state-threading path."""
        from deeplearning4j_tpu.nn import dtype as DT

        with DT.precision_scope(self.conf.dtype):
            if DT.needs_cast(self.conf.dtype):
                # mixed policy: bf16 compute against f32 master params — ONE cast
                # chokepoint so grads flow back to the f32 masters
                cd = DT.compute_dtype(self.conf.dtype)
                params = DT.cast_floats(params, cd)
                inputs = DT.cast_floats(inputs, cd)
                if rnn_states is not None:
                    rnn_states = DT.cast_floats(rnn_states, cd)
            acts: Dict[str, Any] = dict(inputs)
            act_masks: Dict[str, Any] = dict(masks or {})
            new_state: Dict[str, Any] = {}
            new_rnn: Optional[Dict[str, Any]] = (
                {} if rnn_states is not None else None)
            layer_names = [n.name for n in self._order if n.kind == "layer"]
            rngs = (jax.random.split(rng, max(len(layer_names), 1))
                    if rng is not None else [None] * len(layer_names))
            rng_map = dict(zip(layer_names, rngs))
            for node in self._order:
                xs = [acts[i] for i in node.inputs]
                if node.kind == "vertex":
                    acts[node.name] = node.vertex.apply(xs)
                    ms = [act_masks.get(i) for i in node.inputs]
                    act_masks[node.name] = next((m for m in ms if m is not None), None)
                else:
                    layer = self.layers[node.name]
                    mask = act_masks.get(node.inputs[0])
                    if (rnn_states is not None
                            and hasattr(layer, "apply_with_state")):
                        x0 = layer._maybe_dropout(xs[0], train=train,
                                                  rng=rng_map[node.name])
                        y, last = layer.apply_with_state(
                            params[node.name], x0, mask=mask,
                            initial=rnn_states.get(node.name))
                        acts[node.name] = y
                        act_masks[node.name] = mask
                        new_state[node.name] = net_state[node.name]
                        new_rnn[node.name] = last
                        continue
                    if new_rnn is not None:
                        new_rnn[node.name] = None
                    if hasattr(layer, "apply_multi"):
                        # parameterized multi-input node (AttentionVertex
                        # role): gets ALL wired inputs; the mask that
                        # matters is the KEYS input's (the last wired
                        # input) — it gates which positions are attended
                        kmask = act_masks.get(node.inputs[-1]) \
                            if len(node.inputs) > 1 else mask
                        y, st, m2 = layer.apply_multi(
                            params[node.name], xs, net_state[node.name],
                            train=train, rng=rng_map[node.name], mask=kmask)
                    else:
                        x = xs[0]
                        if getattr(node, "_flatten_input", False):
                            if x.ndim == 4:  # NHWC → reference C-major flat
                                x = x.transpose(0, 3, 1, 2).reshape(x.shape[0], -1)
                            elif x.ndim == 5:  # NDHWC → C-major flat
                                x = x.transpose(0, 4, 1, 2, 3).reshape(x.shape[0], -1)
                        y, st, m2 = layer.apply(
                            params[node.name], x, net_state[node.name],
                            train=train, rng=rng_map[node.name], mask=mask)
                    acts[node.name] = y
                    act_masks[node.name] = m2
                    new_state[node.name] = st
            if DT.needs_cast(self.conf.dtype):
                for o in self.conf.network_outputs:  # loss/eval math stays f32
                    acts[o] = DT.cast_floats(acts[o], jnp.float32)
        if new_rnn is not None:
            return acts, new_state, new_rnn
        return acts, new_state

    def output(self, *inputs, masks=None) -> List[np.ndarray]:
        """graph.output(inputs...) — list of output-node activations."""
        feed = {n: jnp.asarray(x) for n, x in zip(self.conf.network_inputs, inputs)}
        fn = self._jit_cache.get("output")
        if fn is None:
            @jax.jit
            def fn(params, net_state, feed, masks):
                acts, _ = self._forward(params, net_state, feed, masks,
                                        train=False, rng=None)
                return [acts[o] for o in self.conf.network_outputs]

            self._jit_cache["output"] = fn
        outs = fn(self.params, self.net_state, feed,
                  None if masks is None else {k: jnp.asarray(v) for k, v in masks.items()})
        return [np.asarray(o) for o in outs]

    def output_single(self, x, masks=None) -> np.ndarray:
        return self.output(x, masks=masks)[0]

    # ------------------------------------------------------------- train step
    def _losses(self, acts, labels: Dict[str, Any], lmasks):
        total = jnp.zeros(())
        for name in self._output_layers:
            node = self._node(name)
            if hasattr(node.layer, "loss_fn"):
                loss_fn = node.layer.loss_fn()  # conf-bound hyperparams (YOLO2)
            else:
                loss_fn = get_loss(node.layer.loss)
            lm = None if lmasks is None else lmasks.get(name)
            total = total + loss_fn(acts[name], labels[name], lm)
        return total

    def _apply_updates(self, params, grads, opt_state, step):
        """Shared update tail (regularization-into-grad, updater math) for
        the standard and tBPTT step functions."""
        conf = self.conf
        layer_names = [n.name for n in self._order if n.kind == "layer"]
        updaters = {name: conf.layer_updater(self.layers[name].lc)
                    for name in layer_names}
        updated = apply_layer_updates(
            conf,
            ((params[n], grads[n], opt_state[n], updaters[n],
              self.layers[n].lc) for n in layer_names),
            step, self._normalize_gradient)
        new_params = {n: p for n, (p, _) in zip(layer_names, updated)}
        new_opt = {n: s for n, (_, s) in zip(layer_names, updated)}
        return new_params, new_opt

    def _reg_penalty(self, params):
        layer_names = [n.name for n in self._order if n.kind == "layer"]
        return reg_penalty(
            self.conf, ((params[n], self.layers[n].lc) for n in layer_names))

    def _make_train_step(self):
        def train_step(params, opt_state, net_state, step, key, feeds, labels,
                       fmasks, lmasks):
            def loss_of(p):
                acts, new_state = self._forward(p, net_state, feeds, fmasks,
                                                train=True, rng=key)
                return (self._losses(acts, labels, lmasks)
                        + aux_losses(new_state), new_state)

            (loss, new_net_state), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(params, grads, opt_state, step)
            return (new_params, new_opt, new_net_state,
                    loss + self._reg_penalty(params))

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    _normalize_gradient = None  # assigned below (shared with MultiLayerNetwork)

    # ------------------------------------------------------ stateful RNN API
    def rnn_time_step(self, *inputs, masks=None):
        """Stateful streaming inference (ComputationGraph.rnnTimeStep):
        recurrent node states carry across calls in ``self._rnn_states``.
        Inputs: (N, T, F) per network input — or (N, F) for one step.
        Returns the network outputs (list, or the single array)."""
        squeeze = False
        feeds = {}
        for name, x in zip(self.conf.network_inputs, inputs):
            x = np.asarray(x)
            if x.ndim == 2:
                x = x[:, None, :]
                squeeze = True
            feeds[name] = jnp.asarray(x)
        batch = next(iter(feeds.values())).shape[0]
        if getattr(self, "_rnn_states", None) is None:
            self._rnn_states = self._zero_rnn_states(batch)
        fn = self._jit_cache.get("rnn_time_step")
        if fn is None:
            @jax.jit
            def fn(params, net_state, rnn_states, feeds, masks):
                acts, _, new_rnn = self._forward(
                    params, net_state, feeds, masks, train=False, rng=None,
                    rnn_states=rnn_states)
                return [acts[o] for o in self.conf.network_outputs], new_rnn

            self._jit_cache["rnn_time_step"] = fn
        outs, self._rnn_states = fn(self.params, self.net_state,
                                    self._rnn_states, feeds, masks)
        outs = [np.asarray(o) for o in outs]
        if squeeze:
            outs = [o[:, -1] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self) -> None:
        self._rnn_states = None

    def _zero_rnn_states(self, batch: int, dtype=np.float32):
        from deeplearning4j_tpu.nn.layers import BidirectionalImpl

        states: Dict[str, Any] = {}
        for name, layer in self.layers.items():
            if isinstance(layer, BidirectionalImpl):
                raise ValueError(
                    "stateful RNN state (rnn_time_step / tBPTT) is not "
                    "supported with Bidirectional layers")
            states[name] = (layer.zero_state(batch, dtype)
                            if hasattr(layer, "zero_state") else None)
        return states

    def _make_train_step_tbptt(self):
        """Truncated-BPTT step (doTruncatedBPTT analog): RNN state enters as
        an input and leaves as an output — gradients truncate at the segment
        boundary (see MultiLayerNetwork._make_train_step_tbptt)."""
        def train_step(params, opt_state, net_state, rnn_states, step, key,
                       feeds, labels, fmasks, lmasks):
            def loss_of(p):
                acts, new_state, new_rnn = self._forward(
                    p, net_state, feeds, fmasks, train=True, rng=key,
                    rnn_states=rnn_states)
                return self._losses(acts, labels, lmasks), (new_state, new_rnn)

            (loss, (new_net_state, new_rnn)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(params, grads, opt_state, step)
            return (new_params, new_opt, new_net_state, new_rnn,
                    loss + self._reg_penalty(params))

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def fit_tbptt(self, features, labels, masks=None, lmasks=None) -> float:
        """One truncated-BPTT pass over a time-series batch: slices the time
        axis into ``conf.tbptt_fwd_length`` segments, carrying RNN state
        (ComputationGraph.doTruncatedBPTT). Single- or multi-input graphs:
        pass arrays or name-keyed dicts of (N, T, F) features / (N, T, C)
        labels."""
        fwd = self.conf.tbptt_fwd_length
        if fwd <= 0:
            raise ValueError("set tbptt lengths on the configuration first")
        if not isinstance(features, dict):
            features = {self.conf.network_inputs[0]: features}
        if not isinstance(labels, dict):
            labels = {self.conf.network_outputs[0]: labels}
        if masks is not None and not isinstance(masks, dict):
            masks = {self.conf.network_inputs[0]: masks}
        if lmasks is not None and not isinstance(lmasks, dict):
            lmasks = {self.conf.network_outputs[0]: lmasks}
        for k, v in labels.items():
            if np.asarray(v).ndim < 3:
                raise ValueError(
                    "tBPTT requires 3-D time-series labels (N, T, C); got "
                    f"shape {np.shape(v)} for output '{k}'")
        step_fn = self._jit_cache.get("train_step_tbptt")
        if step_fn is None:
            step_fn = self._make_train_step_tbptt()
            self._jit_cache["train_step_tbptt"] = step_fn
        T = next(iter(features.values())).shape[1]
        batch = next(iter(features.values())).shape[0]
        rnn_states = self._zero_rnn_states(batch)
        segments = list(range(0, T, fwd))
        loss = 0.0
        for i, t0 in enumerate(segments):
            t1 = min(t0 + fwd, T)
            seg_f = {k: jnp.asarray(np.asarray(v)[:, t0:t1])
                     for k, v in features.items()}
            seg_y = {k: jnp.asarray(np.asarray(v)[:, t0:t1])
                     for k, v in labels.items()}
            seg_fm = (None if masks is None else
                      {k: jnp.asarray(np.asarray(v)[:, t0:t1])
                       for k, v in masks.items()})
            seg_lm = (None if lmasks is None else
                      {k: jnp.asarray(np.asarray(v)[:, t0:t1])
                       for k, v in lmasks.items()})
            self._key, sub = jax.random.split(self._key)
            (self.params, self.opt_state, self.net_state, rnn_states,
             loss) = step_fn(self.params, self.opt_state, self.net_state,
                             rnn_states,
                             jnp.asarray(self.iteration_count, jnp.int32),
                             sub, seg_f, seg_y, seg_fm, seg_lm)
            self._score = loss
            if i < len(segments) - 1:
                self.iteration_count += 1
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count,
                                   self.epoch_count, loss)
        self.iteration_count += 1
        return float(loss)

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32) -> None:
        """fit over DataSet/iterator. Single-input single-output DataSets map
        features -> first input, labels -> first output (MultiDataSet support:
        pass dicts via fit_multi)."""
        if labels is not None:
            data = ListDataSetIterator(DataSet(data, labels), batch_size=batch_size)
        elif isinstance(data, DataSet):
            data = ListDataSetIterator(data, batch_size=batch_size)
        tbptt = (self.conf.backprop_type == "tbptt"
                 and self.conf.tbptt_fwd_length > 0)
        if tbptt:
            # truncated-BPTT dispatch (doTruncatedBPTT), as in
            # MultiLayerNetwork.fit — NOT silent full-sequence BPTT
            for _ in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self)
                skip = self.batch_in_epoch
                for bi, ds in enumerate(data):
                    if bi < skip:
                        continue
                    faults.maybe_fail("preemption")
                    if faults.preemption_requested():
                        notify_preemption(self, self.listeners)
                        return
                    self.last_batch_size = ds.num_examples()
                    # checkpoint saves must not land mid-batch: a segment
                    # snapshot (params mid-batch, stale data cursor, live
                    # RNN carry the payload does not include) could never
                    # resume exactly. Listeners that declare
                    # ``defers_mid_tbptt`` skip themselves per segment and
                    # get ONE batch-boundary call after the cursor update;
                    # score/perf listeners keep their per-segment firing.
                    self._tbptt_mid_batch = True
                    try:
                        loss = self.fit_tbptt(ds.features, ds.labels,
                                              masks=ds.features_mask,
                                              lmasks=ds.labels_mask)
                    finally:
                        self._tbptt_mid_batch = False
                    self.batch_in_epoch = bi + 1
                    for lst in self.listeners:
                        if getattr(lst, "defers_mid_tbptt", False):
                            lst.iteration_done(self, self.iteration_count,
                                               self.epoch_count, loss)
                self.batch_in_epoch = 0
                self.epoch_count += 1
                for lst in self.listeners:
                    lst.on_epoch_end(self)
            notify_fit_done(self, self.listeners)
            return
        step_fn = self._jit_cache.get("train_step")
        if step_fn is None:
            step_fn = self._make_train_step()
            self._jit_cache["train_step"] = step_fn
        in_name = self.conf.network_inputs[0]
        out_name = self.conf.network_outputs[0]
        _m = observe.metrics()
        _steps_c = _m.counter("dl4j_tpu_train_steps_total", model="graph")
        _ex_c = _m.counter("dl4j_tpu_train_examples_total", model="graph")
        _xfer_c = _m.counter("dl4j_tpu_host_to_device_transfers_total",
                             model="graph")
        _step_h = _m.histogram("dl4j_tpu_train_step_seconds", model="graph")
        for _ in range(epochs):
            for lst in self.listeners:
                lst.on_epoch_start(self)
            t_prev = time.perf_counter()
            n_steps = 0
            # nonzero only when resuming mid-epoch from a checkpoint: the
            # first `skip` batches were already consumed by the killed run
            skip = self.batch_in_epoch
            for bi, ds in enumerate(data):
                if bi < skip:
                    continue
                # preemption (docs/ROBUSTNESS.md): injected fault = HARD
                # kill (supervisor restores+resumes); flag = SOFT SIGTERM
                # path (final snapshot, clean exit)
                faults.maybe_fail("preemption")
                if faults.preemption_requested():
                    notify_preemption(self, self.listeners)
                    return
                self.last_batch_size = ds.num_examples()
                observe.note_jit_signature(
                    step_fn, graph="graph", key="train_step",
                    signature=observe.signature_of(
                        x=ds.features, y=ds.labels, fm=ds.features_mask,
                        lm=ds.labels_mask))
                self._key, sub = jax.random.split(self._key)
                feeds = {in_name: jnp.asarray(ds.features)}
                labs = {out_name: jnp.asarray(ds.labels)}
                fmasks = (None if ds.features_mask is None
                          else {in_name: jnp.asarray(ds.features_mask)})
                lmasks = (None if ds.labels_mask is None
                          else {out_name: jnp.asarray(ds.labels_mask)})
                self.params, self.opt_state, self.net_state, loss = step_fn(
                    self.params, self.opt_state, self.net_state,
                    jnp.asarray(self.iteration_count, jnp.int32), sub,
                    feeds, labs, fmasks, lmasks)
                self._score = loss
                self.iteration_count += 1
                self.batch_in_epoch = bi + 1  # cursor BEFORE listeners save
                now = time.perf_counter()
                _step_h.observe(now - t_prev)
                t_prev = now
                n_steps += 1
                _steps_c.inc()
                _ex_c.inc(ds.num_examples())
                _xfer_c.inc(2 + (fmasks is not None) + (lmasks is not None))
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count, self.epoch_count, loss)
            self.batch_in_epoch = 0
            self.epoch_count += 1
            observe.log_event("train_epoch", model="graph",
                              epoch=self.epoch_count, steps=n_steps)
            for lst in self.listeners:
                lst.on_epoch_end(self)
        notify_fit_done(self, self.listeners)

    def fit_multi(self, inputs, labels) -> float:
        """One training step with multiple inputs/outputs (the
        ComputationGraph.fit(MultiDataSet) role). ``inputs``/``labels``:
        lists aligned with network_inputs/network_outputs, or name dicts.
        Returns the step loss."""
        if not isinstance(inputs, dict):
            inputs = dict(zip(self.conf.network_inputs, inputs))
        if not isinstance(labels, dict):
            labels = dict(zip(self.conf.network_outputs, labels))
        step_fn = self._jit_cache.get("train_step")
        if step_fn is None:
            step_fn = self._make_train_step()
            self._jit_cache["train_step"] = step_fn
        self._key, sub = jax.random.split(self._key)
        feeds = {k: jnp.asarray(v) for k, v in inputs.items()}
        labs = {k: jnp.asarray(v) for k, v in labels.items()}
        self.last_batch_size = next(iter(feeds.values())).shape[0]
        self.params, self.opt_state, self.net_state, loss = step_fn(
            self.params, self.opt_state, self.net_state,
            jnp.asarray(self.iteration_count, jnp.int32), sub,
            feeds, labs, None, None)
        self._score = loss
        self.iteration_count += 1
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration_count, self.epoch_count,
                               loss)
        return float(loss)

    def fit_scanned(self, features, labels, steps: Optional[int] = None) -> np.ndarray:
        """Many fused train steps in ONE XLA call — lax.scan over the train
        step with donated carry (see MultiLayerNetwork.fit_scanned; same two
        modes). ``features``/``labels``: single-input/-output arrays, or
        dicts keyed by input/output name for multi-IO graphs."""
        import functools

        step_fn = self._jit_cache.get("train_step")
        if step_fn is None:
            step_fn = self._make_train_step()
            self._jit_cache["train_step"] = step_fn
        if not isinstance(features, dict):
            features = {self.conf.network_inputs[0]: features}
        if not isinstance(labels, dict):
            labels = {self.conf.network_outputs[0]: labels}
        feeds = {k: jnp.asarray(v) for k, v in features.items()}
        labs = {k: jnp.asarray(v) for k, v in labels.items()}
        per_step_data = steps is None
        n_steps = (int(next(iter(feeds.values())).shape[0]) if per_step_data
                   else int(steps))

        cache_key = ("fit_scanned", per_step_data, n_steps)
        many = self._jit_cache.get(cache_key)
        if many is None:
            @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
            def many(params, opt_state, net_state, start, key, feeds, labs):
                def body(carry, it):
                    p, o, s = carry
                    if per_step_data:
                        i, f, y = it
                    else:
                        i, f, y = it, feeds, labs
                    p, o, s, loss = step_fn(p, o, s, i, jax.random.fold_in(key, i),
                                            f, y, None, None)
                    return (p, o, s), loss
                idx = start + jnp.arange(n_steps, dtype=jnp.int32)
                sc = (idx, feeds, labs) if per_step_data else idx
                (p, o, s), losses = jax.lax.scan(body, (params, opt_state, net_state), sc)
                return p, o, s, losses

            self._jit_cache[cache_key] = many
        self._key, sub = jax.random.split(self._key)
        self.params, self.opt_state, self.net_state, losses = many(
            self.params, self.opt_state, self.net_state,
            jnp.asarray(self.iteration_count, jnp.int32), sub, feeds, labs)
        start = self.iteration_count
        self.iteration_count += n_steps
        self._score = losses[-1]
        losses = np.asarray(losses)
        # fire listeners after the fused chunk (per-step losses; params only
        # current as of chunk end) — the fast path no longer skips them.
        # Iteration-major order so multi-listener interleaving matches fit()
        first_feed = next(iter(feeds.values()))
        self.last_batch_size = int(first_feed.shape[1]) if per_step_data \
            else int(first_feed.shape[0])
        for k in range(n_steps):
            for lst in self.listeners:
                lst.iteration_done(self, start + k + 1, self.epoch_count,
                                   float(losses[k]))
        return losses

    def score(self) -> float:
        return float(getattr(self, "_score", float("nan")))

    def evaluate(self, iterator, evaluation=None) -> Evaluation:
        e = evaluation if evaluation is not None else Evaluation()
        if isinstance(iterator, DataSet):
            iterator = ListDataSetIterator(iterator, batch_size=256)
        in_name = self.conf.network_inputs[0]
        for ds in iterator:
            masks = (None if ds.features_mask is None
                     else {in_name: ds.features_mask})
            out = self.output_single(ds.features, masks=masks)
            e.eval(ds.labels, out, ds.labels_mask)
        return e

    # ---------------------------------------------------- flat params / serde
    def params_flat(self) -> np.ndarray:
        leaves = []
        for name in sorted(self.params):
            leaves.extend(_sorted_leaves(self.params[name]))
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate([np.asarray(l).reshape(-1) for l in leaves])

    def set_params_flat(self, flat: np.ndarray) -> None:
        flat = np.asarray(flat)
        offset = 0
        new_params = {}
        for name in sorted(self.params):
            new_p, offset = _unflatten_like(self.params[name], flat, offset)
            new_params[name] = new_p
        if offset != flat.size:
            raise ValueError(f"param vector length {flat.size} != model size {offset}")
        self.params = jax.tree.map(jnp.asarray, new_params)

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape))
                   for p in self.params.values() for l in jax.tree.leaves(p))


# share the gradient-normalization logic with MultiLayerNetwork
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork as _MLN  # noqa: E402

ComputationGraph._normalize_gradient = _MLN._normalize_gradient


def save_graph(net: ComputationGraph, path: str, save_updater: bool = True) -> None:
    """ModelSerializer.writeModel for ComputationGraph."""
    import zipfile

    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", net.conf.to_json())
        z.writestr("coefficients.bin", net.params_flat().astype(np.float32).tobytes())
        meta = {"iteration_count": net.iteration_count, "epoch_count": net.epoch_count,
                "model_type": "ComputationGraph"}
        z.writestr("meta.json", json.dumps(meta))
        if save_updater and net.opt_state is not None:
            leaves = []
            for name in sorted(net.opt_state):
                leaves.extend(_sorted_leaves(net.opt_state[name]))
            blob = (np.concatenate([np.asarray(l).reshape(-1) for l in leaves])
                    if leaves else np.zeros((0,), np.float32))
            z.writestr("updaterState.bin", blob.astype(np.float32).tobytes())


def restore_graph(path: str, load_updater: bool = True) -> ComputationGraph:
    import zipfile

    with zipfile.ZipFile(path, "r") as z:
        conf = ComputationGraphConfiguration.from_json(z.read("configuration.json").decode())
        net = ComputationGraph(conf).init()
        net.set_params_flat(np.frombuffer(z.read("coefficients.bin"), np.float32))
        if "meta.json" in z.namelist():
            meta = json.loads(z.read("meta.json").decode())
            net.iteration_count = meta.get("iteration_count", 0)
            net.epoch_count = meta.get("epoch_count", 0)
        if load_updater and "updaterState.bin" in z.namelist():
            flat = np.frombuffer(z.read("updaterState.bin"), np.float32)
            offset = 0
            new_states = {}
            for name in sorted(net.opt_state):
                ns, offset = _unflatten_like(net.opt_state[name], flat, offset)
                new_states[name] = ns
            net.opt_state = jax.tree.map(jnp.asarray, new_states)
    return net
