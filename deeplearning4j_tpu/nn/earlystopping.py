"""Early stopping — deeplearning4j-core earlystopping parity.

Reference parity:
  * org/deeplearning4j/earlystopping/EarlyStoppingConfiguration.java,
    trainer/EarlyStoppingTrainer.java, termination conditions
    (MaxEpochsTerminationCondition, MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition, MaxScoreIterationTermination),
    saver/{LocalFileModelSaver, InMemoryModelSaver}, EarlyStoppingResult.
"""

from __future__ import annotations

import copy
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class EpochTerminationCondition:
    def terminate(self, epoch: int, score: float, history: List[float]) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score, history):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without (min_improvement) improvement."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement

    def terminate(self, epoch, score, history):
        if len(history) <= self.patience:
            return False
        best_before = min(history[: -self.patience])
        recent_best = min(history[-self.patience :])
        # no strict improvement of at least min_improvement in `patience` epochs
        return recent_best >= best_before - self.min_improvement


class IterationTerminationCondition:
    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort if score explodes past a bound (divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score):
        return score > self.max_score or not np.isfinite(score)


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def terminate(self, score):
        # monotonic clock: the max-time budget is a duration, and a wall
        # clock jumping (NTP) would terminate training early or never
        if self._start is None:
            self._start = time.perf_counter()
            return False
        return time.perf_counter() - self._start > self.max_seconds


class InMemoryModelSaver:
    """saver/InMemoryModelSaver.java."""

    def __init__(self):
        self.best = None
        self.latest = None

    def save_best(self, net):
        self.best = {"params": copy.deepcopy(net.params),
                     "net_state": copy.deepcopy(net.net_state)}

    def save_latest(self, net):
        self.latest = {"params": copy.deepcopy(net.params),
                       "net_state": copy.deepcopy(net.net_state)}

    def restore_best(self, net):
        if self.best is not None:
            net.params = self.best["params"]
            net.net_state = self.best["net_state"]
        return net


class LocalFileModelSaver:
    """saver/LocalFileModelSaver.java: bestModel.zip / latestModel.zip."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def save_best(self, net):
        from deeplearning4j_tpu.nn.serde import save_model

        save_model(net, os.path.join(self.dir, "bestModel.zip"))

    def save_latest(self, net):
        from deeplearning4j_tpu.nn.serde import save_model

        save_model(net, os.path.join(self.dir, "latestModel.zip"))

    def restore_best(self, net):
        from deeplearning4j_tpu.nn.serde import restore_model

        return restore_model(os.path.join(self.dir, "bestModel.zip"))


class EarlyStoppingConfiguration:
    """EarlyStoppingConfiguration.Builder analog (kwargs instead of builder)."""

    def __init__(self,
                 epoch_termination_conditions: Optional[List[EpochTerminationCondition]] = None,
                 iteration_termination_conditions: Optional[List[IterationTerminationCondition]] = None,
                 score_calculator: Optional[Callable[[Any], float]] = None,
                 model_saver=None,
                 evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False):
        self.epoch_conditions = epoch_termination_conditions or []
        self.iteration_conditions = iteration_termination_conditions or []
        self.score_calculator = score_calculator
        self.saver = model_saver if model_saver is not None else InMemoryModelSaver()
        self.every_n = max(1, evaluate_every_n_epochs)
        self.save_last_model = save_last_model


class EarlyStoppingResult:
    """EarlyStoppingResult.java: reason, best epoch/score, score history."""

    def __init__(self, termination_reason: str, termination_details: str,
                 best_epoch: int, best_score: float,
                 total_epochs: int, score_history: Dict[int, float], best_model):
        self.termination_reason = termination_reason
        self.termination_details = termination_details
        self.best_epoch = best_epoch
        self.best_score = best_score
        self.total_epochs = total_epochs
        self.score_history = score_history
        self.best_model = best_model


class EarlyStoppingTrainer:
    """trainer/EarlyStoppingTrainer.java for MultiLayerNetwork (and graphs)."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator,
                 test_iterator=None):
        self.cfg = config
        self.net = net
        self.train_iter = train_iterator
        self.test_iter = test_iterator

    def _score(self) -> float:
        if self.cfg.score_calculator is not None:
            return float(self.cfg.score_calculator(self.net))
        if self.test_iter is not None:
            # default: loss on the test set (DataSetLossCalculator analog)
            scores = []
            for ds in self.test_iter:
                scores.append(self.net.score(ds))
            return float(np.mean(scores))
        return self.net.score()

    def fit(self) -> EarlyStoppingResult:
        best_score = float("inf")
        best_epoch = -1
        history: Dict[int, float] = {}
        epoch_scores: List[float] = []
        epoch = 0
        reason, details = "EpochTerminationCondition", "exhausted"
        while True:
            self.net.fit(self.train_iter, epochs=1)
            # iteration-condition check on the training score
            train_score = self.net.score()
            stop_iter = False
            for c in self.cfg.iteration_conditions:
                if c.terminate(train_score):
                    reason = "IterationTerminationCondition"
                    details = f"{type(c).__name__} at epoch {epoch}"
                    stop_iter = True
                    break
            if stop_iter:
                break
            if epoch % self.cfg.every_n == 0:
                score = self._score()
                history[epoch] = score
                epoch_scores.append(score)
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    self.cfg.saver.save_best(self.net)
                if self.cfg.save_last_model:
                    self.cfg.saver.save_latest(self.net)
            # termination checks run EVERY epoch (reference semantics); score
            # conditions see the most recent calculated score
            last = epoch_scores[-1] if epoch_scores else float("inf")
            stop_epoch = False
            for c in self.cfg.epoch_conditions:
                if c.terminate(epoch, last, epoch_scores):
                    reason = "EpochTerminationCondition"
                    details = f"{type(c).__name__} at epoch {epoch}"
                    stop_epoch = True
                    break
            if stop_epoch:
                break
            epoch += 1
        best_model = self.cfg.saver.restore_best(self.net)
        return EarlyStoppingResult(reason, details, best_epoch, best_score,
                                   epoch + 1, history, best_model)
