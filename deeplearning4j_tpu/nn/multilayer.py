"""MultiLayerNetwork — the sequential model runtime.

Reference parity:
  * org/deeplearning4j/nn/multilayer/MultiLayerNetwork.java (~4.5k lines):
    init/fit/output/score/evaluate, flattened params, listeners.
  * org/deeplearning4j/optimize/Solver.java + solvers/StochasticGradientDescent:
    the per-minibatch optimize step.
  * org/deeplearning4j/nn/updater/MultiLayerUpdater.java: per-layer updater
    blocks over the flattened gradient, regularization + clipping.

TPU-native realization (the SURVEY §4.1 collapse): forward + loss + backward +
regularization + clipping + updater all trace into ONE jitted step function
with donated buffers — the reference's thousands of per-op JNI round trips
per second become one XLA executable launch per iteration. Parameters are a
pytree (list of per-layer dicts); ``params_flat()`` reproduces the
reference's single contiguous parameter view for parity/serde.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults, observe

from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn.layers import Layer, build_layer, apply_preprocessor
from deeplearning4j_tpu.nn.updater import Updater
from deeplearning4j_tpu.nn.listeners import (
    TrainingListener, notify_fit_done, notify_preemption)
from deeplearning4j_tpu.ops.losses import get_loss
from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator, ListDataSetIterator
from deeplearning4j_tpu.eval.evaluation import Evaluation, RegressionEvaluation, ROC

logger = logging.getLogger(__name__)

WEIGHT_KEYS = {"W", "RW", "dW", "pW", "Wq", "Wk", "Wv", "Wo"}


def _map_weights(fn, tree, other=None):
    """Apply fn to weight leaves only (regularization targets — the
    reference regularizes weights, not biases/gamma/beta, by default)."""
    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = _map_weights(fn, v, None if other is None else other[k])
            elif k in WEIGHT_KEYS:
                out[k] = fn(v) if other is None else fn(v, other[k])
            else:
                out[k] = v
        return out
    return tree


def _tree_l2_sq_weights(tree) -> jax.Array:
    total = jnp.zeros(())
    if isinstance(tree, dict):
        for k, v in tree.items():
            if isinstance(v, dict):
                total = total + _tree_l2_sq_weights(v)
            elif k in WEIGHT_KEYS:
                total = total + jnp.sum(v.astype(jnp.float32) ** 2)
    return total


def _tree_l1_weights(tree) -> jax.Array:
    total = jnp.zeros(())
    if isinstance(tree, dict):
        for k, v in tree.items():
            if isinstance(v, dict):
                total = total + _tree_l1_weights(v)
            elif k in WEIGHT_KEYS:
                total = total + jnp.sum(jnp.abs(v.astype(jnp.float32)))
    return total


def apply_layer_updates(conf, items, step, normalize_fn):
    """THE per-layer update block, shared by MultiLayerNetwork and
    ComputationGraph: L1/L2 into the gradient, clipping, updater math, weight
    decay (BaseMultiLayerUpdater.update + WeightDecay.applyStep).

    items: iterable of (params, grads, opt_state, updater, layer_conf).
    Returns a list of (new_params, new_opt_state) in input order."""
    out = []
    for p, g, s, upd, lc in items:
        l1 = conf.layer_l1(lc)
        l2 = conf.layer_l2(lc)
        wd = conf.layer_weight_decay(lc)
        if l2:
            g = _map_weights(lambda gw, w: gw + l2 * w, g, p)
        if l1:
            g = _map_weights(lambda gw, w: gw + l1 * jnp.sign(w), g, p)
        g = normalize_fn(g)
        lr = upd.lr(step)
        flat_p, treedef = jax.tree.flatten(p)
        flat_g = treedef.flatten_up_to(g)
        flat_s = treedef.flatten_up_to(s)
        new_p, news = [], []
        for pw, gw, sw in zip(flat_p, flat_g, flat_s):
            # fused step: the registry op's TPU helper runs the whole
            # updater chain as ONE kernel pass per leaf when the tuning
            # table says it wins; generic impl = the identical apply() math
            npw, ns = upd.apply_fused(pw, gw, sw, lr, step)
            new_p.append(npw)
            news.append(ns)
        if wd:
            rebuilt = _map_weights(lambda w, w0: w - lr * wd * w0,
                                   treedef.unflatten(new_p),
                                   treedef.unflatten(flat_p))
            new_p = treedef.flatten_up_to(rebuilt)
        out.append((treedef.unflatten(new_p), treedef.unflatten(news)))
    return out


def aux_losses(new_state):
    """Sum differentiable side losses layers stash in their state under
    ``_aux_loss`` (MoE load-balance loss, nn/moe_layer.py). new_state is a
    list (MultiLayerNetwork) or dict (ComputationGraph) of layer states;
    the scalars are computed inside the loss closure, so gradients flow."""
    states = new_state.values() if isinstance(new_state, dict) else new_state
    total = jnp.zeros(())
    for st in states:
        if isinstance(st, dict) and "_aux_loss" in st:
            total = total + st["_aux_loss"]
    return total


def reg_penalty(conf, items):
    """Score regularization penalty (BaseLayer.calcRegularizationScore).
    items: iterable of (params, layer_conf)."""
    penalty = jnp.zeros(())
    for p, lc in items:
        l1 = conf.layer_l1(lc)
        l2 = conf.layer_l2(lc)
        if l2:
            penalty = penalty + 0.5 * l2 * _tree_l2_sq_weights(p)
        if l1:
            penalty = penalty + l1 * _tree_l1_weights(p)
    return penalty


class MultiLayerNetwork:
    """Sequential network over a MultiLayerConfiguration."""

    def __init__(self, conf: C.MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = []
        itype = conf.input_type
        for i, lc in enumerate(conf.layers):
            pre = conf.preprocessors.get(i)
            if pre is not None and itype is not None:
                if isinstance(pre, C.FeedForwardToCnnPreProcessor):
                    itype = C.InputType.convolutional(pre.height, pre.width, pre.channels)
                elif isinstance(pre, C.CnnToFeedForwardPreProcessor):
                    itype = C.InputType.feed_forward(pre.height * pre.width * pre.channels)
            layer = build_layer(conf, lc, itype or C.InputType.feed_forward(0))
            self.layers.append(layer)
            itype = layer.otype
        self.params: Optional[List[Dict[str, Any]]] = None
        self.net_state: Optional[List[Dict[str, Any]]] = None
        self.opt_state: Optional[List[Any]] = None
        self.updaters: List[Updater] = [conf.layer_updater(lc) for lc in conf.layers]
        self.iteration_count = 0
        self.epoch_count = 0
        # completed batches in the CURRENT epoch — the data cursor exact
        # resume replays from (checkpointed; docs/ROBUSTNESS.md)
        self.batch_in_epoch = 0
        self.listeners: List[TrainingListener] = []
        self.last_batch_size = 0
        self._key = jax.random.key(conf.seed)
        self._jit_cache: Dict[str, Any] = {}
        # loss comes from the terminal layer config
        last = conf.layers[-1] if conf.layers else None
        self._loss_name = getattr(last, "loss", None)
        if hasattr(last, "loss_fn"):  # conf binds its own hyperparameters
            self._loss_fn = last.loss_fn()
        else:
            self._loss_fn = get_loss(self._loss_name) if self._loss_name else None

    # ------------------------------------------------------------------ init
    def init(self, params: Optional[List[Dict[str, Any]]] = None) -> "MultiLayerNetwork":
        """Initialize parameters (MultiLayerNetwork.init())."""
        from deeplearning4j_tpu.nn import dtype as DT

        with DT.precision_scope(self.conf.dtype):
            if params is not None:
                self.params = params
            else:
                key = jax.random.key(self.conf.seed)
                keys = jax.random.split(key, max(len(self.layers), 1))
                self.params = [l.init(k) for l, k in zip(self.layers, keys)]
            self.net_state = [l.init_state() for l in self.layers]
            self.opt_state = [
                jax.tree.map(upd.init_state, p)
                for upd, p in zip(self.updaters, self.params)
            ]
        return self

    def set_listeners(self, *ls: TrainingListener) -> None:
        self.listeners = list(ls)

    def add_listeners(self, *ls: TrainingListener) -> None:
        self.listeners.extend(ls)

    # --------------------------------------------------------------- forward
    def _forward(self, params, net_state, x, mask, *, train: bool, rng,
                 rnn_states=None, tap_input_of: Optional[int] = None):
        """Run preprocessors + layers; returns (out, new_net_state) — or,
        when ``rnn_states`` is given (a list, one entry per layer, None for
        non-recurrent layers), (out, new_net_state, new_rnn_states): the
        tBPTT / rnnTimeStep state-threading path
        (rnnActivateUsingStoredState in the reference)."""
        from deeplearning4j_tpu.nn import dtype as DT

        with DT.precision_scope(self.conf.dtype):
            if DT.needs_cast(self.conf.dtype):
                # mixed policy: bf16 compute against f32 master params — ONE cast
                # chokepoint so grads flow back to the f32 masters
                cd = DT.compute_dtype(self.conf.dtype)
                params = DT.cast_floats(params, cd)
                x = DT.cast_floats(x, cd)
                if rnn_states is not None:
                    rnn_states = DT.cast_floats(rnn_states, cd)
            new_state = []
            new_rnn = [] if rnn_states is not None else None
            tapped = None
            rngs = jax.random.split(rng, max(len(self.layers), 1)) if rng is not None else [None] * len(self.layers)
            for i, layer in enumerate(self.layers):
                x = apply_preprocessor(self.conf.preprocessors.get(i), x)
                if i == tap_input_of:
                    tapped = x
                if rnn_states is not None and hasattr(layer, "apply_with_state"):
                    x = layer._maybe_dropout(x, train=train, rng=rngs[i])
                    x, last = layer.apply_with_state(
                        params[i], x, mask=mask, initial=rnn_states[i])
                    new_rnn.append(last)
                    new_state.append(net_state[i])
                else:
                    x, st, mask = layer.apply(
                        params[i], x, net_state[i], train=train, rng=rngs[i], mask=mask)
                    new_state.append(st)
                    if new_rnn is not None:
                        new_rnn.append(None)
            if DT.needs_cast(self.conf.dtype):
                x = DT.cast_floats(x, jnp.float32)  # loss/eval math stays f32
        if rnn_states is not None:
            return x, new_state, new_rnn
        if tap_input_of is not None:
            return x, new_state, tapped
        return x, new_state

    def feed_forward(self, x, train: bool = False) -> List[np.ndarray]:
        """Per-layer activations list (MultiLayerNetwork.feedForward) —
        un-jitted debugging path."""
        acts = []
        xj = jnp.asarray(x)
        mask = None
        rngs = jax.random.split(self._key, max(len(self.layers), 1))
        for i, layer in enumerate(self.layers):
            xj = apply_preprocessor(self.conf.preprocessors.get(i), xj)
            xj, _, mask = layer.apply(
                self.params[i], xj, self.net_state[i], train=train, rng=rngs[i], mask=mask)
            acts.append(np.asarray(xj))
        return acts

    # ---------------------------------------------------------------- output
    def output(self, x, mask=None) -> np.ndarray:
        """Inference forward (MultiLayerNetwork.output) — jitted."""
        fn = self._jit_cache.get("output")
        if fn is None:
            @jax.jit
            def fn(params, net_state, x, mask):
                out, _ = self._forward(params, net_state, x, mask, train=False, rng=None)
                return out

            self._jit_cache["output"] = fn
        return np.asarray(fn(self.params, self.net_state, jnp.asarray(x),
                             None if mask is None else jnp.asarray(mask)))

    def predict(self, x) -> np.ndarray:
        return self.output(x).argmax(axis=-1)

    # ------------------------------------------------------ stateful RNN API
    def rnn_time_step(self, x, mask=None) -> np.ndarray:
        """Stateful streaming inference (MultiLayerNetwork.rnnTimeStep):
        feeds (N, T, F) — or (N, F) for a single step — carrying hidden state
        across calls in ``self._rnn_states``."""
        squeeze = False
        x = np.asarray(x)
        if x.ndim == 2:
            x = x[:, None, :]
            squeeze = True
        if not hasattr(self, "_rnn_states") or self._rnn_states is None:
            self._rnn_states = self._zero_rnn_states(x.shape[0], x.dtype)
        fn = self._jit_cache.get("rnn_time_step")
        if fn is None:
            @jax.jit
            def fn(params, net_state, rnn_states, x, mask):
                out, _, new_rnn = self._forward(
                    params, net_state, x, mask, train=False, rng=None,
                    rnn_states=rnn_states)
                return out, new_rnn

            self._jit_cache["rnn_time_step"] = fn
        out, self._rnn_states = fn(self.params, self.net_state, self._rnn_states,
                                   jnp.asarray(x),
                                   None if mask is None else jnp.asarray(mask))
        out = np.asarray(out)
        return out[:, -1] if squeeze else out

    def rnn_clear_previous_state(self) -> None:
        """MultiLayerNetwork.rnnClearPreviousState analog."""
        self._rnn_states = None

    def rnn_get_previous_state(self, layer_idx: int):
        states = getattr(self, "_rnn_states", None)
        return None if states is None else states[layer_idx]

    def _zero_rnn_states(self, batch: int, dtype=np.float32):
        from deeplearning4j_tpu.nn.layers import BidirectionalImpl

        states = []
        for layer in self.layers:
            if isinstance(layer, BidirectionalImpl):
                # reference rnnTimeStep throws UnsupportedOperationException
                # for bidirectional layers — backward pass needs the future
                raise ValueError(
                    "stateful RNN state (rnn_time_step / tBPTT) is not "
                    "supported with Bidirectional layers")
            if hasattr(layer, "zero_state"):
                states.append(layer.zero_state(batch, dtype))
            else:
                states.append(None)
        return states

    # ------------------------------------------------------------- train step
    def _loss_from_out(self, out, labels, lmask):
        if self._loss_fn is None:
            raise ValueError("terminal layer has no loss configured")
        return self._loss_fn(out, labels, lmask)

    def _apply_updates(self, params, grads, opt_state, step):
        new_items = apply_layer_updates(
            self.conf,
            zip(params, grads, opt_state, self.updaters, self.conf.layers),
            step, self._normalize_gradient)
        return [p for p, _ in new_items], [s for _, s in new_items]

    def _reg_penalty(self, params):
        return reg_penalty(self.conf, zip(params, self.conf.layers))

    def _make_train_step(self):
        last_lc = self.conf.layers[-1] if self.conf.layers else None
        center = isinstance(last_lc, C.CenterLossOutputLayer)

        def train_step(params, opt_state, net_state, step, key, features, labels, fmask, lmask):
            def loss_fn(p):
                if center:
                    # CenterLossOutputLayer: tap the features feeding the
                    # output layer and add λ·½‖f − c_y‖²; gradients flow both
                    # into the centers (params[-1]["centers"]) and back into
                    # the feature extractor — reference semantics.
                    out, new_state, feats = self._forward(
                        p, net_state, features, fmask, train=True, rng=key,
                        tap_input_of=len(self.layers) - 1)
                    loss = self._loss_from_out(out, labels, lmask)
                    f32 = jnp.promote_types(jnp.float32, feats.dtype)
                    f = feats.astype(f32)
                    centers = p[-1]["centers"].astype(f32)
                    y_idx = jnp.argmax(labels, axis=-1)
                    # decoupled center loss: λ weighs the FEATURE pull toward
                    # (detached) centers; α weighs the CENTER pull toward
                    # (detached) features — the gradient α(c_y − f̄) is the
                    # reference's moving-average center update c←c−α(c−f̄)
                    # realized through the optimizer (CenterLossOutputLayer
                    # alpha/lambda semantics).
                    sg = jax.lax.stop_gradient
                    d_feat = f - sg(centers[y_idx])
                    d_ctr = sg(f) - centers[y_idx]
                    loss = (loss
                            + 0.5 * last_lc.lambda_ * jnp.mean(
                                jnp.sum(jnp.square(d_feat), axis=-1))
                            + 0.5 * last_lc.alpha * jnp.mean(
                                jnp.sum(jnp.square(d_ctr), axis=-1)))
                    return loss, new_state
                out, new_state = self._forward(p, net_state, features, fmask, train=True, rng=key)
                loss = self._loss_from_out(out, labels, lmask)
                return loss + aux_losses(new_state), new_state

            (loss, new_net_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(params, grads, opt_state, step)
            return new_params, new_opt, new_net_state, loss + self._reg_penalty(params)

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _make_train_step_tbptt(self):
        """Truncated-BPTT step: same fused step, but RNN state enters as an
        input and leaves as an output — gradients truncate at the segment
        boundary because the incoming state is a constant w.r.t. this
        segment's params (reference MultiLayerNetwork.doTruncatedBPTT)."""

        def train_step(params, opt_state, net_state, rnn_states, step, key,
                       features, labels, fmask, lmask):
            def loss_fn(p):
                out, new_state, new_rnn = self._forward(
                    p, net_state, features, fmask, train=True, rng=key,
                    rnn_states=rnn_states)
                loss = self._loss_from_out(out, labels, lmask)
                return loss + aux_losses(new_state), (new_state, new_rnn)

            (loss, (new_net_state, new_rnn)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(params, grads, opt_state, step)
            return new_params, new_opt, new_net_state, new_rnn, loss + self._reg_penalty(params)

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _fit_tbptt_batch(self, ds, step_fn):
        """Slice the time axis into tBPTT segments, carrying RNN state."""
        fwd = self.conf.tbptt_fwd_length
        if ds.labels.ndim < 3:
            # reference tBPTT requires time-series (3D) labels; a per-sequence
            # label would get one full update per segment against prefixes
            raise ValueError(
                "tBPTT requires 3-D time-series labels (N, T, C); got shape "
                f"{ds.labels.shape} — use standard backprop for per-sequence labels")
        T = ds.features.shape[1]
        rnn_states = self._zero_rnn_states(ds.features.shape[0])
        segments = list(range(0, T, fwd))
        for i, t0 in enumerate(segments):
            t1 = min(t0 + fwd, T)
            seg_x = jnp.asarray(ds.features[:, t0:t1])
            seg_y = jnp.asarray(ds.labels[:, t0:t1])
            seg_fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask[:, t0:t1])
            seg_lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask[:, t0:t1])
            self._key, sub = jax.random.split(self._key)
            (self.params, self.opt_state, self.net_state, rnn_states, loss) = step_fn(
                self.params, self.opt_state, self.net_state, rnn_states,
                jnp.asarray(self.iteration_count, jnp.int32), sub,
                seg_x, seg_y, seg_fm, seg_lm)
            # the reference advances the iteration once per optimize call, i.e.
            # per tBPTT segment (Adam bias-correction t, LR schedules); fit()
            # adds the final +1 covering the last segment
            if i < len(segments) - 1:
                self.iteration_count += 1
        return loss

    def _normalize_gradient(self, g):
        """GradientNormalization enum semantics (BaseMultiLayerUpdater)."""
        kind = self.conf.gradient_normalization
        if not kind:
            return g
        thr = self.conf.gradient_normalization_threshold
        leaves = jax.tree.leaves(g)
        if kind == "renormalize_l2_per_layer":
            norm = jnp.sqrt(sum(jnp.sum(l**2) for l in leaves) + 1e-12)
            return jax.tree.map(lambda l: l / norm, g)
        if kind == "clip_element_wise_absolute_value":
            return jax.tree.map(lambda l: jnp.clip(l, -thr, thr), g)
        if kind == "clip_l2_per_layer":
            norm = jnp.sqrt(sum(jnp.sum(l**2) for l in leaves) + 1e-12)
            scale = jnp.minimum(1.0, thr / norm)
            return jax.tree.map(lambda l: l * scale, g)
        if kind == "clip_l2_per_param_type":
            def clip_one(l):
                n = jnp.sqrt(jnp.sum(l**2) + 1e-12)
                return l * jnp.minimum(1.0, thr / n)
            return jax.tree.map(clip_one, g)
        raise ValueError(f"unknown gradient normalization '{kind}'")

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs: int = 1, batch_size: int = 32) -> None:
        """fit(DataSetIterator | DataSet | (features, labels)).

        MultiLayerNetwork.fit analog; each minibatch runs the single fused
        step function. Arrays are device-put once per batch; donation recycles
        param/optimizer buffers in place (the workspace-arena analog).
        """
        if labels is not None:
            data = ListDataSetIterator(DataSet(data, labels), batch_size=batch_size)
        elif isinstance(data, DataSet):
            data = ListDataSetIterator(data, batch_size=batch_size)

        tbptt = (self.conf.backprop_type == "tbptt" and self.conf.tbptt_fwd_length > 0)
        cache_name = "train_step_tbptt" if tbptt else "train_step"
        step_fn = self._jit_cache.get(cache_name)
        if step_fn is None:
            step_fn = (self._make_train_step_tbptt() if tbptt
                       else self._make_train_step())
            self._jit_cache[cache_name] = step_fn

        _m = observe.metrics()
        _steps_c = _m.counter("dl4j_tpu_train_steps_total", model="mln")
        _ex_c = _m.counter("dl4j_tpu_train_examples_total", model="mln")
        _xfer_c = _m.counter("dl4j_tpu_host_to_device_transfers_total",
                             model="mln")
        _step_h = _m.histogram("dl4j_tpu_train_step_seconds", model="mln")
        for _ in range(epochs):
            for lst in self.listeners:
                lst.on_epoch_start(self)
            t_prev = time.perf_counter()
            n_steps = 0
            # nonzero only when resuming mid-epoch from a checkpoint: the
            # first `skip` batches were already consumed by the killed run
            skip = self.batch_in_epoch
            for bi, ds in enumerate(data):
                if bi < skip:
                    continue
                # preemption (docs/ROBUSTNESS.md): the injected fault is a
                # HARD pod kill (raise — the supervisor restores+resumes);
                # the flag is the SOFT SIGTERM path (final snapshot, clean
                # exit). Both checked at the step boundary, off-trace.
                faults.maybe_fail("preemption")
                if faults.preemption_requested():
                    notify_preemption(self, self.listeners)
                    return
                self.last_batch_size = ds.num_examples()
                # recompile ledger: a new feed shape/dtype signature on the
                # cached jitted step is a silent XLA retrace — record it
                observe.note_jit_signature(
                    step_fn, graph="mln", key=cache_name,
                    signature=observe.signature_of(
                        x=ds.features, y=ds.labels, fm=ds.features_mask,
                        lm=ds.labels_mask))
                # host-side reference only (no copy): StatsListener's
                # activation charts feed_forward this batch on demand
                self._last_features = ds.features
                if tbptt:
                    loss = self._fit_tbptt_batch(ds, step_fn)
                else:
                    self._key, sub = jax.random.split(self._key)
                    self.params, self.opt_state, self.net_state, loss = step_fn(
                        self.params, self.opt_state, self.net_state,
                        jnp.asarray(self.iteration_count, jnp.int32), sub,
                        jnp.asarray(ds.features), jnp.asarray(ds.labels),
                        None if ds.features_mask is None else jnp.asarray(ds.features_mask),
                        None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
                    )
                # keep the device array — float() would force a host sync per
                # step and stall async dispatch; score() converts lazily
                self._score = loss
                self.iteration_count += 1
                self.batch_in_epoch = bi + 1  # cursor BEFORE listeners save
                # inter-step latency on the monotonic clock (first delta
                # includes compile); all telemetry is host-side, off-trace
                now = time.perf_counter()
                _step_h.observe(now - t_prev)
                t_prev = now
                n_steps += 1
                _steps_c.inc()
                _ex_c.inc(ds.num_examples())
                _xfer_c.inc(2 + (ds.features_mask is not None)
                            + (ds.labels_mask is not None))
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count, self.epoch_count, loss)
            self.batch_in_epoch = 0
            self.epoch_count += 1
            observe.log_event("train_epoch", model="mln",
                              epoch=self.epoch_count, steps=n_steps)
            for lst in self.listeners:
                lst.on_epoch_end(self)
        notify_fit_done(self, self.listeners)

    def fit_scanned(self, features, labels, steps: Optional[int] = None) -> np.ndarray:
        """Run many fused train steps in ONE XLA call (lax.scan over the
        train step) — the TPU-native inner loop: zero host dispatch between
        steps, donated carry, schedules/iteration advancing on-device.

        Two modes:
          * ``steps`` given — train repeatedly on the single device-resident
            batch (throughput/benchmark mode).
          * ``steps`` None — ``features``/``labels`` carry a leading
            [steps, batch, ...] axis of per-step minibatches (the
            device-resident-epoch pattern: stage the epoch to HBM once, scan).

        Masks are not supported on this path (use fit()). Returns the
        per-step loss array. Reference analog: there is none — the per-op
        JNI dispatch makes a fused multi-step loop impossible there; this is
        the whole-graph-compile dividend (SURVEY §8.1)."""
        step_fn = self._jit_cache.get("train_step")
        if step_fn is None:
            step_fn = self._make_train_step()
            self._jit_cache["train_step"] = step_fn
        per_step_data = steps is None
        xs = jnp.asarray(features)
        ys = jnp.asarray(labels)
        n_steps = int(xs.shape[0]) if per_step_data else int(steps)

        cache_key = ("fit_scanned", per_step_data, n_steps)
        many = self._jit_cache.get(cache_key)
        if many is None:
            @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
            def many(params, opt_state, net_state, start, key, xs, ys):
                def body(carry, it):
                    p, o, s = carry
                    if per_step_data:
                        i, x, y = it
                    else:
                        i, x, y = it, xs, ys
                    p, o, s, loss = step_fn(p, o, s, i, jax.random.fold_in(key, i),
                                            x, y, None, None)
                    return (p, o, s), loss
                idx = start + jnp.arange(n_steps, dtype=jnp.int32)
                sc_xs = (idx, xs, ys) if per_step_data else idx
                (p, o, s), losses = jax.lax.scan(body, (params, opt_state, net_state), sc_xs)
                return p, o, s, losses

            self._jit_cache[cache_key] = many
        observe.note_jit_signature(
            many, graph="mln", key="fit_scanned",
            signature=observe.signature_of(x=xs, y=ys))
        self._key, sub = jax.random.split(self._key)
        t0 = time.perf_counter()
        self.params, self.opt_state, self.net_state, losses = many(
            self.params, self.opt_state, self.net_state,
            jnp.asarray(self.iteration_count, jnp.int32), sub, xs, ys)
        start = self.iteration_count
        self.iteration_count += n_steps
        _m = observe.metrics()
        _m.counter("dl4j_tpu_train_steps_total", model="mln").inc(n_steps)
        _m.counter("dl4j_tpu_host_to_device_transfers_total",
                   model="mln").inc(2)
        self._score = losses[-1]
        losses = np.asarray(losses)  # host sync: the chunk is done here
        # listeners fire AFTER the fused chunk, once per inner step with the
        # recorded loss — coarser timing than fit() (params are only current
        # as of the chunk end) but checkpoint/score listeners keep working on
        # the fast path instead of silently not firing (round-2 weak #8).
        # Iteration-major order so multi-listener interleaving matches fit()
        self.last_batch_size = int(xs.shape[1]) if per_step_data \
            else int(xs.shape[0])
        _m.counter("dl4j_tpu_train_examples_total", model="mln").inc(
            n_steps * self.last_batch_size)
        observe.tracer().complete_between(
            "fit_scanned", t0, time.perf_counter(), category="train",
            steps=n_steps)
        for k in range(n_steps):
            for lst in self.listeners:
                lst.iteration_done(self, start + k + 1, self.epoch_count,
                                   float(losses[k]))
        return losses

    def score(self, ds: Optional[DataSet] = None) -> float:
        """Loss on a dataset, or last training score (MultiLayerNetwork.score)."""
        if ds is None:
            s = getattr(self, "_score", float("nan"))
            return float(s)
        out = self.output(ds.features, ds.features_mask)
        loss = self._loss_fn(
            jnp.asarray(out), jnp.asarray(ds.labels),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask))
        return float(loss)

    # -------------------------------------------------------------- evaluate
    def evaluate(self, iterator, evaluation=None) -> Evaluation:
        """evaluate(DataSetIterator) -> Evaluation (net.evaluate analog)."""
        e = evaluation if evaluation is not None else Evaluation()
        if isinstance(iterator, DataSet):
            iterator = ListDataSetIterator(iterator, batch_size=256)
        for ds in iterator:
            out = self.output(ds.features, ds.features_mask)
            e.eval(ds.labels, out, ds.labels_mask)
        return e

    def evaluate_regression(self, iterator) -> RegressionEvaluation:
        return self.evaluate(iterator, RegressionEvaluation())

    def evaluate_roc(self, iterator) -> ROC:
        return self.evaluate(iterator, ROC())

    # ------------------------------------------------------- flattened params
    def params_flat(self) -> np.ndarray:
        """Single flat parameter vector (MultiLayerNetwork.params()).

        The reference stores ALL params as views into one contiguous buffer;
        we reproduce the export for serde/parity. Order: layer order, then
        sorted param keys within a layer (deterministic)."""
        leaves = []
        for p in self.params:
            leaves.extend(_sorted_leaves(p))
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate([np.asarray(l).reshape(-1) for l in leaves])

    def set_params_flat(self, flat: np.ndarray) -> None:
        flat = np.asarray(flat)
        offset = 0
        new_params = []
        for p in self.params:
            new_p, offset = _unflatten_like(p, flat, offset)
            new_params.append(new_p)
        if offset != flat.size:
            raise ValueError(f"param vector length {flat.size} != model size {offset}")
        self.params = jax.tree.map(jnp.asarray, new_params)

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape)) for p in self.params for l in jax.tree.leaves(p))

    # ------------------------------------------------------- updater state io
    def updater_state_flat(self) -> np.ndarray:
        leaves = []
        for s in self.opt_state:
            leaves.extend(_sorted_leaves(s))
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate([np.asarray(l).reshape(-1) for l in leaves])

    def set_updater_state_flat(self, flat: np.ndarray) -> None:
        flat = np.asarray(flat)
        offset = 0
        new_states = []
        for s in self.opt_state:
            new_s, offset = _unflatten_like(s, flat, offset)
            new_states.append(new_s)
        self.opt_state = jax.tree.map(jnp.asarray, new_states)


def _sorted_leaves(tree) -> List[Any]:
    """Deterministic (sorted-key DFS) leaf order for flat export."""
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            v = tree[k]
            if isinstance(v, dict):
                out.extend(_sorted_leaves(v))
            else:
                out.append(v)
    return out


def _unflatten_like(tree, flat, offset):
    if isinstance(tree, dict):
        out = {}
        for k in sorted(tree):
            v = tree[k]
            if isinstance(v, dict):
                out[k], offset = _unflatten_like(v, flat, offset)
            else:
                n = int(np.prod(v.shape)) if v.shape else 1
                out[k] = flat[offset : offset + n].reshape(v.shape).astype(np.asarray(v).dtype)
                offset += n
        # preserve original insertion order of the source dict
        return {k: out[k] for k in tree}, offset
    return tree, offset
