"""Dtype policy — mixed precision the TPU way.

Reference parity: the reference's global data-type switch
(org.nd4j.linalg.api.buffer.DataType + NeuralNetConfiguration.dataType),
which flips every buffer to FLOAT/HALF/DOUBLE. On TPU the profitable policy
is finer: keep parameters, optimizer state, and loss math in float32 while
running layer compute (conv/matmul activations) in bfloat16 so the MXU gets
bf16 operands and HBM traffic halves — the jmp/flax "mixed_bfloat16" recipe.

Policies (MultiLayerConfiguration.dtype / GraphBuilder.dtype):
  * "float32" / "float64"  — everything in one dtype (reference semantics)
  * "bfloat16" / "float16" — params AND compute in the low dtype
  * "mixed" (alias "mixed_bfloat16") — f32 params/updater/loss, bf16 compute

Casting happens at ONE chokepoint per network (the top of ``_forward``), so
gradients flow through the cast back to the f32 master weights — the
standard master-weights scheme, without a loss-scale knob because bf16
shares float32's exponent range (unlike fp16, no underflow cliff).
"""

from __future__ import annotations

import contextlib
from typing import Any

import jax
import jax.numpy as jnp

_MIXED = ("mixed", "mixed_bfloat16")
_LOW = ("bfloat16", "float16")


def matmul_precision(policy: str) -> str:
    """XLA dot/conv precision implied by the dtype policy.

    Reference parity: DL4J's DataType.FLOAT means float32 math everywhere
    (CUDA fp32 kernels). The TPU MXU natively multiplies bf16, so a float32
    network must request multi-pass precision — otherwise f32 matmuls
    silently run at bf16-class (~1e-2 rel) error, which is exactly what
    sank the CPU-vs-TPU consistency suite. Low/mixed policies keep
    'default': their operands are already bf16/fp16 so the knob costs
    nothing and buys nothing.

    'high' (bf16x3 passes, ~1e-5 abs error vs true f32) rather than
    'highest' (bf16x6): measured on the v5e, 'highest' blows XLA conv
    compile time up ~90x (LeNet train step: 4s default, 174s high, >380s
    highest) for precision nobody can observe through f32 storage. An
    explicitly set Environment.matmul_precision (e.g. 'highest') still
    overrides via precision_scope.
    """
    if policy in _MIXED or policy in _LOW:
        return "default"
    return "high"


def precision_scope(policy: str):
    """Context manager pinning matmul/conv precision for traces under it.

    Applied at the network _forward chokepoint (trace time), so every
    dot_general/conv the layers emit inherits the policy's precision.
    An explicit Environment.matmul_precision setting (the global knob,
    pushed via apply_jax_config) wins over the policy-derived default —
    a user who asked for fast f32 matmuls keeps them.
    """
    from deeplearning4j_tpu.environment import environment

    stack = contextlib.ExitStack()
    if policy == "float64":
        # DataType.DOUBLE semantics: without this scope JAX silently
        # truncates every requested f64 buffer to f32 (with a UserWarning),
        # so "double" networks were double in name only
        stack.enter_context(jax.enable_x64())
    if environment().matmul_precision != "default":
        return stack  # respect the explicit global knob
    prec = matmul_precision(policy)
    if prec != "default":
        stack.enter_context(jax.default_matmul_precision(prec))
    return stack


def param_dtype(policy: str) -> jnp.dtype:
    """Storage dtype for parameters/optimizer state under the policy."""
    if policy in _MIXED:
        return jnp.dtype(jnp.float32)
    return jnp.dtype(policy)


def compute_dtype(policy: str) -> jnp.dtype:
    """Dtype layer compute runs in under the policy."""
    if policy in _MIXED:
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(policy)


def needs_cast(policy: str) -> bool:
    return policy in _MIXED


def cast_floats(tree: Any, dtype) -> Any:
    """Cast every inexact-dtype leaf to ``dtype``; ints/bools untouched."""
    def cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact):
            return leaf.astype(dtype)
        return leaf

    return jax.tree.map(cast, tree)
