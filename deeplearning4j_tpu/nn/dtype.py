"""Dtype policy — mixed precision the TPU way.

Reference parity: the reference's global data-type switch
(org.nd4j.linalg.api.buffer.DataType + NeuralNetConfiguration.dataType),
which flips every buffer to FLOAT/HALF/DOUBLE. On TPU the profitable policy
is finer: keep parameters, optimizer state, and loss math in float32 while
running layer compute (conv/matmul activations) in bfloat16 so the MXU gets
bf16 operands and HBM traffic halves — the jmp/flax "mixed_bfloat16" recipe.

Policies (MultiLayerConfiguration.dtype / GraphBuilder.dtype):
  * "float32" / "float64"  — everything in one dtype (reference semantics)
  * "bfloat16" / "float16" — params AND compute in the low dtype
  * "mixed" (alias "mixed_bfloat16") — f32 params/updater/loss, bf16 compute

Casting happens at ONE chokepoint per network (the top of ``_forward``), so
gradients flow through the cast back to the f32 master weights — the
standard master-weights scheme, without a loss-scale knob because bf16
shares float32's exponent range (unlike fp16, no underflow cliff).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

_MIXED = ("mixed", "mixed_bfloat16")


def param_dtype(policy: str) -> jnp.dtype:
    """Storage dtype for parameters/optimizer state under the policy."""
    if policy in _MIXED:
        return jnp.dtype(jnp.float32)
    return jnp.dtype(policy)


def compute_dtype(policy: str) -> jnp.dtype:
    """Dtype layer compute runs in under the policy."""
    if policy in _MIXED:
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(policy)


def needs_cast(policy: str) -> bool:
    return policy in _MIXED


def cast_floats(tree: Any, dtype) -> Any:
    """Cast every inexact-dtype leaf to ``dtype``; ints/bools untouched."""
    def cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact):
            return leaf.astype(dtype)
        return leaf

    return jax.tree.map(cast, tree)
