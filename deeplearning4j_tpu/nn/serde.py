"""Model persistence — org/deeplearning4j/util/ModelSerializer.java parity.

The reference writes a zip of:
  * ``configuration.json`` — full architecture (Jackson JSON round-trip)
  * ``coefficients.bin`` — the single flat parameter buffer
  * ``updaterState.bin`` — flat updater state (exact resume)
  * optional normalizer stats

We reproduce exactly that layout (float32 little-endian buffers + JSON), plus
a ``netState.bin`` entry for BatchNorm running stats (the reference keeps
those inside coefficients; ours are separate state — recorded explicitly so
restore is exact). Large-scale sharded checkpoints (orbax/tensorstore) live in
parallel/checkpoint.py; this zip format is the user-facing parity surface.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import numpy as np

from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, _sorted_leaves


def _flat_state(states) -> np.ndarray:
    leaves = []
    for s in states:
        leaves.extend(_sorted_leaves(s))
    return flatten_pytree(leaves)


def flatten_pytree(tree) -> np.ndarray:
    """Flatten any param pytree to ONE f32 coefficients buffer in
    ``jax.tree.leaves`` order (deterministic for a fixed structure) — the
    coefficients.bin convention for raw-pytree models (models/gpt.py)."""
    import jax

    leaves = jax.tree.leaves(tree)
    if not leaves:
        return np.zeros((0,), np.float32)
    return np.concatenate(
        [np.asarray(l).reshape(-1).astype(np.float32) for l in leaves])


def unflatten_pytree(template, flat: np.ndarray):
    """Inverse of :func:`flatten_pytree`: rebuild ``template``'s structure
    (shapes/dtypes from the template leaves) from the flat buffer. The
    template may hold real arrays OR abstract ``jax.eval_shape`` leaves —
    only ``.shape``/``.dtype`` are read."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(template)
    out, offset = [], 0
    for l in leaves:
        shape = tuple(getattr(l, "shape", np.shape(l)))
        dtype = getattr(l, "dtype", None) or jnp.asarray(l).dtype
        n = int(np.prod(shape)) if shape else 1
        chunk = flat[offset:offset + n]
        if chunk.size != n:
            raise ValueError(
                f"coefficients buffer exhausted: leaf needs {n} values, "
                f"{chunk.size} left — config/params mismatch")
        out.append(jnp.asarray(chunk.reshape(shape), dtype=dtype))
        offset += n
    if offset != flat.size:
        raise ValueError(
            f"coefficients buffer has {flat.size - offset} trailing values "
            f"— config/params mismatch")
    return jax.tree.unflatten(treedef, out)


def save_model(net: MultiLayerNetwork, path: str, save_updater: bool = True,
               normalizer=None) -> None:
    """ModelSerializer.writeModel analog."""
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", net.conf.to_json())
        z.writestr("coefficients.bin", net.params_flat().astype(np.float32).tobytes())
        z.writestr("netState.bin", _flat_state(net.net_state).tobytes())
        meta = {"iteration_count": net.iteration_count, "epoch_count": net.epoch_count}
        z.writestr("meta.json", json.dumps(meta))
        if save_updater and net.opt_state is not None:
            z.writestr("updaterState.bin", net.updater_state_flat().astype(np.float32).tobytes())
        if normalizer is not None:
            state = {k: np.asarray(v).tolist() for k, v in normalizer.state().items()}
            z.writestr("normalizer.json", json.dumps(
                {"@type": type(normalizer).__name__, "state": state}))


def restore_model(path: str, load_updater: bool = True) -> MultiLayerNetwork:
    """ModelSerializer.restoreMultiLayerNetwork analog."""
    with zipfile.ZipFile(path, "r") as z:
        conf = MultiLayerConfiguration.from_json(z.read("configuration.json").decode())
        net = MultiLayerNetwork(conf).init()
        coeffs = np.frombuffer(z.read("coefficients.bin"), np.float32)
        net.set_params_flat(coeffs)
        if "netState.bin" in z.namelist():
            state_flat = np.frombuffer(z.read("netState.bin"), np.float32)
            offset = 0
            from deeplearning4j_tpu.nn.multilayer import _unflatten_like
            import jax.numpy as jnp
            import jax

            new_states = []
            for s in net.net_state:
                ns, offset = _unflatten_like(s, state_flat, offset)
                new_states.append(ns)
            net.net_state = jax.tree.map(jnp.asarray, new_states)
        if "meta.json" in z.namelist():
            meta = json.loads(z.read("meta.json").decode())
            net.iteration_count = meta.get("iteration_count", 0)
            net.epoch_count = meta.get("epoch_count", 0)
        if load_updater and "updaterState.bin" in z.namelist():
            net.set_updater_state_flat(np.frombuffer(z.read("updaterState.bin"), np.float32))
    return net


def restore_normalizer(path: str):
    """ModelSerializer.restoreNormalizers analog."""
    from deeplearning4j_tpu.datasets import dataset as D

    with zipfile.ZipFile(path, "r") as z:
        if "normalizer.json" not in z.namelist():
            return None
        d = json.loads(z.read("normalizer.json").decode())
    cls = getattr(D, d["@type"])
    norm = cls()
    norm.load_state({k: np.asarray(v) for k, v in d["state"].items()})
    return norm
