"""Audio record reading — the datavec-data-audio role.

Reference parity: datavec-data-audio wraps musicg/jlayer to read WAV files
and extract spectrogram/fingerprint features
(org/datavec/audio/recordreader/WavFileRecordReader.java,
audio/extension/Spectrogram.java). Here: stdlib ``wave`` PCM decoding and
numpy STFT features — no native audio stack needed for the same surface.
"""

from __future__ import annotations

import io
import os
import wave
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np


def read_wav(source: Union[str, bytes]) -> Tuple[np.ndarray, int]:
    """Decode a PCM WAV file → (float32 samples in [-1, 1] shaped
    (frames, channels), sample_rate)."""
    if isinstance(source, (bytes, bytearray)):
        f = wave.open(io.BytesIO(source), "rb")
    else:
        f = wave.open(source, "rb")
    with f:
        n = f.getnframes()
        ch = f.getnchannels()
        width = f.getsampwidth()
        rate = f.getframerate()
        raw = f.readframes(n)
    if width == 2:
        arr = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 1:  # unsigned 8-bit
        arr = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width == 4:
        arr = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    return arr.reshape(-1, ch), rate


def write_wav(path: str, samples: np.ndarray, rate: int) -> None:
    """float32 [-1, 1] (frames,) or (frames, channels) → 16-bit PCM WAV.
    2-D input is taken EXACTLY as (frames, channels) — no orientation
    guessing: a (1, C) array is one C-channel frame."""
    samples = np.asarray(samples, np.float32)
    if samples.ndim == 1:
        samples = samples.reshape(-1, 1)
    elif samples.ndim != 2:
        raise ValueError(f"samples must be 1-D or (frames, channels); "
                         f"got shape {samples.shape}")
    pcm = np.clip(samples, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype("<i2")
    with wave.open(path, "wb") as f:
        f.setnchannels(pcm.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(rate))
        f.writeframes(pcm.tobytes())


def spectrogram(samples: np.ndarray, *, frame_size: int = 256,
                overlap: float = 0.5, window: str = "hann",
                log_scale: bool = False) -> np.ndarray:
    """Magnitude spectrogram (audio/extension/Spectrogram.java analog):
    (frames, channels)|(frames,) samples → (time, frame_size // 2 + 1)."""
    x = np.asarray(samples, np.float32)
    if x.ndim == 2:
        x = x.mean(axis=1)  # downmix, as the reference fingerprinting does
    hop = max(1, int(frame_size * (1.0 - overlap)))
    if len(x) < frame_size:
        x = np.pad(x, (0, frame_size - len(x)))
    n_frames = 1 + (len(x) - frame_size) // hop
    win = (np.hanning(frame_size) if window == "hann"
           else np.ones(frame_size, np.float32))
    frames = np.stack([x[i * hop:i * hop + frame_size] * win
                       for i in range(n_frames)])
    mag = np.abs(np.fft.rfft(frames, axis=1)).astype(np.float32)
    return np.log1p(mag) if log_scale else mag


class WavFileRecordReader:
    """WavFileRecordReader.java: each WAV source becomes one record of raw
    samples — or spectrogram feature rows when ``features='spectrogram'``."""

    def __init__(self, features: str = "samples", frame_size: int = 256,
                 overlap: float = 0.5, log_scale: bool = True):
        if features not in ("samples", "spectrogram"):
            raise ValueError(f"unknown features mode {features!r}")
        self.features = features
        self.frame_size = frame_size
        self.overlap = overlap
        self.log_scale = log_scale

    def read_record(self, source) -> np.ndarray:
        samples, _rate = read_wav(source)
        if self.features == "samples":
            return samples.reshape(-1)
        return spectrogram(samples, frame_size=self.frame_size,
                           overlap=self.overlap, log_scale=self.log_scale)

    def read(self, sources: Union[str, bytes, Sequence]) -> List[np.ndarray]:
        """A directory of .wav files, a single path/bytes, or an explicit
        list of paths/bytes."""
        if isinstance(sources, str):
            if os.path.isdir(sources):
                sources = sorted(
                    os.path.join(sources, f) for f in os.listdir(sources)
                    if f.lower().endswith(".wav"))
            else:
                sources = [sources]  # single file path
        elif isinstance(sources, (bytes, bytearray)):
            sources = [sources]
        return [self.read_record(s) for s in sources]
