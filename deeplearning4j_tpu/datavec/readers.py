"""Record readers beyond CSV + the parallel transform executor.

Reference parity (datavec-api records/reader/impl/** and datavec-spark):
  * LineRecordReader.java — one record per line.
  * regex/RegexLineRecordReader.java — regex with capture groups → columns.
  * jackson/JacksonLineRecordReader.java — one JSON document per line,
    field-selected into columns.
  * misc/SVMLightRecordReader.java — sparse `label idx:val ...` rows.
  * csv/CSVSequenceRecordReader.java — one sequence (list of timesteps) per
    file / blank-line-separated block.
  * SparkTransformExecutor.java — cluster-parallel TransformProcess
    execution; here a fork-based multiprocess executor (the single-host
    analog — the reference's Spark local[N] mode).
"""

from __future__ import annotations

import csv
import io
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np


def _read_text(source: Union[str, io.TextIOBase]) -> str:
    if isinstance(source, str) and "\n" not in source and os.path.exists(source):
        with open(source) as f:
            return f.read()
    return source if isinstance(source, str) else source.read()


class LineRecordReader:
    """records/reader/impl/LineRecordReader.java: each line is a
    single-column record."""

    def __init__(self, skip_lines: int = 0):
        self.skip_lines = skip_lines

    def read(self, source) -> List[List[str]]:
        lines = _read_text(source).splitlines()
        return [[ln] for ln in lines[self.skip_lines:]]


class RegexLineRecordReader:
    """records/reader/impl/regex/RegexLineRecordReader.java: each line must
    match ``pattern``; capture groups become the record's columns."""

    def __init__(self, pattern: str, skip_lines: int = 0):
        self.pattern = re.compile(pattern)
        self.skip_lines = skip_lines

    def read(self, source) -> List[List[str]]:
        out = []
        for i, ln in enumerate(_read_text(source).splitlines()):
            if i < self.skip_lines or not ln:
                continue
            m = self.pattern.match(ln)
            if m is None:
                raise ValueError(
                    f"line {i} does not match pattern "
                    f"{self.pattern.pattern!r}: {ln!r}")
            out.append(list(m.groups()))
        return out


class JacksonLineRecordReader:
    """records/reader/impl/jackson/JacksonLineRecordReader.java: one JSON
    object per line; ``field_selection`` lists the keys (in order) to pull
    into columns — missing keys take the per-field default (None)."""

    def __init__(self, field_selection: Sequence[str],
                 defaults: Optional[Dict[str, Any]] = None):
        self.fields = list(field_selection)
        self.defaults = defaults or {}

    def read(self, source) -> List[List[Any]]:
        out = []
        for ln in _read_text(source).splitlines():
            if not ln.strip():
                continue
            doc = json.loads(ln)
            out.append([doc.get(f, self.defaults.get(f)) for f in self.fields])
        return out


class SVMLightRecordReader:
    """records/reader/impl/misc/SVMLightRecordReader.java: sparse
    ``label idx:val idx:val ...`` rows → dense feature vector + label.
    ``num_features`` fixes the dense width; ``zero_based`` controls whether
    indices start at 0 (default: 1-based, the SVMLight convention)."""

    def __init__(self, num_features: int, zero_based: bool = False):
        self.num_features = num_features
        self.zero_based = zero_based

    def read(self, source) -> List[List[float]]:
        out = []
        for ln in _read_text(source).splitlines():
            ln = ln.split("#")[0].strip()
            if not ln:
                continue
            parts = ln.split()
            label = float(parts[0])
            feats = np.zeros(self.num_features, np.float32)
            for tok in parts[1:]:
                idx, val = tok.split(":")
                j = int(idx) - (0 if self.zero_based else 1)
                if not 0 <= j < self.num_features:
                    raise ValueError(f"feature index {idx} out of range "
                                     f"for num_features={self.num_features}")
                feats[j] = float(val)
            out.append(list(feats) + [label])
        return out

    def read_dataset(self, source):
        """Dense (features, labels) arrays (the RecordReaderDataSetIterator
        shortcut for SVMLight sources)."""
        rows = self.read(source)
        arr = np.asarray(rows, np.float32)
        return arr[:, :-1], arr[:, -1]


class CSVSequenceRecordReader:
    """records/reader/impl/csv/CSVSequenceRecordReader.java: sequences of
    CSV timesteps — one sequence per file, or blank-line-separated blocks
    when reading a single source."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def read_sequence(self, source) -> List[List[str]]:
        rows = list(csv.reader(io.StringIO(_read_text(source)),
                               delimiter=self.delimiter))
        return [r for r in rows[self.skip_lines:] if r]

    def read(self, sources: Union[str, Iterable[Any]]) -> List[List[List[str]]]:
        if isinstance(sources, (list, tuple)):
            return [self.read_sequence(s) for s in sources]
        text = _read_text(sources)
        blocks = re.split(r"\n\s*\n", text.strip())
        return [self.read_sequence(b) for b in blocks if b.strip()]


# ---------------------------------------------------------------------------
# Parallel transform execution (datavec-spark SparkTransformExecutor role)
# ---------------------------------------------------------------------------

_FORK_TP = None  # set in the child via fork inheritance


def _run_chunk(chunk):
    return _FORK_TP.execute(chunk)


def _spawn_init():
    # keep spawned workers off the accelerator: they only run host-side
    # record transforms, and the TPU tunnel is single-client
    os.environ["JAX_PLATFORMS"] = "cpu"


def _run_chunk_spawn(args):
    tp, chunk = args
    return tp.execute(chunk)


class ParallelTransformExecutor:
    """SparkTransformExecutor.execute analog on one host: multiprocess map
    over contiguous record chunks (the reference's Spark local[N] mode).

    Start-method choice is a correctness matter, not a tuning knob:
      * fork is used only while the process is still single-threaded
        (before jax import) — forking a multi-threaded process can deadlock
        on locks held by jax/XLA background threads. Fork inheritance
        carries closure-based conditions/filters unchanged.
      * once jax is loaded, workers are spawned fresh (initializer pins
        them to CPU); the TransformProcess must then be picklable — every
        step/condition in the built-in DSL is. An unpicklable process
        (user lambdas) falls back to in-process execution.
    Small inputs always run inline — process spin-up dominates them."""

    def __init__(self, workers: int = 0, min_parallel: int = 512):
        self.workers = workers or (os.cpu_count() or 2)
        self.min_parallel = min_parallel

    def execute(self, records: List[List[Any]], tp) -> List[List[Any]]:
        import multiprocessing as mp
        import pickle
        import sys

        if (len(records) < self.min_parallel
                or not hasattr(os, "fork")):
            return tp.execute(records)
        n = min(self.workers, max(1, len(records) // 64))
        size = -(-len(records) // n)
        # CONTIGUOUS chunks: filters may drop records, so per-chunk result
        # lengths vary — concatenation in chunk order preserves the
        # reference's record order regardless
        chunks = [records[i * size:(i + 1) * size] for i in range(n)]
        if "jax" not in sys.modules:
            global _FORK_TP
            _FORK_TP = tp
            try:
                ctx = mp.get_context("fork")
                with ctx.Pool(n) as pool:
                    results = pool.map(_run_chunk, chunks)
            finally:
                _FORK_TP = None
        else:
            try:
                pickle.dumps(tp)
            except Exception:
                return tp.execute(records)  # closures: stay in-process
            ctx = mp.get_context("spawn")
            with ctx.Pool(n, initializer=_spawn_init) as pool:
                results = pool.map(_run_chunk_spawn,
                                   [(tp, c) for c in chunks])
        return [r for res in results for r in res]


class ExcelRecordReader:
    """datavec-excel ExcelRecordReader analog: .xlsx parsing with the
    stdlib only (an xlsx IS a zip of XML — no poi/openpyxl dependency).
    Reads the first worksheet (or ``sheet_index``) into rows of typed cells
    (numbers become float, shared/inline strings str, booleans bool)."""

    _NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"

    def __init__(self, sheet_index: int = 0, skip_rows: int = 0):
        self.sheet_index = sheet_index
        self.skip_rows = skip_rows

    def read(self, path: str) -> List[List[Any]]:
        import xml.etree.ElementTree as ET
        import zipfile

        ns = self._NS
        with zipfile.ZipFile(path) as z:
            shared: List[str] = []
            if "xl/sharedStrings.xml" in z.namelist():
                root = ET.fromstring(z.read("xl/sharedStrings.xml"))
                for si in root.findall(f"{ns}si"):
                    shared.append("".join(t.text or ""
                                          for t in si.iter(f"{ns}t")))
            import re as _re

            def _sheet_no(nm):
                m = _re.search(r"sheet(\d+)\.xml$", nm)
                return int(m.group(1)) if m else 0

            # numeric sort: lexicographic puts sheet10 before sheet2
            sheets = sorted((n for n in z.namelist()
                             if n.startswith("xl/worksheets/sheet")
                             and n.endswith(".xml")), key=_sheet_no)
            if self.sheet_index >= len(sheets):
                raise ValueError(
                    f"xlsx has {len(sheets)} sheets; index "
                    f"{self.sheet_index} out of range")
            root = ET.fromstring(z.read(sheets[self.sheet_index]))
        def _col_index(ref) -> Optional[int]:
            # "BC12" -> column 54 (0-based); writers omit EMPTY cells, so
            # alignment must come from the cell reference, not cell order
            if not ref:
                return None
            col = 0
            for ch in ref:
                if ch.isalpha():
                    col = col * 26 + (ord(ch.upper()) - ord("A") + 1)
                else:
                    break
            return col - 1 if col else None

        rows: List[List[Any]] = []
        for row in root.iter(f"{ns}row"):
            out: List[Any] = []
            for c in row.findall(f"{ns}c"):
                t = c.get("t", "n")
                v = c.find(f"{ns}v")
                if t == "inlineStr":
                    is_el = c.find(f"{ns}is")
                    val = ("".join(tt.text or ""
                                   for tt in is_el.iter(f"{ns}t"))
                           if is_el is not None else "")
                elif v is None:
                    val = None
                elif t == "s":
                    val = shared[int(v.text)]
                elif t == "b":
                    val = v.text == "1"
                else:
                    val = float(v.text)
                idx = _col_index(c.get("r"))
                if idx is None:
                    out.append(val)
                else:
                    while len(out) < idx:
                        out.append(None)  # omitted empty cells
                    if len(out) == idx:
                        out.append(val)
                    else:
                        out[idx] = val
            rows.append(out)
        return rows[self.skip_rows:]


class SQLRecordReader:
    """datavec-jdbc JDBCRecordReader analog over any DB-API 2.0 connection
    (sqlite3 in the stdlib plays the role of the JDBC driver): run a query,
    stream rows as records; ``schema()`` derives a datavec Schema from the
    cursor description + first row's types."""

    def __init__(self, connection, query: str):
        self.conn = connection
        self.query = query
        self._cache: Optional[List[List[Any]]] = None

    def read(self) -> List[List[Any]]:
        if self._cache is not None:
            return self._cache
        cur = self.conn.cursor()
        try:
            cur.execute(self.query)
            self._description = cur.description
            self._cache = [list(r) for r in cur.fetchall()]
            return self._cache
        finally:
            cur.close()

    def schema(self):
        from deeplearning4j_tpu.datavec.transform import Schema

        rows = self.read()
        b = Schema.Builder()
        names = [d[0] for d in (self._description or [])]
        first = rows[0] if rows else []
        for i, name in enumerate(names):
            v = first[i] if i < len(first) else None
            if isinstance(v, bool):
                b.add_column_categorical(name, "false", "true")
            elif isinstance(v, int):
                b.add_column_long(name)
            elif isinstance(v, float):
                b.add_column_double(name)
            else:
                b.add_column_string(name)
        return b.build()


def haversine_km(lat1, lon1, lat2, lon2) -> float:
    """Great-circle distance (datavec-geo CoordinatesDistanceTransform
    math)."""
    import math

    r = 6371.0088
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = math.radians(lat2 - lat1)
    dl = math.radians(lon2 - lon1)
    a = (math.sin(dp / 2) ** 2
         + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2)
    return 2 * r * math.asin(math.sqrt(a))
