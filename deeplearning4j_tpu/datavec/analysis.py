"""DataVec joins, sequence ops, and quality analysis.

Reference parity:
  * datavec-api transform/join/Join.java — Inner/LeftOuter/RightOuter/
    FullOuter joins of two record sets on key columns.
  * transform/sequence/** — ConvertToSequence (group by key, order by a
    column), ConvertFromSequence, sequence comparators.
  * analysis/AnalyzeLocal + DataQualityAnalysis / *QualityAnalysis —
    per-column counts of missing/invalid entries and min/max/mean/stddev
    for numeric columns.

TPU-native note: these are host-side ETL (the reference runs them on
Spark/local executors); numeric summaries vectorize through numpy. They
feed the same records → DataSet bridge the rest of datavec uses.
"""

from __future__ import annotations

import math
from collections import OrderedDict, defaultdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datavec.transform import Schema


class Join:
    """transform/join/Join.java analog (Builder: join type + key columns)."""

    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"

    def __init__(self, join_type: str, left_schema: Schema,
                 right_schema: Schema, key_columns: Sequence[str]):
        if join_type not in (self.INNER, self.LEFT_OUTER, self.RIGHT_OUTER,
                             self.FULL_OUTER):
            raise ValueError(f"unknown join type {join_type}")
        self.join_type = join_type
        self.left_schema = left_schema
        self.right_schema = right_schema
        self.key_columns = list(key_columns)

    def output_schema(self) -> Schema:
        cols = list(self.left_schema.columns)
        for c in self.right_schema.columns:
            if c["name"] not in self.key_columns:
                cols.append(c)
        return Schema(cols)

    def execute(self, left: List[List[Any]],
                right: List[List[Any]]) -> List[List[Any]]:
        lk = [self.left_schema.index_of(k) for k in self.key_columns]
        rk = [self.right_schema.index_of(k) for k in self.key_columns]
        r_other = [i for i in range(self.right_schema.num_columns())
                   if i not in rk]
        l_width = self.left_schema.num_columns()

        rmap: Dict[Tuple, List[List[Any]]] = defaultdict(list)
        for row in right:
            rmap[tuple(row[i] for i in rk)].append(row)

        out: List[List[Any]] = []
        matched_right = set()
        for row in left:
            key = tuple(row[i] for i in lk)
            matches = rmap.get(key, [])
            if matches:
                matched_right.add(key)
                for r in matches:
                    out.append(list(row) + [r[i] for i in r_other])
            elif self.join_type in (self.LEFT_OUTER, self.FULL_OUTER):
                out.append(list(row) + [None] * len(r_other))
        if self.join_type in (self.RIGHT_OUTER, self.FULL_OUTER):
            for key, rows in rmap.items():
                if key in matched_right:
                    continue
                for r in rows:
                    blank = [None] * l_width
                    for li, ri in zip(lk, rk):
                        blank[li] = r[ri]
                    out.append(blank + [r[i] for i in r_other])
        return out


# ---------------------------------------------------------------------------
# Sequences (transform/sequence/*)
# ---------------------------------------------------------------------------


def convert_to_sequence(records: List[List[Any]], schema: Schema,
                        key_column: str,
                        order_column: Optional[str] = None
                        ) -> List[List[List[Any]]]:
    """ConvertToSequence analog: group rows by key, order each group by the
    order column (e.g. a timestamp) — records → list of sequences."""
    ki = schema.index_of(key_column)
    oi = None if order_column is None else schema.index_of(order_column)
    groups: "OrderedDict[Any, List[List[Any]]]" = OrderedDict()
    for row in records:
        groups.setdefault(row[ki], []).append(row)
    out = []
    for rows in groups.values():
        if oi is not None:
            rows = sorted(rows, key=lambda r: r[oi])
        out.append(rows)
    return out


def convert_from_sequence(sequences: List[List[List[Any]]]) -> List[List[Any]]:
    """ConvertFromSequence analog: flatten sequences back to records."""
    return [row for seq in sequences for row in seq]


def sequence_to_dataset(sequences: List[List[List[Any]]], schema: Schema,
                        feature_columns: Sequence[str], label_column: str,
                        num_classes: int):
    """SequenceRecordReaderDataSetIterator bridging role: equal-length
    sequences → (features (N, T, F), one-hot labels per step (N, T, C))."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    fi = [schema.index_of(c) for c in feature_columns]
    li = schema.index_of(label_column)
    t = len(sequences[0])
    if any(len(s) != t for s in sequences):
        raise ValueError("sequence_to_dataset needs equal-length sequences — "
                         "pad or window upstream")
    feats = np.asarray([[[float(r[i]) for i in fi] for r in s]
                        for s in sequences], np.float32)
    labels = np.zeros((len(sequences), t, num_classes), np.float32)
    for n, s in enumerate(sequences):
        for ti, r in enumerate(s):
            labels[n, ti, int(r[li])] = 1.0
    return DataSet(feats, labels)


# ---------------------------------------------------------------------------
# Quality analysis (analysis/quality/* + AnalyzeLocal)
# ---------------------------------------------------------------------------


class ColumnQuality:
    """(Numeric|Categorical|String)Quality analog."""

    def __init__(self, name: str):
        self.name = name
        self.count_total = 0
        self.count_missing = 0
        self.count_invalid = 0

    def as_dict(self) -> Dict[str, int]:
        return {"total": self.count_total, "missing": self.count_missing,
                "invalid": self.count_invalid}


class DataQualityAnalysis:
    """DataQualityAnalysis analog: per-column quality counters."""

    def __init__(self, columns: List[ColumnQuality]):
        self.columns = {c.name: c for c in columns}

    def quality_of(self, name: str) -> ColumnQuality:
        return self.columns[name]

    def __repr__(self):
        rows = [f"  {n}: {c.as_dict()}" for n, c in self.columns.items()]
        return "DataQualityAnalysis(\n" + "\n".join(rows) + "\n)"


class DataAnalysis:
    """DataAnalysis analog: numeric column summaries."""

    def __init__(self, stats: Dict[str, Dict[str, float]]):
        self.stats = stats

    def min_of(self, name: str) -> float:
        return self.stats[name]["min"]

    def max_of(self, name: str) -> float:
        return self.stats[name]["max"]

    def mean_of(self, name: str) -> float:
        return self.stats[name]["mean"]

    def std_of(self, name: str) -> float:
        return self.stats[name]["std"]


def analyze_quality(records: List[List[Any]], schema: Schema
                    ) -> DataQualityAnalysis:
    """AnalyzeLocal.analyzeQuality analog."""
    cols = [ColumnQuality(n) for n in schema.names]
    for row in records:
        for i, col in enumerate(cols):
            col.count_total += 1
            v = row[i] if i < len(row) else None
            if v is None or (isinstance(v, str) and v == ""):
                col.count_missing += 1
                continue
            t = schema.columns[i]["type"]
            if t in ("integer", "long"):
                ok = isinstance(v, (int, np.integer)) or \
                    (isinstance(v, str) and v.lstrip("-").isdigit())
            elif t in ("double", "float"):
                try:
                    ok = math.isfinite(float(v))
                except (TypeError, ValueError):
                    ok = False
            else:
                ok = True
            if not ok:
                col.count_invalid += 1
    return DataQualityAnalysis(cols)


def analyze(records: List[List[Any]], schema: Schema) -> DataAnalysis:
    """AnalyzeLocal.analyze analog (numeric min/max/mean/std)."""
    stats: Dict[str, Dict[str, float]] = {}
    for i, c in enumerate(schema.columns):
        if c["type"] not in ("integer", "long", "double", "float"):
            continue
        vals = []
        for row in records:
            try:
                v = float(row[i])
            except (TypeError, ValueError, IndexError):
                continue
            if math.isfinite(v):
                vals.append(v)
        a = np.asarray(vals, np.float64)
        stats[c["name"]] = {
            "min": float(a.min()) if a.size else float("nan"),
            "max": float(a.max()) if a.size else float("nan"),
            "mean": float(a.mean()) if a.size else float("nan"),
            "std": float(a.std()) if a.size else float("nan"),
        }
    return DataAnalysis(stats)
