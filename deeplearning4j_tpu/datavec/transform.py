"""DataVec — schema'd ETL transform DSL.

Reference parity:
  * org/datavec/api/transform/schema/Schema.java (typed columns, Builder)
  * org/datavec/api/transform/TransformProcess.java (Builder chaining
    transforms/filters; executable), org/datavec/api/transform/transform/*
    (math ops, string ops, categorical↔integer/one-hot, remove/rename,
    deduplicate...), condition/* (column conditions, boolean compositions),
    filter/* (ConditionFilter), reduce/* (Reducer with per-column ops).
  * org/datavec/api/records/reader/impl/csv/CSVRecordReader.java,
    org/datavec/local/transforms/LocalTransformExecutor.java.

Records are Python lists of values (the Writable-list analog); execution is
host-side (ETL is host work feeding device batches, as in the reference where
DataVec runs on the JVM/Spark side).
"""

from __future__ import annotations

import csv
import io
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

# ---------------------------------------------------------------------------
# Schema
# ---------------------------------------------------------------------------

COLUMN_TYPES = ("string", "integer", "double", "categorical", "long", "time", "float")


class Schema:
    """Schema.java: ordered, typed columns."""

    def __init__(self, columns: List[Dict[str, Any]]):
        self.columns = columns

    @property
    def names(self) -> List[str]:
        return [c["name"] for c in self.columns]

    def type_of(self, name: str) -> str:
        return self._col(name)["type"]

    def _col(self, name: str) -> Dict[str, Any]:
        for c in self.columns:
            if c["name"] == name:
                return c
        raise KeyError(f"no column '{name}' in schema {self.names}")

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def num_columns(self) -> int:
        return len(self.columns)

    # Schema.toJson/fromJson parity
    def to_dict(self) -> Dict[str, Any]:
        return {"columns": [dict(c) for c in self.columns]}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Schema":
        return Schema([dict(c) for c in d["columns"]])

    def to_json(self) -> str:
        import json

        return json.dumps(self.to_dict())

    @staticmethod
    def from_json(s: str) -> "Schema":
        import json

        return Schema.from_dict(json.loads(s))

    class Builder:
        def __init__(self):
            self._cols: List[Dict[str, Any]] = []

        def add_column_string(self, name: str):
            self._cols.append({"name": name, "type": "string"})
            return self

        def add_column_integer(self, name: str):
            self._cols.append({"name": name, "type": "integer"})
            return self

        def add_column_long(self, name: str):
            self._cols.append({"name": name, "type": "long"})
            return self

        def add_column_double(self, name: str):
            self._cols.append({"name": name, "type": "double"})
            return self

        def add_column_float(self, name: str):
            self._cols.append({"name": name, "type": "float"})
            return self

        def add_column_categorical(self, name: str, *state_names: str):
            self._cols.append({"name": name, "type": "categorical",
                               "states": list(state_names)})
            return self

        def add_column_time(self, name: str):
            self._cols.append({"name": name, "type": "time"})
            return self

        def build(self) -> "Schema":
            return Schema(list(self._cols))

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()


# ---------------------------------------------------------------------------
# Conditions (condition/column/*) — predicates over one record
# ---------------------------------------------------------------------------


class Condition:
    def check(self, record: List[Any], schema: Schema) -> bool:
        raise NotImplementedError

    def __and__(self, other):
        return BooleanCondition("and", self, other)

    def __or__(self, other):
        return BooleanCondition("or", self, other)

    def __invert__(self):
        return BooleanCondition("not", self)


class BooleanCondition(Condition):
    """condition/BooleanCondition.java: AND/OR/NOT composition."""

    def __init__(self, op: str, *conds: Condition):
        self.op = op
        self.conds = conds

    def check(self, record, schema):
        if self.op == "and":
            return all(c.check(record, schema) for c in self.conds)
        if self.op == "or":
            return any(c.check(record, schema) for c in self.conds)
        return not self.conds[0].check(record, schema)


_OPS: Dict[str, Callable[[Any, Any], bool]] = {
    "Equal": lambda a, b: a == b,
    "NotEqual": lambda a, b: a != b,
    "LessThan": lambda a, b: a < b,
    "LessOrEqual": lambda a, b: a <= b,
    "GreaterThan": lambda a, b: a > b,
    "GreaterOrEqual": lambda a, b: a >= b,
    "InSet": lambda a, b: a in b,
    "NotInSet": lambda a, b: a not in b,
}


class ColumnCondition(Condition):
    """DoubleColumnCondition / StringColumnCondition / etc. in one."""

    def __init__(self, column: str, op: str, value: Any):
        self.column = column
        self.op = op
        self.value = value

    def check(self, record, schema):
        v = record[schema.index_of(self.column)]
        return _OPS[self.op](v, self.value)


class NullWritableColumnCondition(Condition):
    def __init__(self, column: str):
        self.column = column

    def check(self, record, schema):
        v = record[schema.index_of(self.column)]
        return v is None or v == ""


# ---------------------------------------------------------------------------
# Transform steps
# ---------------------------------------------------------------------------


class _Step:
    """One step: transforms schema and/or records."""

    def out_schema(self, schema: Schema) -> Schema:
        return schema

    def apply(self, records: List[List[Any]], schema: Schema) -> List[List[Any]]:
        return records


class _RemoveColumns(_Step):
    def __init__(self, names):
        self.names = set(names)

    def out_schema(self, schema):
        return Schema([c for c in schema.columns if c["name"] not in self.names])

    def apply(self, records, schema):
        keep = [i for i, n in enumerate(schema.names) if n not in self.names]
        return [[r[i] for i in keep] for r in records]


class _KeepColumns(_Step):
    def __init__(self, names):
        self.names = list(names)

    def out_schema(self, schema):
        return Schema([schema._col(n) for n in self.names])

    def apply(self, records, schema):
        idx = [schema.index_of(n) for n in self.names]
        return [[r[i] for i in idx] for r in records]


class _RenameColumn(_Step):
    def __init__(self, old, new):
        self.old, self.new = old, new

    def out_schema(self, schema):
        cols = [dict(c) for c in schema.columns]
        for c in cols:
            if c["name"] == self.old:
                c["name"] = self.new
        return Schema(cols)


class _MathOp(_Step):
    """transform/doubletransform/DoubleMathOpTransform + integer variant."""

    _FNS = {"Add": lambda a, b: a + b, "Subtract": lambda a, b: a - b,
            "Multiply": lambda a, b: a * b, "Divide": lambda a, b: a / b,
            "Modulus": lambda a, b: a % b, "ReverseSubtract": lambda a, b: b - a,
            "ReverseDivide": lambda a, b: b / a, "ScalarMax": max, "ScalarMin": min}

    def __init__(self, column, op, scalar):
        self.column, self.op, self.scalar = column, op, scalar

    def apply(self, records, schema):
        i = schema.index_of(self.column)
        fn = self._FNS[self.op]
        out = []
        for r in records:
            r = list(r)
            r[i] = fn(r[i], self.scalar)
            out.append(r)
        return out


class _MathFunction(_Step):
    """DoubleMathFunctionTransform: log/sqrt/sin/abs/..."""

    _FNS = {"LOG": math.log, "LOG10": math.log10, "EXP": math.exp,
            "SQRT": math.sqrt, "ABS": abs, "SIN": math.sin, "COS": math.cos,
            "TAN": math.tan, "FLOOR": math.floor, "CEIL": math.ceil,
            "SIGNUM": lambda v: (v > 0) - (v < 0)}

    def __init__(self, column, fn):
        self.column, self.fn = column, fn

    def apply(self, records, schema):
        i = schema.index_of(self.column)
        f = self._FNS[self.fn.upper()]
        out = []
        for r in records:
            r = list(r)
            r[i] = f(r[i])
            out.append(r)
        return out


class _StringTransform(_Step):
    """stringtransform/*: lower/upper/trim/replace/append/concat."""

    def __init__(self, column, kind, *args):
        self.column, self.kind, self.args = column, kind, args

    def apply(self, records, schema):
        i = schema.index_of(self.column)
        out = []
        for r in records:
            r = list(r)
            v = str(r[i])
            if self.kind == "lower":
                v = v.lower()
            elif self.kind == "upper":
                v = v.upper()
            elif self.kind == "trim":
                v = v.strip()
            elif self.kind == "replace":
                v = v.replace(self.args[0], self.args[1])
            elif self.kind == "append":
                v = v + self.args[0]
            r[i] = v
            out.append(r)
        return out


class _CategoricalToInteger(_Step):
    def __init__(self, column):
        self.column = column

    def out_schema(self, schema):
        cols = [dict(c) for c in schema.columns]
        for c in cols:
            if c["name"] == self.column:
                self._states = c.get("states", [])
                c["type"] = "integer"
                c.pop("states", None)
        return Schema(cols)

    def apply(self, records, schema):
        i = schema.index_of(self.column)
        states = schema._col(self.column).get("states", [])
        lut = {s: j for j, s in enumerate(states)}
        out = []
        for r in records:
            r = list(r)
            r[i] = lut[r[i]]
            out.append(r)
        return out


class _CategoricalToOneHot(_Step):
    def __init__(self, column):
        self.column = column

    def out_schema(self, schema):
        cols = []
        for c in schema.columns:
            if c["name"] == self.column:
                for s in c.get("states", []):
                    cols.append({"name": f"{self.column}[{s}]", "type": "integer"})
            else:
                cols.append(dict(c))
        return Schema(cols)

    def apply(self, records, schema):
        i = schema.index_of(self.column)
        states = schema._col(self.column).get("states", [])
        out = []
        for r in records:
            onehot = [1 if r[i] == s else 0 for s in states]
            out.append(r[:i] + onehot + r[i + 1 :])
        return out


class _IntegerToCategorical(_Step):
    def __init__(self, column, states):
        self.column, self.states = column, list(states)

    def out_schema(self, schema):
        cols = [dict(c) for c in schema.columns]
        for c in cols:
            if c["name"] == self.column:
                c["type"] = "categorical"
                c["states"] = self.states
        return Schema(cols)

    def apply(self, records, schema):
        i = schema.index_of(self.column)
        out = []
        for r in records:
            r = list(r)
            r[i] = self.states[int(r[i])]
            out.append(r)
        return out


class _ConditionalReplace(_Step):
    """transform/condition/ConditionalReplaceValueTransform."""

    def __init__(self, column, new_value, condition: Condition):
        self.column, self.new_value, self.condition = column, new_value, condition

    def apply(self, records, schema):
        i = schema.index_of(self.column)
        out = []
        for r in records:
            r = list(r)
            if self.condition.check(r, schema):
                r[i] = self.new_value
            out.append(r)
        return out


class _Filter(_Step):
    """filter/ConditionFilter: REMOVE records matching the condition."""

    def __init__(self, condition: Condition):
        self.condition = condition

    def apply(self, records, schema):
        return [r for r in records if not self.condition.check(r, schema)]


class _DuplicateColumns(_Step):
    def __init__(self, names, new_names):
        self.names, self.new_names = list(names), list(new_names)

    def out_schema(self, schema):
        cols = [dict(c) for c in schema.columns]
        for n, nn in zip(self.names, self.new_names):
            c = dict(schema._col(n))
            c["name"] = nn
            cols.append(c)
        return Schema(cols)

    def apply(self, records, schema):
        idx = [schema.index_of(n) for n in self.names]
        return [r + [r[i] for i in idx] for r in records]


# ---------------------------------------------------------------------------
# Reductions (reduce/Reducer.java)
# ---------------------------------------------------------------------------

_REDUCE_FNS = {
    "SUM": lambda vs: sum(vs),
    "MEAN": lambda vs: sum(vs) / len(vs),
    "MIN": min,
    "MAX": max,
    "COUNT": len,
    "RANGE": lambda vs: max(vs) - min(vs),
    "STDEV": lambda vs: float(np.std(np.asarray(vs, float), ddof=1)) if len(vs) > 1 else 0.0,
    "FIRST": lambda vs: vs[0],
    "LAST": lambda vs: vs[-1],
    "COUNT_UNIQUE": lambda vs: len(set(vs)),
}


class Reducer:
    """Reducer.Builder: group by key column(s), reduce others."""

    def __init__(self, key_columns: Sequence[str], ops: Dict[str, str]):
        self.keys = list(key_columns)
        self.ops = ops  # column -> op name

    def reduce(self, records: List[List[Any]], schema: Schema):
        key_idx = [schema.index_of(k) for k in self.keys]
        groups: Dict[tuple, List[List[Any]]] = {}
        for r in records:
            groups.setdefault(tuple(r[i] for i in key_idx), []).append(r)
        out_cols = [dict(schema._col(k)) for k in self.keys]
        for col, op in self.ops.items():
            t = "double" if op in ("MEAN", "STDEV") else schema.type_of(col)
            out_cols.append({"name": f"{op.lower()}({col})", "type": t})
        out_schema = Schema(out_cols)
        out_records = []
        for key, rows in groups.items():
            rec = list(key)
            for col, op in self.ops.items():
                i = schema.index_of(col)
                rec.append(_REDUCE_FNS[op]([r[i] for r in rows]))
            out_records.append(rec)
        return out_records, out_schema


# ---------------------------------------------------------------------------
# TransformProcess
# ---------------------------------------------------------------------------


class TransformProcess:
    """TransformProcess.java: initial schema + ordered steps."""

    def __init__(self, initial_schema: Schema, steps: List[_Step]):
        self.initial_schema = initial_schema
        self.steps = steps

    def final_schema(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.out_schema(s)
        return s

    def execute(self, records: List[List[Any]]) -> List[List[Any]]:
        s = self.initial_schema
        for st in self.steps:
            records = st.apply(records, s)
            s = st.out_schema(s)
        return records

    class Builder:
        def __init__(self, schema: Schema):
            self.schema = schema
            self.steps: List[_Step] = []

        def remove_columns(self, *names):
            self.steps.append(_RemoveColumns(names))
            return self

        def remove_all_columns_except_for(self, *names):
            self.steps.append(_KeepColumns(names))
            return self

        def rename_column(self, old, new):
            self.steps.append(_RenameColumn(old, new))
            return self

        def math_op(self, column, op, scalar):
            self.steps.append(_MathOp(column, op, scalar))
            return self

        def math_function(self, column, fn):
            self.steps.append(_MathFunction(column, fn))
            return self

        def string_to_lower(self, column):
            self.steps.append(_StringTransform(column, "lower"))
            return self

        def string_to_upper(self, column):
            self.steps.append(_StringTransform(column, "upper"))
            return self

        def trim(self, column):
            self.steps.append(_StringTransform(column, "trim"))
            return self

        def replace_string(self, column, old, new):
            self.steps.append(_StringTransform(column, "replace", old, new))
            return self

        def categorical_to_integer(self, column):
            self.steps.append(_CategoricalToInteger(column))
            return self

        def categorical_to_one_hot(self, column):
            self.steps.append(_CategoricalToOneHot(column))
            return self

        def integer_to_categorical(self, column, states):
            self.steps.append(_IntegerToCategorical(column, states))
            return self

        def conditional_replace_value_transform(self, column, new_value, condition):
            self.steps.append(_ConditionalReplace(column, new_value, condition))
            return self

        def filter(self, condition: Condition):
            self.steps.append(_Filter(condition))
            return self

        def duplicate_columns(self, names, new_names):
            self.steps.append(_DuplicateColumns(names, new_names))
            return self

        def build(self) -> "TransformProcess":
            return TransformProcess(self.schema, list(self.steps))

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)


class LocalTransformExecutor:
    """datavec-local LocalTransformExecutor.execute analog."""

    @staticmethod
    def execute(records: List[List[Any]], tp: TransformProcess) -> List[List[Any]]:
        return tp.execute(records)


# ---------------------------------------------------------------------------
# Record readers (records/reader/impl/*)
# ---------------------------------------------------------------------------


class CSVRecordReader:
    """CSVRecordReader.java: parse CSV into typed records per a Schema
    (types coerced if a schema is given, else strings)."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ",",
                 schema: Optional[Schema] = None):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.schema = schema

    def _coerce(self, row: List[str]) -> List[Any]:
        if self.schema is None:
            return row
        out = []
        for v, c in zip(row, self.schema.columns):
            t = c["type"]
            if t in ("integer", "long"):
                out.append(int(v))
            elif t in ("double", "float"):
                out.append(float(v))
            else:
                out.append(v)
        return out

    def read_matrix(self, source: Union[str, "io.TextIOBase"],
                    cols: int) -> "np.ndarray":
        """All-numeric fast path: CSV → (rows, cols) float32 with NaN for
        non-numeric cells, through the NATIVE loader when built
        (native/record_loader.cpp — the reference's native record-reader
        role); numpy fallback otherwise."""
        from deeplearning4j_tpu.native_ops.record_loader import (
            csv_to_float_matrix)

        if isinstance(source, str) and "\n" not in source:
            with open(source) as f:
                text = f.read()
        else:
            text = source if isinstance(source, str) else source.read()
        return csv_to_float_matrix(text, cols, delimiter=self.delimiter,
                                   skip_rows=self.skip_lines)

    def read(self, source: Union[str, io.TextIOBase]) -> List[List[Any]]:
        if isinstance(source, str) and "\n" not in source:
            with open(source, newline="") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter))
        else:
            text = source if isinstance(source, str) else source.read()
            rows = list(csv.reader(io.StringIO(text), delimiter=self.delimiter))
        rows = rows[self.skip_lines :]
        return [self._coerce(r) for r in rows if r]


def records_to_dataset(records: List[List[Any]], schema: Schema,
                       label_column: str, num_classes: Optional[int] = None):
    """RecordReaderDataSetIterator bridging role: records → DataSet."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    li = schema.index_of(label_column)
    feats, labels = [], []
    for r in records:
        feats.append([float(v) for i, v in enumerate(r) if i != li])
        labels.append(r[li])
    x = np.asarray(feats, np.float32)
    if num_classes:
        y = np.zeros((len(labels), num_classes), np.float32)
        y[np.arange(len(labels)), [int(l) for l in labels]] = 1.0
    else:
        y = np.asarray(labels, np.float32).reshape(-1, 1)
    return DataSet(x, y)
