"""DataVec — ETL: schemas, transform DSL, record readers (SURVEY §3.4)."""

from deeplearning4j_tpu.datavec.transform import (
    Schema,
    TransformProcess,
    LocalTransformExecutor,
    CSVRecordReader,
    Condition,
    ColumnCondition,
    BooleanCondition,
    NullWritableColumnCondition,
    Reducer,
    records_to_dataset,
)
