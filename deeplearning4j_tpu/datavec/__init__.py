"""DataVec — ETL: schemas, transform DSL, record readers (SURVEY §3.4)."""

from deeplearning4j_tpu.datavec.transform import (
    Schema,
    TransformProcess,
    LocalTransformExecutor,
    CSVRecordReader,
    Condition,
    ColumnCondition,
    BooleanCondition,
    NullWritableColumnCondition,
    Reducer,
    records_to_dataset,
)
from deeplearning4j_tpu.datavec.readers import (
    LineRecordReader,
    RegexLineRecordReader,
    JacksonLineRecordReader,
    SVMLightRecordReader,
    CSVSequenceRecordReader,
    ParallelTransformExecutor,
)
from deeplearning4j_tpu.datavec.audio import (
    WavFileRecordReader,
    read_wav,
    write_wav,
    spectrogram,
)
from deeplearning4j_tpu.datavec.columnar import (
    ColumnarBatch,
    to_columnar,
    save_columnar,
    load_columnar,
)
from deeplearning4j_tpu.datavec.analysis import (
    Join,
    convert_to_sequence,
    convert_from_sequence,
    sequence_to_dataset,
    DataQualityAnalysis,
    DataAnalysis,
    analyze,
    analyze_quality,
)
