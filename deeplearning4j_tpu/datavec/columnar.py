"""Columnar batch interchange — the datavec-arrow ArrowConverter ROLE.

Reference parity: datavec-arrow ArrowConverter.java converts records ↔
columnar Arrow batches and persists them so downstream systems read columns
zero-copy. This module fulfils the same role for the TPU build: records ↔
a column-major numpy batch with a compact persisted form.

DIVERGENCE (documented, not hidden): the on-disk format is NOT Arrow IPC —
producing real Arrow files without the pyarrow/Arrow C++ stack would mean
reimplementing flatbuffers framing for no consumer in this environment.
The format here is `npz` (numpy's standard container), readable by any
numpy — the interchange property the reference actually uses Arrow for.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.datavec.transform import Schema

_COL_DTYPES = {
    "integer": np.int32, "long": np.int64, "double": np.float64,
    "float": np.float32, "string": object, "categorical": object,
    "boolean": np.bool_, "time": np.int64,
}


class ColumnarBatch:
    """Column-major record batch (ArrowWritableRecordBatch analog):
    one numpy array per column, zero-copy column access."""

    def __init__(self, schema: Schema, columns: Dict[str, np.ndarray]):
        self.schema = schema
        self.columns = columns
        sizes = {len(v) for v in columns.values()}
        if len(sizes) > 1:
            raise ValueError(f"ragged columns: {sizes}")
        self.num_rows = sizes.pop() if sizes else 0

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def to_records(self) -> List[List[Any]]:
        names = [c["name"] for c in self.schema.columns]
        cols = [self.columns[n] for n in names]
        return [[c[i].item() if hasattr(c[i], "item") else c[i]
                 for c in cols] for i in range(self.num_rows)]

    def to_matrix(self) -> np.ndarray:
        """All-numeric columns → (rows, cols) float32 matrix (the
        RecordReaderDataSetIterator bridge)."""
        names = [c["name"] for c in self.schema.columns]
        return np.stack([np.asarray(self.columns[n], np.float32)
                         for n in names], axis=1)


def to_columnar(records: List[List[Any]], schema: Schema) -> ColumnarBatch:
    """ArrowConverter.toArrow analog: row records → ColumnarBatch."""
    names = [c["name"] for c in schema.columns]
    types = [c["type"] for c in schema.columns]
    cols = {}
    for j, (name, t) in enumerate(zip(names, types)):
        dt = _COL_DTYPES.get(t, object)
        cols[name] = np.asarray([r[j] for r in records], dtype=dt)
    return ColumnarBatch(schema, cols)


def save_columnar(batch: ColumnarBatch, path: str) -> None:
    """Persist (ArrowConverter write analog; npz container, see module
    docstring for the format divergence)."""
    meta = json.dumps(batch.schema.to_dict())
    arrays = {f"col_{k}": (v.astype("U") if v.dtype == object else v)
              for k, v in batch.columns.items()}
    np.savez(path, __schema__=np.asarray(meta), **arrays)


def load_columnar(path: str) -> ColumnarBatch:
    with np.load(path if path.endswith(".npz") else path + ".npz",
                 allow_pickle=False) as z:
        schema = Schema.from_dict(json.loads(str(z["__schema__"])))
        cols = {}
        for c in schema.columns:
            arr = z[f"col_{c['name']}"]
            if arr.dtype.kind == "U" and _COL_DTYPES.get(c["type"]) is object:
                arr = arr.astype(object)
            cols[c["name"]] = arr
    return ColumnarBatch(schema, cols)
