"""Image data pipeline — datavec-data-image parity.

Reference parity:
  * org/datavec/image/recordreader/ImageRecordReader.java +
    loader/NativeImageLoader.java (OpenCV decode, resize, NCHW floats) — the
    ImageNet input path.
  * org/datavec/image/transform/*Transform.java — augmentation chain
    (Crop/Flip/Rotate/Warp/ColorConversion/PipelineImageTransform with
    per-transform probabilities).

TPU-native realization: host-side numpy pipeline feeding NHWC float batches
(decode via PIL if available — OpenCV jars are a JVM artifact). Augmentations
are pure-numpy (cheap vs the device step; runs while the chip computes thanks
to AsyncDataSetIterator prefetch). A deterministic synthetic-ImageNet
generator stands in for the offline-unavailable dataset (SURVEY §8.3 #6).
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator


# ---------------------------------------------------------------------------
# Image transforms (datavec ImageTransform chain)
# ---------------------------------------------------------------------------


class ImageTransform:
    """Base transform: (H, W, C) float image -> image. Seeded per call."""

    def __call__(self, img: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
        raise NotImplementedError


class FlipImageTransform(ImageTransform):
    """FlipImageTransform.java: horizontal flip."""

    def __call__(self, img, rng):
        return img[:, ::-1]


class RandomCropTransform(ImageTransform):
    """CropImageTransform.java: random crop to (h, w), pad if needed."""

    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def __call__(self, img, rng):
        H, W = img.shape[:2]
        if H < self.h or W < self.w:
            ph, pw = max(0, self.h - H), max(0, self.w - W)
            img = np.pad(img, ((0, ph), (0, pw), (0, 0)))
            H, W = img.shape[:2]
        y = rng.randint(0, H - self.h + 1)
        x = rng.randint(0, W - self.w + 1)
        return img[y : y + self.h, x : x + self.w]


class RotateImageTransform(ImageTransform):
    """RotateImageTransform.java: right-angle rotations (arbitrary-angle
    warps need cv2; right angles cover the augmentation role losslessly)."""

    def __init__(self, quarters: Sequence[int] = (0, 1, 2, 3)):
        self.quarters = list(quarters)

    def __call__(self, img, rng):
        k = self.quarters[rng.randint(len(self.quarters))]
        return np.rot90(img, k=k, axes=(0, 1)).copy()


class ColorJitterTransform(ImageTransform):
    """ColorConversionTransform-role: brightness/contrast jitter."""

    def __init__(self, brightness: float = 0.2, contrast: float = 0.2):
        self.brightness = brightness
        self.contrast = contrast

    def __call__(self, img, rng):
        b = 1.0 + self.brightness * (2 * rng.rand() - 1)
        c = 1.0 + self.contrast * (2 * rng.rand() - 1)
        mean = img.mean()
        return np.clip((img - mean) * c + mean * b, 0.0, 1.0)


class PipelineImageTransform(ImageTransform):
    """PipelineImageTransform.java: chain with per-stage probabilities."""

    def __init__(self, stages: Sequence[Tuple[ImageTransform, float]]):
        self.stages = list(stages)

    def __call__(self, img, rng):
        for t, prob in self.stages:
            if rng.rand() < prob:
                img = t(img, rng)
        return img


# ---------------------------------------------------------------------------
# File-based reader (ImageRecordReader) — used when real images exist on disk
# ---------------------------------------------------------------------------

_EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".gif"}


def _load_image(path: str, height: int, width: int) -> np.ndarray:
    try:
        from PIL import Image  # pillow, if present in the env
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("PIL unavailable; file-based images unsupported") from e
    img = Image.open(path).convert("RGB").resize((width, height))
    return np.asarray(img, np.float32) / 255.0


class ImageRecordReader(DataSetIterator):
    """ImageRecordReader.java analog: label = parent directory name."""

    def __init__(self, root: str, height: int, width: int, batch_size: int = 32,
                 transform: Optional[ImageTransform] = None, seed: int = 0):
        self.root = root
        self.h, self.w = height, width
        self._bs = batch_size
        self.transform = transform
        self.seed = seed
        self.files: List[Tuple[str, int]] = []
        self.labels: List[str] = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        for li, lab in enumerate(self.labels):
            d = os.path.join(root, lab)
            for f in sorted(os.listdir(d)):
                if os.path.splitext(f)[1].lower() in _EXTS:
                    self.files.append((os.path.join(d, f), li))
        self._epoch = 0

    def shard_files(self, process_id: int = None, num_processes: int = None
                    ) -> "ImageRecordReader":
        """FILE-level per-host sharding (parallel.launch.host_shard wiring):
        this host keeps files[pid::N] and iterates ONLY those — per-host ETL
        is O(global/N), unlike batch round-robin which decodes everything on
        every host (SURVEY §6.8 per-host shard assignment)."""
        from deeplearning4j_tpu.parallel.launch import host_shard

        self.files = host_shard(self.files, process_id, num_processes)
        return self

    @property
    def batch_size(self):
        return self._bs

    def __iter__(self):
        rng = np.random.RandomState(self.seed + self._epoch)
        self._epoch += 1
        order = rng.permutation(len(self.files))
        n_classes = len(self.labels)
        for i in range(0, len(order), self._bs):
            idx = order[i : i + self._bs]
            imgs, labs = [], []
            for j in idx:
                path, li = self.files[j]
                img = _load_image(path, self.h, self.w)
                if self.transform is not None:
                    img = self.transform(img, rng)
                imgs.append(img)
                labs.append(li)
            y = np.zeros((len(labs), n_classes), np.float32)
            y[np.arange(len(labs)), labs] = 1.0
            yield self._maybe_pre(DataSet(np.stack(imgs), y))


# ---------------------------------------------------------------------------
# Synthetic ImageNet-shaped data (offline stand-in; SURVEY §8.3 #6)
# ---------------------------------------------------------------------------


def synthetic_image_batch(batch: int, height: int, width: int, channels: int,
                          num_classes: int, seed: int,
                          proto_seed: int = 4242) -> Tuple[np.ndarray, np.ndarray]:
    """Class-conditional random-frequency textures: learnable, deterministic."""
    prng = np.random.RandomState(proto_seed)
    freqs = prng.rand(num_classes, channels, 4) * 0.3 + 0.05  # per-class freq signature
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, batch)
    yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
    imgs = np.empty((batch, height, width, channels), np.float32)
    for i, lab in enumerate(labels):
        phase = rng.rand(channels, 2) * 6.28
        for c in range(channels):
            fy, fx, fy2, fx2 = freqs[lab, c]
            img = (np.sin(fy * yy + phase[c, 0]) * np.cos(fx * xx + phase[c, 1])
                   + 0.5 * np.sin(fy2 * yy + fx2 * xx))
            imgs[i, :, :, c] = img
    imgs = (imgs - imgs.min()) / max(imgs.max() - imgs.min(), 1e-6)
    imgs += 0.05 * rng.rand(*imgs.shape).astype(np.float32)
    return imgs.astype(np.float32), labels


class SyntheticImageNetIterator(DataSetIterator):
    """ImageNet-shaped iterator for throughput + convergence work when no
    real dataset exists on disk."""

    def __init__(self, batch_size: int = 32, height: int = 224, width: int = 224,
                 channels: int = 3, num_classes: int = 1000,
                 batches_per_epoch: int = 10, seed: int = 0):
        self._bs = batch_size
        self.h, self.w, self.c = height, width, channels
        self.num_classes = num_classes
        self.batches_per_epoch = batches_per_epoch
        self.seed = seed
        self._epoch = 0

    @property
    def batch_size(self):
        return self._bs

    def __iter__(self):
        base = self.seed + 100003 * self._epoch
        self._epoch += 1
        for b in range(self.batches_per_epoch):
            imgs, labels = synthetic_image_batch(
                self._bs, self.h, self.w, self.c, self.num_classes, base + b)
            y = np.zeros((self._bs, self.num_classes), np.float32)
            y[np.arange(self._bs), labels] = 1.0
            yield self._maybe_pre(DataSet(imgs, y))
