"""DataSet + iterators + normalizers — the ND4J dataset package analog.

Reference parity:
  * org/nd4j/linalg/dataset/DataSet.java — features/labels (+ per-example
    masks for sequence data), batching, shuffling, splitting.
  * org/nd4j/linalg/dataset/api/iterator/DataSetIterator.java and impls
    (ListDataSetIterator, ExistingDataSetIterator, IteratorDataSetIterator);
    AsyncDataSetIterator (prefetch thread) — on TPU the async-prefetch role is
    played by dispatching device puts ahead of compute; a thread-based
    prefetcher is still provided for host-side pipelines.
  * Normalizers: NormalizerStandardize, NormalizerMinMaxScaler,
    ImagePreProcessingScaler (org/nd4j/linalg/dataset/api/preprocessor/*).

Host-side data stays numpy; device transfer happens at the jit boundary.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np


class DataSet:
    """features/labels (+ masks) minibatch container (DataSet.java)."""

    def __init__(self, features, labels=None, features_mask=None, labels_mask=None):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        def cut(a, lo, hi):
            return None if a is None else a[lo:hi]

        n = self.num_examples()
        return (
            DataSet(self.features[:n_train], cut(self.labels, 0, n_train),
                    cut(self.features_mask, 0, n_train), cut(self.labels_mask, 0, n_train)),
            DataSet(self.features[n_train:], cut(self.labels, n_train, n),
                    cut(self.features_mask, n_train, n), cut(self.labels_mask, n_train, n)),
        )

    def shuffle(self, seed: Optional[int] = None) -> None:
        rng = np.random.RandomState(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        out = []
        for i in range(0, self.num_examples(), batch_size):
            j = i + batch_size

            def cut(a):
                return None if a is None else a[i:j]

            out.append(DataSet(self.features[i:j], cut(self.labels),
                               cut(self.features_mask), cut(self.labels_mask)))
        return out

    @staticmethod
    def merge(sets: Sequence["DataSet"]) -> "DataSet":
        def cat(parts):
            if any(p is None for p in parts):
                return None
            return np.concatenate(parts, axis=0)

        return DataSet(
            np.concatenate([d.features for d in sets], axis=0),
            cat([d.labels for d in sets]),
            cat([d.features_mask for d in sets]),
            cat([d.labels_mask for d in sets]),
        )


class DataSetIterator:
    """DataSetIterator.java analog: resettable iterator over DataSet batches."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    @property
    def batch_size(self) -> int:
        raise NotImplementedError

    def set_pre_processor(self, pre) -> None:
        self._pre = pre

    def _maybe_pre(self, ds: DataSet) -> DataSet:
        pre = getattr(self, "_pre", None)
        if pre is not None:
            pre.transform(ds)
        return ds


class ListDataSetIterator(DataSetIterator):
    """ListDataSetIterator.java: iterate a list (or one big DataSet) in batches."""

    def __init__(self, data, batch_size: int = 32, shuffle: bool = False, seed: int = 0):
        if isinstance(data, DataSet):
            self._all = data
            self._batches = None
        else:
            self._all = None
            self._batches = list(data)
        self._bs = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0

    @property
    def batch_size(self) -> int:
        return self._bs

    def __iter__(self):
        if self._all is not None:
            ds = self._all
            if self._shuffle:
                ds = DataSet(ds.features, ds.labels, ds.features_mask, ds.labels_mask)
                ds.shuffle(self._seed + self._epoch)
            self._epoch += 1
            for b in ds.batch_by(self._bs):
                yield self._maybe_pre(b)
        else:
            for b in self._batches:
                yield self._maybe_pre(b)


class AsyncDataSetIterator(DataSetIterator):
    """AsyncDataSetIterator.java: background-thread prefetch of N batches."""

    def __init__(self, base: DataSetIterator, prefetch: int = 2):
        self._base = base
        self._prefetch = prefetch

    @property
    def batch_size(self) -> int:
        return self._base.batch_size

    def reset(self):
        self._base.reset()

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._prefetch)
        DONE = object()

        def worker():
            try:
                for item in self._base:
                    q.put(item)
                q.put(DONE)
            except BaseException as e:  # propagate to the consumer
                q.put(e)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # DONE / the exception is the worker's last put, so on normal
            # exits this join is instant; if the consumer abandons the
            # generator mid-epoch the worker may be blocked on a full
            # queue — daemon=True plus the bounded join keeps close()
            # from hanging on it
            t.join(timeout=1.0)


# ---------------------------------------------------------------------------
# Normalizers (api/preprocessor/*)
# ---------------------------------------------------------------------------


class NormalizerStandardize:
    """NormalizerStandardize.java: per-feature z-score from fitted stats."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, data) -> None:
        feats = data.features if isinstance(data, DataSet) else DataSet.merge(list(data)).features
        # feature/channel axis is LAST in our layout (NHWC / (N,T,F) / (N,F))
        axes = tuple(range(feats.ndim - 1))
        self.mean = feats.mean(axis=axes)
        self.std = feats.std(axis=axes) + 1e-8

    def transform(self, ds: DataSet) -> None:
        if (getattr(ds.features, "dtype", None) == np.uint8
                and np.ndim(self.mean) == 1
                and ds.features.shape[-1] == np.shape(self.mean)[0]):
            from deeplearning4j_tpu.native_ops.pixops import u8_standardize

            ds.features = u8_standardize(ds.features, self.mean, self.std)
            return
        ds.features = (ds.features - self.mean) / self.std

    def revert(self, ds: DataSet) -> None:
        ds.features = ds.features * self.std + self.mean

    def state(self):
        return {"mean": self.mean, "std": self.std}

    def load_state(self, s):
        self.mean, self.std = np.asarray(s["mean"]), np.asarray(s["std"])


class NormalizerMinMaxScaler:
    """NormalizerMinMaxScaler.java: rescale features to [lo, hi]."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi
        self.fmin = None
        self.fmax = None

    def fit(self, data) -> None:
        feats = data.features if isinstance(data, DataSet) else DataSet.merge(list(data)).features
        flat = feats.reshape(feats.shape[0], -1)
        self.fmin = flat.min()
        self.fmax = flat.max()

    def transform(self, ds: DataSet) -> None:
        rng = max(self.fmax - self.fmin, 1e-8)
        if getattr(ds.features, "dtype", None) == np.uint8:
            from deeplearning4j_tpu.native_ops.pixops import u8_normalize

            scale = (self.hi - self.lo) / rng
            ds.features = u8_normalize(ds.features, scale,
                                       self.lo - self.fmin * scale)
            return
        ds.features = (ds.features - self.fmin) / rng * (self.hi - self.lo) + self.lo

    def state(self):
        return {"fmin": self.fmin, "fmax": self.fmax, "lo": self.lo, "hi": self.hi}

    def load_state(self, s):
        self.fmin, self.fmax = s["fmin"], s["fmax"]
        self.lo, self.hi = s.get("lo", 0.0), s.get("hi", 1.0)


class ImagePreProcessingScaler:
    """ImagePreProcessingScaler.java: pixels [0, maxPixel] -> [lo, hi]."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0, max_pixel: float = 255.0):
        self.lo, self.hi, self.max_pixel = lo, hi, max_pixel

    def fit(self, data) -> None:  # stateless
        pass

    def transform(self, ds: DataSet) -> None:
        if getattr(ds.features, "dtype", None) == np.uint8:
            # uint8 batches take the native pixel loop (native/pixops.cpp)
            from deeplearning4j_tpu.native_ops.pixops import u8_normalize

            ds.features = u8_normalize(
                ds.features, (self.hi - self.lo) / self.max_pixel, self.lo)
            return
        ds.features = ds.features / self.max_pixel * (self.hi - self.lo) + self.lo

    def state(self):
        return {"lo": self.lo, "hi": self.hi, "max_pixel": self.max_pixel}

    def load_state(self, s):
        self.lo, self.hi, self.max_pixel = s["lo"], s["hi"], s["max_pixel"]
