"""MNIST-shaped dataset iterator — deeplearning4j-datasets parity.

Reference parity: MnistDataSetIterator / MnistDataFetcher
(deeplearning4j-datasets/.../iterator/impl/MnistDataSetIterator.java), which
downloads the IDX files and serves (N, 784) float batches with one-hot labels.

This environment has no network (SURVEY §8.3 hard part #6), so:
  * If real IDX files exist under ``root`` (default ~/.dl4jtpu/mnist), they
    are loaded (same ubyte format the reference fetches).
  * Otherwise a DETERMINISTIC SYNTHETIC stand-in is generated: each class is
    a smoothed random prototype glyph; samples are the prototype + small
    random shift + pixel noise. It is genuinely learnable (a LeNet reaches
    >95% quickly) so convergence tests exercise the real training dynamics.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

_DEFAULT_ROOT = os.path.expanduser("~/.dl4jtpu/mnist")


def _load_idx(path: str) -> np.ndarray:
    """Read an IDX ubyte file through the shared (native-capable) parser
    (native/record_loader.cpp via native_ops.record_loader); returns uint8
    to preserve the historical contract for label files."""
    from deeplearning4j_tpu.native_ops.record_loader import idx_to_array

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        buf = f.read()
    return idx_to_array(buf, scale=False).astype(np.uint8)


def _find_idx(root: str, names) -> Optional[str]:
    for n in names:
        for ext in ("", ".gz"):
            p = os.path.join(root, n + ext)
            if os.path.exists(p):
                return p
    return None


def _smooth(img: np.ndarray, passes: int = 2) -> np.ndarray:
    for _ in range(passes):
        img = (
            img
            + np.roll(img, 1, 0) + np.roll(img, -1, 0)
            + np.roll(img, 1, 1) + np.roll(img, -1, 1)
        ) / 5.0
    return img


def synthetic_mnist(n: int, seed: int = 123, num_classes: int = 10,
                    proto_seed: int = 777) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable digit-like data: (n, 784) float32 in [0,1],
    int labels (n,). Class prototypes come from ``proto_seed`` (shared across
    train/test splits); sample noise/shifts come from ``seed``."""
    proto_rng = np.random.RandomState(proto_seed)
    protos = []
    for _ in range(num_classes):
        p = _smooth(proto_rng.rand(28, 28) > 0.75, passes=3).astype(np.float32)
        p = p / max(p.max(), 1e-6)
        protos.append(p)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n)
    imgs = np.empty((n, 28, 28), np.float32)
    shifts = rng.randint(-2, 3, size=(n, 2))
    noise = rng.rand(n, 28, 28).astype(np.float32)
    for i, (lab, (dy, dx)) in enumerate(zip(labels, shifts)):
        img = np.roll(np.roll(protos[lab], dy, axis=0), dx, axis=1)
        imgs[i] = np.clip(img + 0.15 * (noise[i] - 0.5), 0.0, 1.0)
    return imgs.reshape(n, 784), labels


def _one_hot(labels: np.ndarray, n: int = 10) -> np.ndarray:
    out = np.zeros((labels.size, n), np.float32)
    out[np.arange(labels.size), labels] = 1.0
    return out


class MnistDataSetIterator(ListDataSetIterator):
    """MnistDataSetIterator analog: (N, 784) features in [0,1], one-hot labels.

    ``train=True`` serves the train split, else the test split. Falls back to
    synthetic data when IDX files are absent (flagged via ``self.synthetic``).
    """

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 root: str = _DEFAULT_ROOT, num_examples: Optional[int] = None):
        img_names = ["train-images-idx3-ubyte"] if train else ["t10k-images-idx3-ubyte"]
        lab_names = ["train-labels-idx1-ubyte"] if train else ["t10k-labels-idx1-ubyte"]
        img_path = _find_idx(root, img_names)
        lab_path = _find_idx(root, lab_names)
        if img_path and lab_path:
            self.synthetic = False
            imgs = _load_idx(img_path).astype(np.float32) / 255.0
            labels = _load_idx(lab_path)
            feats = imgs.reshape(imgs.shape[0], -1)
        else:
            self.synthetic = True
            n = num_examples or (4096 if train else 1024)
            feats, labels = synthetic_mnist(n, seed=seed + (0 if train else 1))
        if num_examples:
            feats, labels = feats[:num_examples], labels[:num_examples]
        super().__init__(DataSet(feats, _one_hot(labels)), batch_size=batch_size,
                         shuffle=train, seed=seed)
