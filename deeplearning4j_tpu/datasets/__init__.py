"""Data layer — DataSet/iterators/normalizers + built-in dataset fetchers.

Reference parity: org/nd4j/linalg/dataset/** and deeplearning4j-datasets
(SURVEY §3.2, §3.3)."""

from deeplearning4j_tpu.datasets.dataset import (
    DataSet,
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    NormalizerStandardize,
    NormalizerMinMaxScaler,
    ImagePreProcessingScaler,
)
