"""Data layer — DataSet/iterators/normalizers + built-in dataset fetchers.

Reference parity: org/nd4j/linalg/dataset/** and deeplearning4j-datasets
(SURVEY §3.2, §3.3)."""

from deeplearning4j_tpu.datasets.dataset import (
    DataSet,
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    NormalizerStandardize,
    NormalizerMinMaxScaler,
    ImagePreProcessingScaler,
)
from deeplearning4j_tpu.datasets.image import (
    ImageRecordReader,
    SyntheticImageNetIterator,
    FlipImageTransform,
    RandomCropTransform,
    RotateImageTransform,
    ColorJitterTransform,
    PipelineImageTransform,
)
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.datasets.cifar import (
    Cifar10DataSetIterator,
    EmnistDataSetIterator,
    EMNIST_SETS,
    synthetic_images,
)
