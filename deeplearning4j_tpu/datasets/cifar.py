"""CIFAR-10 / EMNIST dataset iterators.

Reference parity: deeplearning4j-datasets fetchers + iterators
(CifarDataSetIterator/Cifar10DataSetIterator, EmnistDataSetIterator with
its EmnistSet splits). The reference downloads archives on first use; this
environment has no egress, so the iterators read LOCAL files when present
(CIFAR python/binary batches under ``root``; EMNIST idx files) and fall
back to the same deterministic synthetic-prototype generator the MNIST
iterator uses — flagged via ``self.synthetic`` so tests/users can tell.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.datasets.mnist import (
    _find_idx, _load_idx, _one_hot, _smooth,
)

_DEFAULT_ROOT = os.path.expanduser("~/.deeplearning4j_tpu/datasets")


def synthetic_images(n: int, height: int, width: int, channels: int,
                     num_classes: int, seed: int = 123,
                     proto_seed: int = 991) -> Tuple[np.ndarray, np.ndarray]:
    """Learnable synthetic color images: class = smoothed color-blob
    prototype + per-sample shift/noise. (n, H, W, C) float32 in [0,1]."""
    proto_rng = np.random.RandomState(proto_seed)
    protos = []
    for _ in range(num_classes):
        chans = []
        for _c in range(channels):
            p = _smooth(proto_rng.rand(height, width) > 0.7, passes=3)
            chans.append(p.astype(np.float32))
        p = np.stack(chans, axis=-1)
        protos.append(p / max(p.max(), 1e-6))
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n)
    out = np.empty((n, height, width, channels), np.float32)
    shifts = rng.randint(-3, 4, size=(n, 2))
    for i, (lab, (dy, dx)) in enumerate(zip(labels, shifts)):
        img = np.roll(np.roll(protos[lab], dy, axis=0), dx, axis=1)
        noise = rng.rand(height, width, channels).astype(np.float32)
        out[i] = np.clip(img + 0.15 * (noise - 0.5), 0.0, 1.0)
    return out, labels


def _load_cifar_local(root: str, train: bool):
    """Read CIFAR-10 from the standard python pickle batches or the binary
    .bin batches if a user has placed them under root."""
    pydir = os.path.join(root, "cifar-10-batches-py")
    if os.path.isdir(pydir):
        names = ([f"data_batch_{i}" for i in range(1, 6)] if train
                 else ["test_batch"])
        xs, ys = [], []
        for nme in names:
            path = os.path.join(pydir, nme)
            if not os.path.exists(path):
                return None
            with open(path, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.extend(d[b"labels"])
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32) / 255.0, np.asarray(ys, np.int64)
    bindir = os.path.join(root, "cifar-10-batches-bin")
    if os.path.isdir(bindir):
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        xs, ys = [], []
        for nme in names:
            path = os.path.join(bindir, nme)
            if not os.path.exists(path):
                return None
            raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0])
            xs.append(raw[:, 1:])
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return x.astype(np.float32) / 255.0, np.concatenate(ys).astype(np.int64)
    return None


class Cifar10DataSetIterator(ListDataSetIterator):
    """Cifar10DataSetIterator analog: (N, 32, 32, 3) in [0,1] NHWC + one-hot
    10-class labels. Synthetic fallback when no local copy exists."""

    NUM_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 root: str = _DEFAULT_ROOT,
                 num_examples: Optional[int] = None):
        loaded = _load_cifar_local(root, train)
        if loaded is not None:
            self.synthetic = False
            feats, labels = loaded
        else:
            self.synthetic = True
            n = num_examples or (4096 if train else 1024)
            feats, labels = synthetic_images(
                n, 32, 32, 3, self.NUM_CLASSES,
                seed=seed + (0 if train else 1))
        if num_examples:
            feats, labels = feats[:num_examples], labels[:num_examples]
        super().__init__(DataSet(feats, _one_hot(labels, self.NUM_CLASSES)),
                         batch_size=batch_size, shuffle=train, seed=seed)


# EMNIST split metadata (EmnistDataSetIterator.Set analog)
EMNIST_SETS = {
    "complete": 62, "merge": 47, "balanced": 47, "letters": 26,
    "digits": 10, "mnist": 10,
}


class EmnistDataSetIterator(ListDataSetIterator):
    """EmnistDataSetIterator analog: 28×28 grayscale flattened to (N, 784),
    classes per the chosen EMNIST split. Reads idx files named
    emnist-<set>-{train,test}-{images-idx3,labels-idx1}-ubyte from root;
    synthetic fallback otherwise."""

    def __init__(self, batch_size: int, emnist_set: str = "balanced",
                 train: bool = True, seed: int = 123,
                 root: str = _DEFAULT_ROOT,
                 num_examples: Optional[int] = None):
        if emnist_set not in EMNIST_SETS:
            raise ValueError(
                f"unknown EMNIST set {emnist_set!r}; known: "
                f"{sorted(EMNIST_SETS)}")
        self.emnist_set = emnist_set
        self.num_classes = EMNIST_SETS[emnist_set]
        split = "train" if train else "test"
        img = _find_idx(root, [f"emnist-{emnist_set}-{split}-images-idx3-ubyte"])
        lab = _find_idx(root, [f"emnist-{emnist_set}-{split}-labels-idx1-ubyte"])
        if img and lab:
            self.synthetic = False
            imgs = _load_idx(img).astype(np.float32) / 255.0
            labels = _load_idx(lab).astype(np.int64)
            # EMNIST letters labels are 1-based
            if emnist_set == "letters" and labels.min() == 1:
                labels = labels - 1
            feats = imgs.reshape(imgs.shape[0], -1)
        else:
            self.synthetic = True
            n = num_examples or (4096 if train else 1024)
            from deeplearning4j_tpu.datasets.mnist import synthetic_mnist

            feats, labels = synthetic_mnist(
                n, seed=seed + (0 if train else 1),
                num_classes=self.num_classes)
        if num_examples:
            feats, labels = feats[:num_examples], labels[:num_examples]
        super().__init__(DataSet(feats, _one_hot(labels, self.num_classes)),
                         batch_size=batch_size, shuffle=train, seed=seed)
