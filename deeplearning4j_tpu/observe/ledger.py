"""Recompile ledger — every jit cache miss, with its cause, on the record.

Item 1 of the ROADMAP (shape-polymorphic AOT serving) exists because diverse
traffic can trigger a recompile storm; this ledger makes the storm VISIBLE
before that item fixes it. ``SameDiff`` (autodiff/samediff.py) and the
network classes (nn/multilayer.py, nn/graph.py) report every compilation —
a ``_jit_cache`` miss or a new input shape/dtype signature hitting a cached
jit wrapper — as one :class:`CompileEvent` carrying:

* ``graph``/``key``: which model and which cached function (exec / grad /
  train_step / output ...),
* ``signature``: the input shape/dtype signature that compiled,
* ``cause``: ``first_compile`` | ``new_shape`` | ``graph_mutation`` |
  ``constant_rebind`` | ``variable_rebind`` | ``cache_hit`` — the
  invalidation that forced the miss (SameDiff threads the cause from the
  exact `_jit_cache.clear()` sites); ``cache_hit`` marks a fn restored
  from the persistent AOT export cache (autodiff/export.py) — a warm
  restore is a compile *event* (visible, attributable) but not a fresh
  XLA compile,
* ``stats``: the live ``OptimizeStats`` when the optimizer produced one, so
  trace-vs-XLA-compile seconds appear in the event once ``CompiledGraph``
  measures them (the stats object is shared, not copied — reads see the
  final timings).

Events also increment ``dl4j_tpu_recompiles_total`` (plus a per-cause
counter) in the default metrics registry and append a ``recompile`` JSONL
event when ``DL4J_TPU_OBS_LOG`` is set.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.observe.registry import default_registry, log_event

CAUSES = ("first_compile", "new_shape", "graph_mutation",
          "constant_rebind", "variable_rebind", "cache_hit")

_MAX_EVENTS = 2000

# the observe package dir (frames inside it are plumbing, not callsites)
# and the repo root callsites are reported relative to
_OBS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_OBS_DIR))


def _caller_callsite() -> Optional[str]:
    """Repo-relative ``path:line`` of the nearest stack frame OUTSIDE the
    observe package — the source site that registered this compile event.
    graftshape's runtime cross-validation (testing/shapetrace.py) matches
    these against the static registration-site inventory, so the format
    (forward slashes, repo-relative when under the repo) must agree with
    lint ``Finding.path``."""
    f = sys._getframe(1)
    while f is not None:
        fname = f.f_code.co_filename
        if not os.path.abspath(fname).startswith(_OBS_DIR):
            try:
                rel = os.path.relpath(fname, _REPO_ROOT)
            except ValueError:  # different drive (windows) — keep absolute
                rel = fname
            if rel.startswith(".."):
                rel = fname
            return f"{rel.replace(os.sep, '/')}:{f.f_lineno}"
        f = f.f_back
    return None


@dataclasses.dataclass
class CompileEvent:
    seq: int
    graph: str            # model identity ("samediff", "mln", "graph", ...)
    key: str              # cached-function kind ("exec", "train", ...)
    signature: str        # input shape/dtype signature
    cause: str
    timestamp: float      # epoch seconds (display only; never subtracted)
    stats: Any = None     # OptimizeStats (live reference) or None
    callsite: Optional[str] = None  # "path:line" of the registering site

    def to_dict(self) -> Dict[str, Any]:
        out = {"seq": self.seq, "graph": self.graph, "key": self.key,
               "signature": self.signature, "cause": self.cause,
               "timestamp": self.timestamp, "callsite": self.callsite}
        st = self.stats
        if st is not None:
            out["trace_seconds"] = getattr(st, "trace_seconds", None)
            out["compile_seconds"] = getattr(st, "compile_seconds", None)
            out["optimize_seconds"] = getattr(st, "optimize_seconds", None)
            out["nodes_before"] = getattr(st, "nodes_before", None)
            out["nodes_after"] = getattr(st, "nodes_after", None)
            # fusion-tier hits (docs/OPTIMIZER.md § Fusion tier) — lets
            # `tools/obsreport.py --log` show fusion counts per compile
            fusions = getattr(st, "fusions", None)
            if fusions:
                out["fusions"] = dict(fusions)
        return out


class RecompileLedger:
    """Bounded, thread-safe event log of compilations."""

    def __init__(self, max_events: int = _MAX_EVENTS):
        self._events: "deque[CompileEvent]" = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, *, graph: str, key: str, signature: str, cause: str,
               stats: Any = None,
               callsite: Optional[str] = None) -> CompileEvent:
        if cause not in CAUSES:
            raise ValueError(f"unknown recompile cause '{cause}'; "
                             f"valid: {list(CAUSES)}")
        if callsite is None:
            callsite = _caller_callsite()
        with self._lock:
            self._seq += 1
            ev = CompileEvent(seq=self._seq, graph=graph, key=key,
                              signature=signature, cause=cause,
                              timestamp=time.time(), stats=stats,
                              callsite=callsite)
            self._events.append(ev)
        m = default_registry()
        m.counter("dl4j_tpu_recompiles_total").inc()
        m.counter("dl4j_tpu_recompile_cause_total", cause=cause).inc()
        fields = {"graph": graph, "key": key, "signature": signature,
                  "cause": cause, "callsite": callsite}
        fusions = getattr(stats, "fusions", None) if stats is not None \
            else None
        if fusions:
            # fusion-tier hits join the JSONL event so obsreport --log can
            # report them per compile (docs/OPTIMIZER.md § Fusion tier)
            fields["fusions"] = dict(fusions)
        log_event("recompile", **fields)
        return ev

    def events(self) -> Tuple[CompileEvent, ...]:
        with self._lock:
            return tuple(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def summary(self) -> Dict[str, Any]:
        evs = self.events()
        by_cause: Dict[str, int] = {}
        by_callsite: Dict[str, int] = {}
        for ev in evs:
            by_cause[ev.cause] = by_cause.get(ev.cause, 0) + 1
            cs = ev.callsite or "<unknown>"
            by_callsite[cs] = by_callsite.get(cs, 0) + 1
        compile_s = [getattr(ev.stats, "compile_seconds", None)
                     for ev in evs if ev.stats is not None]
        compile_s = [s for s in compile_s if s is not None]
        return {"total": len(evs), "by_cause": by_cause,
                "by_callsite": by_callsite,
                "compile_seconds_sum": round(sum(compile_s), 4)
                if compile_s else None}


_DEFAULT: Optional[RecompileLedger] = None
_DEFAULT_LOCK = threading.Lock()


def default_ledger() -> RecompileLedger:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = RecompileLedger()
        return _DEFAULT


def reset_default_ledger() -> RecompileLedger:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
    return default_ledger()


# ---------------------------------------------------------------------------
# helpers the runtimes call
# ---------------------------------------------------------------------------


def signature_of(*arrays: Any, **named: Any) -> str:
    """Compact shape/dtype signature of a feed set, e.g.
    ``x:f32[32,128],y:f32[32,10]``. Accepts positional arrays (labelled by
    position) and/or name->array pairs; None entries are skipped."""
    import numpy as np

    parts = []
    items = [(str(i), a) for i, a in enumerate(arrays)]
    items += sorted(named.items())
    for name, a in items:
        if a is None:
            continue
        dt = np.dtype(getattr(a, "dtype", type(a))).name \
            if hasattr(a, "dtype") else type(a).__name__
        shape = ",".join(str(int(d)) for d in getattr(a, "shape", ()))
        parts.append(f"{name}:{dt}[{shape}]")
    return "|".join(parts)


def note_jit_signature(fn: Any, *, graph: str, key: str, signature: str,
                       stats: Any = None,
                       cause_if_new_fn: str = "first_compile",
                       callsite: Optional[str] = None) -> Optional[str]:
    """Record a compile event iff ``signature`` is new for ``fn``.

    The seen-signature set rides ON the cached function object, so the
    exact cache-invalidation paths that drop the function also drop its
    history — a rebuilt fn reports ``cause_if_new_fn`` (the invalidation
    cause), a cached fn seeing a fresh signature reports ``new_shape``
    (jax retraces per shape under the hood). Two attributes set by the
    AOT export layer (autodiff/export.py) override those causes:
    ``fn._aot_restored`` marks a fn deserialized from the persistent
    export cache — every event it produces is a ``cache_hit``, not a
    fresh compile; ``fn._aot_polymorphic`` marks a symbolic-batch-dim
    executable — a fresh signature is served by the SAME executable
    without a retrace, so it too records ``cache_hit`` instead of
    ``new_shape``. ``stats`` is attached only to
    the new-fn event: a new_shape retrace never re-ran the optimizer, so
    inheriting the original compile's OptimizeStats would double-count its
    trace/compile seconds in ledger summaries. ``callsite`` defaults to
    the nearest caller frame outside the observe package — the source
    site graftshape's shapetrace attributes the event to. Returns the
    cause recorded, or None on a plain cache hit."""
    try:
        sigs = fn._obs_sigs
    except AttributeError:
        try:
            fn._obs_sigs = sigs = set()
        except (AttributeError, TypeError):
            return None  # fn refuses attributes; skip tracking, never fail
    if signature in sigs:
        return None
    new_fn = not sigs
    restored = getattr(fn, "_aot_restored", False)
    if new_fn:
        cause = "cache_hit" if restored else cause_if_new_fn
    else:
        cause = ("cache_hit"
                 if restored or getattr(fn, "_aot_polymorphic", False)
                 else "new_shape")
    sigs.add(signature)
    if callsite is None:
        # resolved HERE (not in record) so the cache-hit fast path above
        # never pays the stack walk
        callsite = _caller_callsite()
    default_ledger().record(graph=graph, key=key, signature=signature,
                            cause=cause, stats=stats if new_fn else None,
                            callsite=callsite)
    return cause
