"""Unified runtime telemetry (docs/OBSERVABILITY.md).

One metric model for the whole framework:

* :func:`metrics` — the process-wide :class:`MetricsRegistry` (counters,
  gauges, histograms with streaming p50/p95/p99), served as Prometheus
  text at the UI server's ``/metrics`` endpoint.
* :func:`tracer` — the process-wide :class:`SpanTracer` (monotonic-clock
  nested spans, Chrome-trace export — the SAME format
  ``utils/profiling.py`` writes).
* :func:`ledger` — the :class:`RecompileLedger` fed by every
  ``SameDiff``/network jit-cache miss with its shape/dtype signature and
  cause.
* :func:`log_event` — JSONL event log, enabled by ``DL4J_TPU_OBS_LOG=path``.
* :func:`summary` — the compact snapshot ``bench.py`` embeds in its final
  JSON line and ``tools/obsreport.py`` prints.

This package imports neither jax nor the model runtimes — it is safe to
import from any layer (including before backend selection).
"""

from __future__ import annotations

from typing import Any, Dict

from deeplearning4j_tpu.observe.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    OBS_LOG_ENV,
    default_registry,
    log_event,
    reset_default_registry,
    reset_log_state,
)
from deeplearning4j_tpu.observe.tracing import (
    SpanTracer,
    default_tracer,
    reset_default_tracer,
)
from deeplearning4j_tpu.observe.ledger import (
    CompileEvent,
    RecompileLedger,
    default_ledger,
    note_jit_signature,
    reset_default_ledger,
    signature_of,
)

# short accessors — the names call sites use
metrics = default_registry
tracer = default_tracer
ledger = default_ledger


def reset() -> None:
    """Fresh registry/tracer/ledger (test isolation; never used in prod)."""
    reset_default_registry()
    reset_default_tracer()
    reset_default_ledger()
    reset_log_state()


def _ms(seconds) -> Any:
    return None if seconds is None else round(seconds * 1e3, 3)


def dispatch_summary() -> Dict[str, int]:
    """Helper-dispatch decisions (``dl4j_tpu_helper_dispatch_total``) as a
    compact ``op/impl/reason -> count`` map — how many times resolve picked
    the Pallas helper vs the XLA generic, and why. A routing regression
    (e.g. flash silently deferring everywhere after a threshold change)
    shows up here instead of only as a throughput delta."""
    out: Dict[str, int] = {}
    for inst in metrics().instruments():
        if inst.name != "dl4j_tpu_helper_dispatch_total":
            continue
        lbl = dict(inst.labels)
        key = f"{lbl.get('op')}/{lbl.get('impl')}/{lbl.get('reason')}"
        out[key] = out.get(key, 0) + int(inst.value)
    return dict(sorted(out.items()))


def summary() -> Dict[str, Any]:
    """Compact cross-layer snapshot: recompiles, train-step latency
    percentiles, serving latency percentiles, helper-dispatch decisions.
    Empty sections are omitted — the bench JSON line only carries what the
    run actually exercised."""
    m = metrics()
    out: Dict[str, Any] = {}

    led = ledger().summary()
    if led["total"]:
        out["recompiles"] = led

    disp = dispatch_summary()
    if disp:
        out["dispatch"] = disp

    steps = m.family_total("dl4j_tpu_train_steps_total")
    if steps:
        h = m.merged_histogram("dl4j_tpu_train_step_seconds")
        pct = h.percentiles()
        out["train"] = {
            "steps": int(steps),
            "examples": int(
                m.family_total("dl4j_tpu_train_examples_total")),
            "step_p50_ms": _ms(pct["p50"]),
            "step_p95_ms": _ms(pct["p95"]),
            "step_p99_ms": _ms(pct["p99"]),
        }

    gen = m.counter("dl4j_tpu_serving_generated_tokens_total").value
    if gen:
        dec = m.histogram("dl4j_tpu_serving_decode_step_seconds").percentiles()
        ttft = m.histogram("dl4j_tpu_serving_ttft_seconds").percentiles()
        itl = m.histogram("dl4j_tpu_serving_intertoken_seconds").percentiles()
        out["generate"] = {
            "generated_tokens": int(gen),
            "admitted": int(
                m.counter("dl4j_tpu_serving_admitted_total").value),
            "evicted": int(
                m.family_total("dl4j_tpu_serving_evicted_total")),
            "decode_p50_ms": _ms(dec["p50"]),
            "decode_p99_ms": _ms(dec["p99"]),
            "ttft_p50_ms": _ms(ttft["p50"]),
            "ttft_p99_ms": _ms(ttft["p99"]),
            "intertoken_p50_ms": _ms(itl["p50"]),
            "intertoken_p99_ms": _ms(itl["p99"]),
        }

    proposed = m.counter("dl4j_tpu_spec_proposed_tokens_total").value
    if proposed:
        accepted = m.counter("dl4j_tpu_spec_accepted_tokens_total").value
        ratio = m.histogram("dl4j_tpu_spec_accept_ratio").percentiles()
        out["spec"] = {
            "proposed_tokens": int(proposed),
            "accepted_tokens": int(accepted),
            "rejected_tokens": int(
                m.counter("dl4j_tpu_spec_rejected_tokens_total").value),
            "acceptance_rate": round(accepted / proposed, 4),
            "accept_ratio_p50": None if ratio["p50"] is None
            else round(ratio["p50"], 3),
        }

    lookups = m.counter("dl4j_tpu_prefix_lookups_total").value
    if lookups:
        hits = m.counter("dl4j_tpu_prefix_hits_total").value
        out["prefix"] = {
            "lookups": int(lookups),
            "hits": int(hits),
            "hit_rate": round(hits / lookups, 4),
            "hit_tokens": int(
                m.counter("dl4j_tpu_prefix_hit_tokens_total").value),
            "cow_copies": int(
                m.counter("dl4j_tpu_prefix_cow_copies_total").value),
            "inserted_pages": int(
                m.counter("dl4j_tpu_prefix_inserted_pages_total").value),
            "evicted_pages": int(
                m.counter("dl4j_tpu_prefix_evicted_pages_total").value),
            "tree_pages": int(m.gauge("dl4j_tpu_prefix_tree_pages").value),
            "pinned_pages": int(
                m.gauge("dl4j_tpu_prefix_pinned_pages").value),
        }

    slo_admitted = m.family_total("dl4j_tpu_slo_admitted_total")
    slo_shed = m.family_total("dl4j_tpu_slo_shed_total")
    if slo_admitted or slo_shed:
        admitted_by_class: Dict[str, int] = {}
        shed_by: Dict[str, int] = {}
        transitions: Dict[str, int] = {}
        for inst in m.instruments():
            lbl = dict(inst.labels)
            if inst.name == "dl4j_tpu_slo_admitted_total" and lbl:
                admitted_by_class[lbl.get("class", "?")] = int(inst.value)
            elif inst.name == "dl4j_tpu_slo_shed_total" and lbl:
                key = f"{lbl.get('class')}/{lbl.get('reason')}"
                shed_by[key] = shed_by.get(key, 0) + int(inst.value)
            elif inst.name == "dl4j_tpu_slo_transitions_total" and lbl:
                transitions[lbl.get("to", "?")] = int(inst.value)
        out["slo"] = {
            "state": int(m.gauge("dl4j_tpu_slo_state").value),
            "breaker_open": int(m.gauge("dl4j_tpu_slo_breaker_open").value),
            "admitted": dict(sorted(admitted_by_class.items())),
            "shed": dict(sorted(shed_by.items())),
            "degraded": int(m.family_total("dl4j_tpu_slo_degraded_total")),
            "transitions": dict(sorted(transitions.items())),
        }

    # preemption-proof training (docs/ROBUSTNESS.md § Preemption-proof
    # training): async checkpoint pipeline health + resume/preemption
    # counts — reported whenever the async writer or supervisor ran
    ck_async = m.counter("dl4j_tpu_ckpt_async_saves_total").value
    ck_resumes = m.counter("dl4j_tpu_ckpt_resumes_total").value
    ck_preempt = m.counter("dl4j_tpu_train_preemptions_total").value
    if ck_async or ck_resumes or ck_preempt:
        wh = m.histogram("dl4j_tpu_ckpt_write_seconds").percentiles()
        out["training"] = {
            "async_saves": int(ck_async),
            "write_p50_ms": _ms(wh["p50"]),
            "write_p99_ms": _ms(wh["p99"]),
            "queue_depth": int(m.gauge("dl4j_tpu_ckpt_queue_depth").value),
            "dropped": int(m.counter("dl4j_tpu_ckpt_dropped_total").value),
            "blocked": int(m.counter("dl4j_tpu_ckpt_blocked_total").value),
            "resumes": int(ck_resumes),
            "preemptions": int(ck_preempt),
        }

    robustness = {
        "faults_injected": int(
            m.family_total("dl4j_tpu_faults_injected_total")),
        "engine_restarts": int(
            m.counter("dl4j_tpu_serving_engine_restarts_total").value),
        "retries": int(m.counter("dl4j_tpu_serving_retries_total").value),
        "shed": int(m.counter("dl4j_tpu_serving_evicted_total",
                              reason="shed").value),
        "checkpoint_corrupt": int(
            m.counter("dl4j_tpu_checkpoint_corrupt_total").value),
        "checkpoint_fallbacks": int(
            m.counter("dl4j_tpu_checkpoint_fallback_total").value),
    }
    if any(robustness.values()):
        # reported when ANY of it happened — a real (un-injected) torn
        # checkpoint or shed burst must be as visible as a chaos run
        out["robustness"] = robustness

    reqs = m.counter("dl4j_tpu_serving_requests_total").value
    if reqs:
        h = m.histogram("dl4j_tpu_serving_request_seconds")
        pct = h.percentiles()
        occ = m.histogram("dl4j_tpu_serving_batch_occupancy")
        out["serving"] = {
            "requests": int(reqs),
            "batches": int(m.counter("dl4j_tpu_serving_batches_total").value),
            "p50_ms": _ms(pct["p50"]),
            "p95_ms": _ms(pct["p95"]),
            "p99_ms": _ms(pct["p99"]),
            "batch_occupancy_mean": round(occ.mean, 4) if occ.count else None,
        }
    return out


__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "SpanTracer",
    "CompileEvent", "RecompileLedger", "OBS_LOG_ENV",
    "metrics", "tracer", "ledger", "default_registry", "default_tracer",
    "default_ledger", "log_event", "note_jit_signature", "signature_of",
    "summary", "dispatch_summary", "reset", "reset_log_state",
]
