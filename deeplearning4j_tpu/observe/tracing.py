"""Lightweight span tracer — ONE trace format for the whole runtime.

Chrome trace-event JSON (``chrome://tracing`` / Perfetto) was already the
profiling artifact (``utils/profiling.py``); this module owns the format now
and ``ChromeTraceWriter`` there subclasses :class:`SpanTracer`, so spans
recorded by the training loop, the compile path, and the serving loop land
in the same timeline as the listener-driven per-iteration events.

Clocks are monotonic (``time.perf_counter``) — wall-clock (``time.time``)
deltas jump with NTP and are banned for durations (graftlint GL010).

Spans nest: each thread keeps its own depth counter and events carry the
thread id as ``tid``, so concurrent serving clients render as separate
tracks. The event buffer is bounded (newest kept) — tracing a week-long
serving process must not grow host memory without bound.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_MAX_EVENTS = 20000


class SpanTracer:
    """Nested-span recorder emitting Chrome trace events."""

    def __init__(self, max_events: Optional[int] = _MAX_EVENTS):
        # max_events=None means unbounded (explicit artifact writers);
        # the process-wide default tracer stays bounded, newest kept
        self.events: "deque[Dict[str, Any]]" = deque(maxlen=max_events)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    # -- clock ---------------------------------------------------------------
    def _us(self) -> float:
        """Microseconds since tracer start (monotonic)."""
        return (time.perf_counter() - self._t0) * 1e6

    # -- recording -----------------------------------------------------------
    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            self.events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, category: str = "step", **args):
        """Record a complete ('X') event around the with-block. Nesting is
        carried by the event ts/dur intervals per tid — how the chrome
        trace viewer reconstructs the stack."""
        start = self._us()
        try:
            yield self
        finally:
            self._append({
                "name": name, "cat": category, "ph": "X", "ts": start,
                "dur": self._us() - start, "pid": 0,
                "tid": threading.get_ident() % 1_000_000, "args": args,
            })

    def complete(self, name: str, start_us: float, dur_us: float,
                 category: str = "step", **args) -> None:
        """Record an explicit complete event (for externally measured
        intervals, e.g. the AOT trace/compile split)."""
        self._append({"name": name, "cat": category, "ph": "X",
                      "ts": start_us, "dur": dur_us, "pid": 0,
                      "tid": threading.get_ident() % 1_000_000, "args": args})

    def complete_between(self, name: str, perf_start: float, perf_end: float,
                         category: str = "step", **args) -> None:
        """Record a complete event from two ``time.perf_counter()`` readings
        (same monotonic clock as the tracer — no epoch conversion)."""
        self.complete(name, (perf_start - self._t0) * 1e6,
                      (perf_end - perf_start) * 1e6, category=category,
                      **args)

    def instant(self, name: str, **args) -> None:
        self._append({"name": name, "cat": "marker", "ph": "i",
                      "ts": self._us(), "pid": 0,
                      "tid": threading.get_ident() % 1_000_000, "s": "g",
                      "args": args})

    # -- export --------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            events = list(self.events)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write(self, path: str) -> None:
        # graftlife: justified(GR005): human-facing trace dump to a
        # caller-chosen path — nothing loads it back; re-run to regenerate
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f)

    def clear(self) -> None:
        with self._lock:
            self.events.clear()


_DEFAULT: Optional[SpanTracer] = None
_DEFAULT_LOCK = threading.Lock()


def default_tracer() -> SpanTracer:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = SpanTracer()
        return _DEFAULT


def reset_default_tracer() -> SpanTracer:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
    return default_tracer()
