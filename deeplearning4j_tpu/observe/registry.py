"""Process-wide metrics registry — counters, gauges, streaming histograms.

The production-serving questions the ROADMAP asks (how many recompiles did
diverse traffic trigger, what are the serving p50/p99 latencies, where did a
slow step go) all reduce to three instrument kinds:

``Counter``    monotonically increasing totals (steps, requests, recompiles).
``Gauge``      last-written level (queue depth, examples/sec).
``Histogram``  streaming distribution with p50/p95/p99 quantiles over
               log-spaced buckets — bounded memory, thread-safe, and
               renderable as a Prometheus cumulative-``le`` histogram.

One process-wide default registry (:func:`default_registry`) is the metric
model every hot layer writes into (SameDiff/MultiLayerNetwork/
ComputationGraph fit, the recompile ledger, ``ParallelInference`` serving);
``ui/server.py`` serves it at ``/metrics`` in Prometheus text format and
``tools/obsreport.py`` summarizes it. All instruments are safe to write from
any thread: one registry lock guards instrument creation, a per-instrument
lock guards updates (serving clients record latencies concurrently).

Naming follows the Prometheus convention: ``dl4j_tpu_<what>_<unit>`` with
``_total`` for counters. Labels are a small dict rendered as
``name{k="v"}``; instruments are keyed by (name, sorted labels).
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# Default latency bucket bounds (seconds): log-spaced from 100µs to ~56min
# (26 power-of-2 buckets, ~3.3 per decade) — honest p99s on sub-ms serving
# latencies AND multi-minute compile times in one scheme.
_DEFAULT_BOUNDS: Tuple[float, ...] = tuple(
    round(1e-4 * (2.0 ** k), 10) for k in range(26))


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    kind = "counter"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self.value += amount

    def snapshot(self) -> Dict[str, Any]:
        return {"kind": self.kind, "value": self.value}

    def render(self) -> List[str]:
        v = self.value
        return [f"{self.name}{_render_labels(self.labels)} "
                f"{int(v) if float(v).is_integer() else v}"]


class Gauge(Counter):
    """Last-written level (Prometheus ``gauge``)."""

    kind = "gauge"

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram:
    """Streaming histogram with quantile estimation.

    Observations land in log-spaced buckets (cumulative-``le`` on render,
    the Prometheus histogram contract); quantiles interpolate linearly
    inside the owning bucket, which bounds the error by the bucket ratio
    (2× by default) — the standard Prometheus ``histogram_quantile``
    trade-off, with bounded memory and O(#buckets) reads."""

    kind = "histogram"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = (),
                 bounds: Optional[Sequence[float]] = None):
        self.name = name
        self.labels = labels
        self.bounds: Tuple[float, ...] = tuple(bounds) if bounds is not None \
            else _DEFAULT_BOUNDS
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        # bisect by hand: bounds are tiny (26) and this avoids an import
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self.counts[lo] += 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (q in [0, 1]); None when empty."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
            vmin, vmax = self.min, self.max
        if not total:
            return None
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else min(
                    vmin if vmin is not None else 0.0, self.bounds[0])
                hi = self.bounds[i] if i < len(self.bounds) else (
                    vmax if vmax is not None else self.bounds[-1])
                frac = (rank - cum) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
            cum += c
        return vmax

    @property
    def mean(self) -> Optional[float]:
        return (self.sum / self.count) if self.count else None

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": self.kind, "count": self.count,
                               "sum": self.sum, "min": self.min,
                               "max": self.max, "mean": self.mean}
        out.update(self.percentiles())
        return out

    def render(self) -> List[str]:
        base = dict(self.labels)
        lines: List[str] = []
        cum = 0
        with self._lock:
            counts = list(self.counts)
            count, total = self.count, self.sum
        for bound, c in zip(self.bounds, counts):
            cum += c
            lbl = _render_labels(_label_key({**base, "le": repr(bound)}))
            lines.append(f"{self.name}_bucket{lbl} {cum}")
        lbl = _render_labels(_label_key({**base, "le": "+Inf"}))
        lines.append(f"{self.name}_bucket{lbl} {count}")
        plain = _render_labels(self.labels)
        lines.append(f"{self.name}_sum{plain} {total}")
        lines.append(f"{self.name}_count{plain} {count}")
        return lines


class MetricsRegistry:
    """Instrument container: create-or-get by (name, labels), render all."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                Counter] = {}

    def _get(self, cls, name: str, labels: Dict[str, str], **kw):
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, key[1], **kw)
                self._instruments[key] = inst
            elif type(inst) is not cls:
                # exact-type check: isinstance would hand a Gauge to a
                # counter() caller (Gauge subclasses Counter), silently
                # dropping monotonicity enforcement
                raise TypeError(
                    f"metric '{name}' already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}")
        return inst

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None,
                  **labels: str) -> Histogram:
        return self._get(Histogram, name, labels, bounds=bounds)

    def instruments(self) -> List[Counter]:
        with self._lock:
            return list(self._instruments.values())

    def family_total(self, name: str) -> float:
        """Sum of a counter/gauge family across ALL label sets (e.g. the
        per-model ``dl4j_tpu_train_steps_total`` counters)."""
        return sum(i.value for i in self.instruments()
                   if i.name == name and not isinstance(i, Histogram))

    def merged_histogram(self, name: str) -> Histogram:
        """A synthetic histogram merging every label set of ``name`` —
        the cross-model latency distribution summaries read."""
        out: Optional[Histogram] = None
        for inst in self.instruments():
            if inst.name != name or not isinstance(inst, Histogram):
                continue
            if out is None:
                out = Histogram(name, bounds=inst.bounds)
            if inst.bounds != out.bounds:
                continue  # families share bounds; a stray mismatch is skipped
            with inst._lock:
                counts = list(inst.counts)
                c, s, mn, mx = inst.count, inst.sum, inst.min, inst.max
            for i, v in enumerate(counts):
                out.counts[i] += v
            out.count += c
            out.sum += s
            if mn is not None:
                out.min = mn if out.min is None else min(out.min, mn)
            if mx is not None:
                out.max = mx if out.max is None else max(out.max, mx)
        return out if out is not None else Histogram(name)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able {rendered-name: instrument snapshot}."""
        out: Dict[str, Any] = {}
        for inst in self.instruments():
            out[f"{inst.name}{_render_labels(inst.labels)}"] = inst.snapshot()
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (one ``# TYPE`` line per family)."""
        by_name: Dict[str, List[Counter]] = {}
        for inst in self.instruments():
            by_name.setdefault(inst.name, []).append(inst)
        lines: List[str] = []
        for name in sorted(by_name):
            family = by_name[name]
            lines.append(f"# TYPE {name} {family[0].kind}")
            for inst in sorted(family, key=lambda i: i.labels):
                lines.extend(inst.render())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# process-wide default registry + JSONL event log
# ---------------------------------------------------------------------------

_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()

# the metric catalog every build exposes, registered eagerly so /metrics
# and snapshots always carry the names (zero-valued until traffic arrives)
_CORE_METRICS: Tuple[Tuple[str, str], ...] = (
    ("counter", "dl4j_tpu_recompiles_total"),
    ("counter", "dl4j_tpu_train_steps_total"),
    ("counter", "dl4j_tpu_train_examples_total"),
    ("counter", "dl4j_tpu_host_to_device_transfers_total"),
    ("counter", "dl4j_tpu_serving_requests_total"),
    ("counter", "dl4j_tpu_serving_batches_total"),
    ("counter", "dl4j_tpu_serving_rows_total"),
    ("histogram", "dl4j_tpu_train_step_seconds"),
    ("histogram", "dl4j_tpu_serving_request_seconds"),
    ("histogram", "dl4j_tpu_serving_queue_wait_seconds"),
    ("histogram", "dl4j_tpu_serving_batch_seconds"),
    ("histogram", "dl4j_tpu_serving_batch_occupancy"),
    ("gauge", "dl4j_tpu_serving_queue_depth"),
    # generative serving (serving/ — docs/SERVING.md). evicted_total grows
    # reason-labelled children next to this eagerly-registered base.
    ("counter", "dl4j_tpu_serving_admitted_total"),
    ("counter", "dl4j_tpu_serving_evicted_total"),
    ("counter", "dl4j_tpu_serving_generated_tokens_total"),
    ("gauge", "dl4j_tpu_serving_slot_occupancy"),
    ("histogram", "dl4j_tpu_serving_decode_step_seconds"),
    ("histogram", "dl4j_tpu_serving_ttft_seconds"),
    ("histogram", "dl4j_tpu_serving_intertoken_seconds"),
    # robustness tier (faults/ + the engine supervisor + durable
    # checkpoints — docs/ROBUSTNESS.md). faults_injected_total grows
    # point-labelled children next to this eagerly-registered base.
    ("counter", "dl4j_tpu_faults_injected_total"),
    ("counter", "dl4j_tpu_serving_engine_restarts_total"),
    ("counter", "dl4j_tpu_serving_retries_total"),
    ("gauge", "dl4j_tpu_serving_stopped_cleanly"),
    ("counter", "dl4j_tpu_checkpoint_saves_total"),
    ("counter", "dl4j_tpu_checkpoint_corrupt_total"),
    ("counter", "dl4j_tpu_checkpoint_fallback_total"),
    # preemption-proof training (parallel/checkpoint.py async writer +
    # parallel/supervisor.py — docs/ROBUSTNESS.md § Preemption-proof
    # training)
    ("counter", "dl4j_tpu_ckpt_async_saves_total"),
    ("counter", "dl4j_tpu_ckpt_dropped_total"),
    ("counter", "dl4j_tpu_ckpt_blocked_total"),
    ("counter", "dl4j_tpu_ckpt_resumes_total"),
    ("counter", "dl4j_tpu_train_preemptions_total"),
    ("gauge", "dl4j_tpu_ckpt_queue_depth"),
    ("histogram", "dl4j_tpu_ckpt_write_seconds"),
    # SLO admission frontend (serving/frontend.py — docs/SERVING.md).
    # admitted/shed/degraded/transitions grow labelled children
    # ({class}, {class,reason}, {to}) next to these eagerly-registered
    # bases; the state gauge carries the OVERLOAD_STATES index.
    ("gauge", "dl4j_tpu_slo_state"),
    ("gauge", "dl4j_tpu_slo_breaker_open"),
    ("counter", "dl4j_tpu_slo_admitted_total"),
    ("counter", "dl4j_tpu_slo_shed_total"),
    ("counter", "dl4j_tpu_slo_degraded_total"),
    ("counter", "dl4j_tpu_slo_transitions_total"),
)


def default_registry() -> MetricsRegistry:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
            for kind, name in _CORE_METRICS:
                getattr(_DEFAULT, kind)(name)
        return _DEFAULT


def reset_default_registry() -> MetricsRegistry:
    """Drop every instrument and start a fresh default registry (tests)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
    return default_registry()


OBS_LOG_ENV = "DL4J_TPU_OBS_LOG"

_LOG_LOCK = threading.Lock()
# paths whose writes failed: logging to them is DISABLED (with one warning
# per path) — an unwritable log or a full disk must cost one syscall per
# event forever after, not an exception inside a training/serving loop
_LOG_FAILED_PATHS: set = set()


def reset_log_state() -> None:
    """Forget failed JSONL log paths (tests; or after freeing disk)."""
    with _LOG_LOCK:
        _LOG_FAILED_PATHS.clear()


def log_event(kind: str, **fields: Any) -> None:
    """Append one JSONL event to the ``DL4J_TPU_OBS_LOG`` file (no-op when
    the env var is unset). Schema: every line is a JSON object with ``ts``
    (epoch seconds — a timestamp, not a duration), ``kind``, plus the
    kind-specific fields (docs/OBSERVABILITY.md).

    Failure policy: a path that cannot be written (bad path, permissions,
    disk full mid-run) warns ONCE and disables logging to that path for
    the rest of the process — observability must never take down the
    training/serving loop it observes. Pointing the env var at a fresh
    path (or :func:`reset_log_state`) re-enables logging."""
    path = os.environ.get(OBS_LOG_ENV)
    if not path or path in _LOG_FAILED_PATHS:
        return
    rec = {"ts": round(time.time(), 6), "kind": kind}
    rec.update(fields)
    try:
        line = json.dumps(rec, default=str)
    except (TypeError, ValueError):
        line = json.dumps({"ts": rec["ts"], "kind": kind,
                           "error": "unserializable event"})
    try:
        with _LOG_LOCK, open(path, "a", encoding="utf-8") as fh:
            fh.write(line + "\n")
    except OSError as e:
        with _LOG_LOCK:
            first = path not in _LOG_FAILED_PATHS
            _LOG_FAILED_PATHS.add(path)
        if first:
            logger.warning(
                "%s: cannot write %s (%s) — JSONL event logging DISABLED "
                "for this path for the rest of the process", OBS_LOG_ENV,
                path, e)
