"""Model zoo + flagship model families (deeplearning4j-zoo role)."""

from deeplearning4j_tpu.models.zoo import (
    ZooModel,
    LeNet,
    SimpleCNN,
    AlexNet,
    VGG16,
    ResNet50,
    Darknet19,
    UNet,
    TextGenerationLSTM,
    GPT,
    VGG19,
    SqueezeNet,
    Xception,
    TinyYOLO,
    YOLO2,
    InceptionResNetV1,
)
from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
from deeplearning4j_tpu.models.hub import ModelHub
