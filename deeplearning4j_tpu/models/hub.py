"""Model hub — the omnihub role (pretrained model registry).

Reference parity: omnihub/ (newer reference tags) downloads pretrained
models into a local cache by name; the zoo's ``initPretrained`` pulls
weights the same way. This environment is zero-egress, so the hub is a
LOCAL directory registry (point ``DL4J_TPU_HUB`` at a shared/network mount
for team distribution — the interchange property the reference's HTTP hub
provides). Every publish writes a manifest with a SHA-256 per artifact;
loads verify it, so a torn copy can never masquerade as a model.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

_DEFAULT_ROOT = os.path.join(os.path.expanduser("~"), ".dl4j_tpu_hub")


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ModelHub:
    """Local pretrained-model registry (omnihub analog)."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get("DL4J_TPU_HUB", _DEFAULT_ROOT)
        os.makedirs(self.root, exist_ok=True)

    # ---------------------------------------------------------------- paths
    @staticmethod
    def _valid_name(name: str) -> bool:
        # block path traversal, not dots: "resnet50-v1.5" is a fine name
        return bool(name) and "/" not in name and "\\" not in name \
            and ".." not in name and not name.startswith(".")

    def _dir(self, name: str) -> str:
        if not self._valid_name(name):
            raise ValueError(f"invalid model name {name!r}")
        return os.path.join(self.root, name)

    def _manifest_path(self, name: str) -> str:
        return os.path.join(self._dir(name), "manifest.json")

    # ------------------------------------------------------------------ api
    def publish(self, name: str, net, *,
                metadata: Optional[Dict[str, Any]] = None) -> str:
        """Save a MultiLayerNetwork or ComputationGraph under ``name``
        (omnihub push / zoo pretrained-artifact role). Returns the model
        directory."""
        from deeplearning4j_tpu.nn.serde import save_model
        from deeplearning4j_tpu.nn.graph import ComputationGraph, save_graph
        from deeplearning4j_tpu.models.gpt import GptModel, save_gpt

        d = self._dir(name)
        os.makedirs(d, exist_ok=True)
        artifact = os.path.join(d, "model.zip")
        if isinstance(net, ComputationGraph):
            save_graph(net, artifact)
            kind = "ComputationGraph"
        elif isinstance(net, GptModel):
            save_gpt(net, artifact)
            kind = "GptModel"
        else:
            save_model(net, artifact)
            kind = "MultiLayerNetwork"
        manifest = {
            "name": name,
            "kind": kind,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "artifacts": {"model.zip": _sha256(artifact)},
            "metadata": metadata or {},
        }
        # atomic publish: load() checksum-verifies against this manifest,
        # so a torn write would brick the whole entry — write the tmp,
        # fsync, then os.replace into place
        mpath = self._manifest_path(name)
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, mpath)
        return d

    def load(self, name: str):
        """Load + checksum-verify a published model."""
        from deeplearning4j_tpu.nn.serde import restore_model
        from deeplearning4j_tpu.nn.graph import restore_graph
        from deeplearning4j_tpu.models.gpt import restore_gpt

        manifest = self.manifest(name)
        d = self._dir(name)
        for fname, want in manifest["artifacts"].items():
            got = _sha256(os.path.join(d, fname))
            if got != want:
                raise IOError(
                    f"checksum mismatch for {name}/{fname}: manifest "
                    f"{want[:12]}…, file {got[:12]}… — artifact corrupt or "
                    f"tampered")
        artifact = os.path.join(d, "model.zip")
        if manifest["kind"] == "ComputationGraph":
            return restore_graph(artifact)
        if manifest["kind"] == "GptModel":
            return restore_gpt(artifact)
        return restore_model(artifact)

    def manifest(self, name: str) -> Dict[str, Any]:
        p = self._manifest_path(name)
        if not os.path.exists(p):
            raise KeyError(
                f"no model '{name}' in hub {self.root} — "
                f"known: {self.list_models()}")
        with open(p) as f:
            return json.load(f)

    def list_models(self) -> List[str]:
        # tolerate stray files on shared mounts (.DS_Store, README, …)
        return sorted(
            n for n in os.listdir(self.root)
            if self._valid_name(n)
            and os.path.exists(self._manifest_path(n)))

    def delete(self, name: str) -> None:
        import shutil

        d = self._dir(name)
        if os.path.exists(d):
            shutil.rmtree(d)
