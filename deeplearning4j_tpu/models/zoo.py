"""Model zoo — deeplearning4j-zoo parity.

Reference parity: org/deeplearning4j/zoo/model/* — LeNet, AlexNet, VGG16/19,
ResNet50, SqueezeNet, Darknet19, TinyYOLO, UNet, SimpleCNN,
InceptionResNetV1, TextGenerationLSTM. Each ZooModel builds a
MultiLayerNetwork or ComputationGraph config; pretrained-weight download does
not exist in this offline environment (initPretrained raises, like the
reference does for models without published weights).

All models use the NHWC internal layout; input shapes quoted in NCHW in the
reference docs map to InputType.convolutional(h, w, c) here.
"""

from __future__ import annotations

from typing import Optional, Tuple

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn import conf as C
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ElementWiseVertex, GraphBuilder, MergeVertex, ScaleVertex, graph_builder,
)


class ZooModel:
    """ZooModel.java analog."""

    def init(self):
        raise NotImplementedError

    def init_pretrained(self):
        raise NotImplementedError(
            "pretrained weights unavailable offline; train from scratch or "
            "load a checkpoint zip")

    @staticmethod
    def _builder(seed, updater):
        b = nn.builder().seed(seed).weight_init("relu")
        if updater is not None:
            b = b.updater(updater)
        return b


class LeNet(ZooModel):
    """zoo/model/LeNet.java: 2×(conv5+maxpool) + dense 500 + softmax."""

    def __init__(self, num_classes: int = 10, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (28, 28, 1)):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Adam(learning_rate=1e-3)
        self.input_shape = input_shape

    def init(self) -> nn.MultiLayerNetwork:
        h, w, c = self.input_shape
        conf = (
            self._builder(self.seed, self.updater).list()
            .layer(nn.ConvolutionLayer(n_out=20, kernel=(5, 5), activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.ConvolutionLayer(n_out=50, kernel=(5, 5), activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.DenseLayer(n_out=500, activation="relu"))
            .layer(nn.OutputLayer(n_out=self.num_classes, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.convolutional_flat(h, w, c))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()


class SimpleCNN(ZooModel):
    """zoo/model/SimpleCNN.java: small conv stack for sanity workloads."""

    def __init__(self, num_classes: int = 10, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (48, 48, 3)):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Adam(learning_rate=1e-3)
        self.input_shape = input_shape

    def init(self) -> nn.MultiLayerNetwork:
        h, w, c = self.input_shape
        conf = (
            self._builder(self.seed, self.updater).list()
            .layer(nn.ConvolutionLayer(n_out=16, kernel=(3, 3), convolution_mode="same",
                                       activation="relu"))
            .layer(nn.BatchNormalization())
            .layer(nn.ConvolutionLayer(n_out=16, kernel=(3, 3), convolution_mode="same",
                                       activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.ConvolutionLayer(n_out=32, kernel=(3, 3), convolution_mode="same",
                                       activation="relu"))
            .layer(nn.BatchNormalization())
            .layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(nn.GlobalPoolingLayer(pooling_type="avg"))
            .layer(nn.OutputLayer(n_out=self.num_classes, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.convolutional(h, w, c))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()


class AlexNet(ZooModel):
    """zoo/model/AlexNet.java (single-tower variant)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (224, 224, 3)):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Nesterovs(learning_rate=1e-2, momentum=0.9)
        self.input_shape = input_shape

    def init(self) -> nn.MultiLayerNetwork:
        h, w, c = self.input_shape
        conf = (
            self._builder(self.seed, self.updater).list()
            .layer(nn.ConvolutionLayer(n_out=96, kernel=(11, 11), stride=(4, 4),
                                       activation="relu"))
            .layer(nn.LocalResponseNormalization())
            .layer(nn.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)))
            .layer(nn.ConvolutionLayer(n_out=256, kernel=(5, 5), convolution_mode="same",
                                       activation="relu"))
            .layer(nn.LocalResponseNormalization())
            .layer(nn.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)))
            .layer(nn.ConvolutionLayer(n_out=384, kernel=(3, 3), convolution_mode="same",
                                       activation="relu"))
            .layer(nn.ConvolutionLayer(n_out=384, kernel=(3, 3), convolution_mode="same",
                                       activation="relu"))
            .layer(nn.ConvolutionLayer(n_out=256, kernel=(3, 3), convolution_mode="same",
                                       activation="relu"))
            .layer(nn.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)))
            .layer(nn.DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(nn.DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(nn.OutputLayer(n_out=self.num_classes, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.convolutional(h, w, c))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()


class VGG16(ZooModel):
    """zoo/model/VGG16.java: 13 conv + 3 dense."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (224, 224, 3)):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Nesterovs(learning_rate=1e-2, momentum=0.9)
        self.input_shape = input_shape

    def init(self) -> nn.MultiLayerNetwork:
        h, w, c = self.input_shape
        b = self._builder(self.seed, self.updater).list()
        for n_out, reps in [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]:
            for _ in range(reps):
                b = b.layer(nn.ConvolutionLayer(n_out=n_out, kernel=(3, 3),
                                                convolution_mode="same",
                                                activation="relu"))
            b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        conf = (
            b.layer(nn.DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(nn.DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(nn.OutputLayer(n_out=self.num_classes, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.convolutional(h, w, c))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()


class ResNet50(ZooModel):
    """zoo/model/ResNet50.java: bottleneck residual DAG (ComputationGraph).

    conv1 7×7/2 → maxpool 3×3/2 → stages [3, 4, 6, 3] of bottleneck blocks
    (1×1 → 3×3 → 1×1 ×4 channels, identity or projection shortcut) → global
    avg pool → softmax. BatchNorm after every conv, relu after the residual
    add (standard v1 arrangement, as the reference builds it).
    """

    def __init__(self, num_classes: int = 1000, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (224, 224, 3),
                 dtype: str = "float32", fused_blocks: bool = False):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Nesterovs(learning_rate=1e-1, momentum=0.9)
        self.input_shape = input_shape
        self.dtype = dtype
        # True: build from the FusedBottleneck layer (nn/fused_blocks.py,
        # Pallas conv+BN fusion on the 1×1 convs — same math, equality-
        # tested). Measured on the v5e (docs/PERF_ANALYSIS.md round 5): the
        # composed graph is FASTER there (XLA's own fusions beat both the
        # Pallas kernel and the 2-D dot reformulation in situ), so the
        # default stays False; the layer remains as the kernel-evidence
        # prototype and for future TPU generations/toolchains.
        self.fused_blocks = fused_blocks

    def _bottleneck(self, b: GraphBuilder, name: str, inp: str, filters: int,
                    stride: int, project: bool) -> str:
        """One bottleneck block; returns output node name."""
        s = (stride, stride)
        b.add_layer(f"{name}_c1", nn.ConvolutionLayer(
            n_out=filters, kernel=(1, 1), stride=s, convolution_mode="same",
            activation="identity", has_bias=False), inp)
        b.add_layer(f"{name}_bn1", nn.BatchNormalization(activation="relu"), f"{name}_c1")
        b.add_layer(f"{name}_c2", nn.ConvolutionLayer(
            n_out=filters, kernel=(3, 3), convolution_mode="same",
            activation="identity", has_bias=False), f"{name}_bn1")
        b.add_layer(f"{name}_bn2", nn.BatchNormalization(activation="relu"), f"{name}_c2")
        b.add_layer(f"{name}_c3", nn.ConvolutionLayer(
            n_out=4 * filters, kernel=(1, 1), convolution_mode="same",
            activation="identity", has_bias=False), f"{name}_bn2")
        b.add_layer(f"{name}_bn3", nn.BatchNormalization(activation="identity"), f"{name}_c3")
        if project:
            b.add_layer(f"{name}_sc", nn.ConvolutionLayer(
                n_out=4 * filters, kernel=(1, 1), stride=s, convolution_mode="same",
                activation="identity", has_bias=False), inp)
            b.add_layer(f"{name}_scbn", nn.BatchNormalization(activation="identity"),
                        f"{name}_sc")
            shortcut = f"{name}_scbn"
        else:
            shortcut = inp
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), f"{name}_bn3", shortcut)
        b.add_layer(f"{name}_out", nn.ActivationLayer(activation="relu"), f"{name}_add")
        return f"{name}_out"

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        b = (graph_builder().seed(self.seed).updater(self.updater)
             .weight_init("relu").dtype(self.dtype)
             .add_inputs("input")
             .set_input_types(input=nn.InputType.convolutional(h, w, c)))
        b.add_layer("conv1", nn.ConvolutionLayer(
            n_out=64, kernel=(7, 7), stride=(2, 2), convolution_mode="same",
            activation="identity", has_bias=False,
            s2d_stem=(h % 2 == 0 and w % 2 == 0)), "input")
        b.add_layer("bn1", nn.BatchNormalization(activation="relu"), "conv1")
        b.add_layer("pool1", nn.SubsamplingLayer(
            kernel=(3, 3), stride=(2, 2), convolution_mode="same"), "bn1")
        node = "pool1"
        fused = self.fused_blocks is True
        stages = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
        for si, (filters, blocks, stride) in enumerate(stages):
            for bi in range(blocks):
                if fused:
                    name = f"res{si}_{bi}"
                    b.add_layer(name, nn.FusedBottleneck(
                        filters=filters, stride=stride if bi == 0 else 1,
                        project=(bi == 0)), node)
                    node = name
                else:
                    node = self._bottleneck(
                        b, f"res{si}_{bi}", node, filters,
                        stride if bi == 0 else 1, project=(bi == 0))
        b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"), node)
        b.add_layer("fc", nn.OutputLayer(n_out=self.num_classes, activation="softmax",
                                         loss="mcxent"), "gap")
        b.set_outputs("fc")
        return ComputationGraph(b.build()).init()


class Darknet19(ZooModel):
    """zoo/model/Darknet19.java: 19-conv backbone (YOLO family)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (224, 224, 3)):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Nesterovs(learning_rate=1e-3, momentum=0.9)
        self.input_shape = input_shape

    def init(self) -> nn.MultiLayerNetwork:
        h, w, c = self.input_shape

        def conv(b, n, k):
            return b.layer(nn.ConvolutionLayer(
                n_out=n, kernel=(k, k), convolution_mode="same",
                activation="identity", has_bias=False)) \
                .layer(nn.BatchNormalization(activation="leakyrelu"))

        b = self._builder(self.seed, self.updater).list()
        b = conv(b, 32, 3)
        b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        b = conv(b, 64, 3)
        b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        b = conv(conv(conv(b, 128, 3), 64, 1), 128, 3)
        b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        b = conv(conv(conv(b, 256, 3), 128, 1), 256, 3)
        b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        b = conv(conv(conv(conv(conv(b, 512, 3), 256, 1), 512, 3), 256, 1), 512, 3)
        b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        b = conv(conv(conv(conv(conv(b, 1024, 3), 512, 1), 1024, 3), 512, 1), 1024, 3)
        conf = (
            b.layer(nn.ConvolutionLayer(n_out=self.num_classes, kernel=(1, 1),
                                        convolution_mode="same", activation="identity"))
            .layer(nn.GlobalPoolingLayer(pooling_type="avg"))
            .layer(nn.LossLayer(activation="softmax", loss="mcxent"))
            .set_input_type(nn.InputType.convolutional(h, w, c))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()


class UNet(ZooModel):
    """zoo/model/UNet.java: encoder-decoder with skip connections (DAG)."""

    def __init__(self, n_channels_out: int = 1, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (128, 128, 1), base: int = 16):
        self.n_channels_out = n_channels_out
        self.seed = seed
        self.updater = updater or nn.Adam(learning_rate=1e-3)
        self.input_shape = input_shape
        self.base = base

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        f = self.base
        b = (graph_builder().seed(self.seed).updater(self.updater).weight_init("relu")
             .add_inputs("input")
             .set_input_types(input=nn.InputType.convolutional(h, w, c)))

        def double_conv(name, inp, n):
            b.add_layer(f"{name}_a", nn.ConvolutionLayer(
                n_out=n, kernel=(3, 3), convolution_mode="same", activation="relu"), inp)
            b.add_layer(f"{name}_b", nn.ConvolutionLayer(
                n_out=n, kernel=(3, 3), convolution_mode="same", activation="relu"),
                f"{name}_a")
            return f"{name}_b"

        e1 = double_conv("enc1", "input", f)
        b.add_layer("pool1", nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)), e1)
        e2 = double_conv("enc2", "pool1", f * 2)
        b.add_layer("pool2", nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)), e2)
        mid = double_conv("mid", "pool2", f * 4)
        b.add_layer("up2", nn.Upsampling2D(size=(2, 2)), mid)
        b.add_vertex("cat2", MergeVertex(), "up2", e2)
        d2 = double_conv("dec2", "cat2", f * 2)
        b.add_layer("up1", nn.Upsampling2D(size=(2, 2)), d2)
        b.add_vertex("cat1", MergeVertex(), "up1", e1)
        d1 = double_conv("dec1", "cat1", f)
        b.add_layer("out", nn.ConvolutionLayer(
            n_out=self.n_channels_out, kernel=(1, 1), convolution_mode="same",
            activation="sigmoid"), d1)
        b.set_outputs("out")
        return ComputationGraph(b.build()).init()


class TextGenerationLSTM(ZooModel):
    """zoo/model/TextGenerationLSTM.java: char-level 2×LSTM."""

    def __init__(self, vocab_size: int, hidden: int = 256, seed: int = 123, updater=None):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.seed = seed
        self.updater = updater or nn.RmsProp(learning_rate=1e-2)

    def init(self) -> nn.MultiLayerNetwork:
        conf = (
            nn.builder().seed(self.seed).updater(self.updater).weight_init("xavier")
            .list()
            .layer(nn.LSTM(n_out=self.hidden, activation="tanh"))
            .layer(nn.LSTM(n_out=self.hidden, activation="tanh"))
            .layer(nn.RnnOutputLayer(n_out=self.vocab_size, activation="softmax",
                                     loss="mcxent"))
            .set_input_type(nn.InputType.recurrent(self.vocab_size))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()


class GPT(ZooModel):
    """Decoder-only generative transformer (models/gpt.py) — the zoo entry
    for the continuous-batching serving tier (docs/SERVING.md). No reference
    Java analog: the reference zoo stops at TextGenerationLSTM; this is the
    TPU-native step past it. ``init()`` returns a ``GptModel`` (raw-pytree
    model like BERT, not a MultiLayerNetwork); serve it through
    ``serving.GenerativeEngine`` / ``ParallelInference.generative``."""

    def __init__(self, preset: str = "tiny", seed: int = 0, **overrides):
        from deeplearning4j_tpu.models.gpt import GptConfig

        if preset not in ("tiny", "base"):
            raise ValueError(f"unknown GPT preset {preset!r} "
                             "(known: tiny, base)")
        self.cfg = (GptConfig.tiny(**overrides) if preset == "tiny"
                    else GptConfig.base(**overrides))
        self.seed = seed

    def init(self):
        from deeplearning4j_tpu.models.gpt import GptModel

        return GptModel(self.cfg, seed=self.seed)

    def init_draft(self, seed: int = None, **overrides):
        """The paired DRAFT model for speculative decoding against this
        target (docs/SERVING.md § Speculative decoding): GPT-tiny dims
        sharing the target's vocab/eos/max_position —
        ``GenerativeEngine(model, spec_k=K, draft_model=zoo_gpt.
        init_draft())`` is the whole wiring. A production draft loads
        trained weights into the same config via ``restore_gpt``."""
        from deeplearning4j_tpu.models.gpt import GptModel, draft_config_for

        return GptModel(draft_config_for(self.cfg, **overrides),
                        seed=self.seed if seed is None else seed)


class VGG19(ZooModel):
    """zoo/model/VGG19.java: 16 conv + 3 dense (VGG16 with one extra conv
    in each of the last three stages)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (224, 224, 3)):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Nesterovs(learning_rate=1e-2, momentum=0.9)
        self.input_shape = input_shape

    def init(self) -> nn.MultiLayerNetwork:
        h, w, c = self.input_shape
        b = self._builder(self.seed, self.updater).list()
        for n_out, reps in [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]:
            for _ in range(reps):
                b = b.layer(nn.ConvolutionLayer(n_out=n_out, kernel=(3, 3),
                                                convolution_mode="same",
                                                activation="relu"))
            b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        conf = (
            b.layer(nn.DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(nn.DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(nn.OutputLayer(n_out=self.num_classes, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(nn.InputType.convolutional(h, w, c))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()


class SqueezeNet(ZooModel):
    """zoo/model/SqueezeNet.java (v1.1): fire modules — 1×1 squeeze then
    parallel 1×1/3×3 expands concatenated (MergeVertex DAG)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (227, 227, 3)):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Adam(learning_rate=1e-3)
        self.input_shape = input_shape

    def _fire(self, b: GraphBuilder, name: str, inp: str, squeeze: int,
              expand: int) -> str:
        b.add_layer(f"{name}_sq", nn.ConvolutionLayer(
            n_out=squeeze, kernel=(1, 1), activation="relu",
            convolution_mode="same"), inp)
        b.add_layer(f"{name}_e1", nn.ConvolutionLayer(
            n_out=expand, kernel=(1, 1), activation="relu",
            convolution_mode="same"), f"{name}_sq")
        b.add_layer(f"{name}_e3", nn.ConvolutionLayer(
            n_out=expand, kernel=(3, 3), activation="relu",
            convolution_mode="same"), f"{name}_sq")
        b.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_cat"

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        b = (graph_builder().seed(self.seed).updater(self.updater)
             .weight_init("relu")
             .add_inputs("input")
             .set_input_types(input=nn.InputType.convolutional(h, w, c)))
        b.add_layer("conv1", nn.ConvolutionLayer(
            n_out=64, kernel=(3, 3), stride=(2, 2), activation="relu",
            convolution_mode="valid"), "input")
        b.add_layer("pool1", nn.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)),
                    "conv1")
        node = self._fire(b, "fire2", "pool1", 16, 64)
        node = self._fire(b, "fire3", node, 16, 64)
        b.add_layer("pool3", nn.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)),
                    node)
        node = self._fire(b, "fire4", "pool3", 32, 128)
        node = self._fire(b, "fire5", node, 32, 128)
        b.add_layer("pool5", nn.SubsamplingLayer(kernel=(3, 3), stride=(2, 2)),
                    node)
        node = self._fire(b, "fire6", "pool5", 48, 192)
        node = self._fire(b, "fire7", node, 48, 192)
        node = self._fire(b, "fire8", node, 64, 256)
        node = self._fire(b, "fire9", node, 64, 256)
        b.add_layer("drop9", nn.DropoutLayer(rate=0.5), node)
        b.add_layer("conv10", nn.ConvolutionLayer(
            n_out=self.num_classes, kernel=(1, 1), activation="relu",
            convolution_mode="same"), "drop9")
        b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"), "conv10")
        b.add_layer("out", nn.LossLayer(loss="mcxent", activation="softmax"), "gap")
        b.set_outputs("out")
        return ComputationGraph(b.build()).init()


class Xception(ZooModel):
    """zoo/model/Xception.java: separable-conv stacks with residual
    projection shortcuts (entry/middle/exit flows; middle-flow repeat count
    is configurable so tests stay small)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (299, 299, 3),
                 middle_repeats: int = 8):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.Adam(learning_rate=1e-3)
        self.input_shape = input_shape
        self.middle_repeats = middle_repeats

    def _sep_bn(self, b, name, inp, n_out, relu_first=True):
        if relu_first:
            b.add_layer(f"{name}_act", nn.ActivationLayer(activation="relu"), inp)
            inp = f"{name}_act"
        b.add_layer(f"{name}_sep", nn.SeparableConvolution2D(
            n_out=n_out, kernel=(3, 3), convolution_mode="same",
            activation="identity", has_bias=False), inp)
        b.add_layer(f"{name}_bn", nn.BatchNormalization(activation="identity"),
                    f"{name}_sep")
        return f"{name}_bn"

    def _entry_block(self, b, name, inp, n_out, first_relu=True):
        node = self._sep_bn(b, f"{name}_a", inp, n_out, relu_first=first_relu)
        node = self._sep_bn(b, f"{name}_b", node, n_out)
        b.add_layer(f"{name}_pool", nn.SubsamplingLayer(
            kernel=(3, 3), stride=(2, 2), convolution_mode="same"), node)
        b.add_layer(f"{name}_sc", nn.ConvolutionLayer(
            n_out=n_out, kernel=(1, 1), stride=(2, 2), convolution_mode="same",
            activation="identity", has_bias=False), inp)
        b.add_layer(f"{name}_scbn", nn.BatchNormalization(activation="identity"),
                    f"{name}_sc")
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"),
                     f"{name}_pool", f"{name}_scbn")
        return f"{name}_add"

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        b = (graph_builder().seed(self.seed).updater(self.updater)
             .weight_init("relu")
             .add_inputs("input")
             .set_input_types(input=nn.InputType.convolutional(h, w, c)))
        b.add_layer("conv1", nn.ConvolutionLayer(
            n_out=32, kernel=(3, 3), stride=(2, 2), activation="identity",
            convolution_mode="same", has_bias=False), "input")
        b.add_layer("bn1", nn.BatchNormalization(activation="relu"), "conv1")
        b.add_layer("conv2", nn.ConvolutionLayer(
            n_out=64, kernel=(3, 3), activation="identity",
            convolution_mode="same", has_bias=False), "bn1")
        b.add_layer("bn2", nn.BatchNormalization(activation="relu"), "conv2")
        node = self._entry_block(b, "entry1", "bn2", 128, first_relu=False)
        node = self._entry_block(b, "entry2", node, 256)
        node = self._entry_block(b, "entry3", node, 728)
        for i in range(self.middle_repeats):
            inp = node
            m = self._sep_bn(b, f"mid{i}_a", inp, 728)
            m = self._sep_bn(b, f"mid{i}_b", m, 728)
            m = self._sep_bn(b, f"mid{i}_c", m, 728)
            b.add_vertex(f"mid{i}_add", ElementWiseVertex(op="add"), m, inp)
            node = f"mid{i}_add"
        # exit block (Xception.java block13): sepconv 728 then 1024, with a
        # 1024-channel projection shortcut
        inp = node
        node = self._sep_bn(b, "exit1_a", inp, 728)
        node = self._sep_bn(b, "exit1_b", node, 1024)
        b.add_layer("exit1_pool", nn.SubsamplingLayer(
            kernel=(3, 3), stride=(2, 2), convolution_mode="same"), node)
        b.add_layer("exit1_sc", nn.ConvolutionLayer(
            n_out=1024, kernel=(1, 1), stride=(2, 2), convolution_mode="same",
            activation="identity", has_bias=False), inp)
        b.add_layer("exit1_scbn", nn.BatchNormalization(activation="identity"),
                    "exit1_sc")
        b.add_vertex("exit1_add", ElementWiseVertex(op="add"),
                     "exit1_pool", "exit1_scbn")
        node = "exit1_add"
        node = self._sep_bn(b, "exit2", node, 1536)
        b.add_layer("exit2_relu", nn.ActivationLayer(activation="relu"), node)
        node = self._sep_bn(b, "exit3", "exit2_relu", 2048)
        b.add_layer("exit3_relu", nn.ActivationLayer(activation="relu"), node)
        b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"),
                    "exit3_relu")
        b.add_layer("fc", nn.OutputLayer(n_out=self.num_classes,
                                         activation="softmax", loss="mcxent"),
                    "gap")
        b.set_outputs("fc")
        return ComputationGraph(b.build()).init()


class TinyYOLO(ZooModel):
    """zoo/model/TinyYOLO.java: darknet-tiny backbone → 1×1 detection conv
    emitting B·(5+C) channels per cell.

    The reference appends Yolo2OutputLayer (anchor-box decode + multi-part
    YOLOv2 loss); here the head is the raw detection tensor plus
    ``yolo_loss`` implementing the same sum-squared objective
    (coords/obj/noobj/class) against (N, H, W, B, 5+C) targets — training
    runs through MultiLayerNetwork.fit with this loss via LossLayer("mse")
    replaced by the external objective (see tests)."""

    def __init__(self, num_classes: int = 20, num_boxes: int = 5,
                 seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (416, 416, 3)):
        self.num_classes = num_classes
        self.num_boxes = num_boxes
        self.seed = seed
        self.updater = updater or nn.Adam(learning_rate=1e-3)
        self.input_shape = input_shape

    def init(self) -> nn.MultiLayerNetwork:
        h, w, c = self.input_shape
        b = self._builder(self.seed, self.updater).list()
        filters = [16, 32, 64, 128, 256]
        for f in filters:
            b = b.layer(nn.ConvolutionLayer(
                n_out=f, kernel=(3, 3), convolution_mode="same",
                activation="identity", has_bias=False))
            b = b.layer(nn.BatchNormalization(activation="leakyrelu"))
            b = b.layer(nn.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        for f in (512, 1024):
            b = b.layer(nn.ConvolutionLayer(
                n_out=f, kernel=(3, 3), convolution_mode="same",
                activation="identity", has_bias=False))
            b = b.layer(nn.BatchNormalization(activation="leakyrelu"))
        depth = self.num_boxes * (5 + self.num_classes)
        conf = (
            b.layer(nn.ConvolutionLayer(n_out=depth, kernel=(1, 1),
                                        convolution_mode="same",
                                        activation="identity"))
            .set_input_type(nn.InputType.convolutional(h, w, c))
            .build()
        )
        return nn.MultiLayerNetwork(conf).init()

    def yolo_loss(self, pred, target, *, lambda_coord: float = 5.0,
                  lambda_noobj: float = 0.5):
        """YOLOv2-style sum-squared loss (Yolo2OutputLayer.computeScore
        analog) — delegates to THE shared implementation (ops/losses.yolo2).
        pred: (N, H, W, B*(5+C)) raw head output; target:
        (N, H, W, B, 5+C) with [x, y, w, h, obj, class-onehot...]."""
        from deeplearning4j_tpu.ops.losses import yolo2

        return yolo2(pred, target, None, lambda_coord=lambda_coord,
                     lambda_noobj=lambda_noobj)


class InceptionResNetV1(ZooModel):
    """zoo/model/InceptionResNetV1.java (the FaceNetNN4-family backbone):
    stem → 5× Inception-ResNet-A → Reduction-A → 10× Inception-ResNet-B →
    Reduction-B → 5× Inception-ResNet-C → avgpool → (dropout) → bottleneck
    embedding + classifier. Block repeat counts are constructor-scaled so
    tests run small."""

    def __init__(self, num_classes: int = 128, seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (160, 160, 3),
                 blocks: Tuple[int, int, int] = (5, 10, 5),
                 embedding_size: int = 128):
        self.num_classes = num_classes
        self.seed = seed
        self.updater = updater or nn.RmsProp(learning_rate=0.1)
        self.input_shape = input_shape
        self.blocks = blocks
        self.embedding_size = embedding_size

    def _conv_bn(self, b, name, inp, n_out, kernel, stride=(1, 1),
                 mode="same"):
        b.add_layer(f"{name}_c", nn.ConvolutionLayer(
            n_out=n_out, kernel=kernel, stride=stride, convolution_mode=mode,
            activation="identity", has_bias=False), inp)
        b.add_layer(f"{name}_bn", nn.BatchNormalization(activation="relu"),
                    f"{name}_c")
        return f"{name}_bn"

    def _block_a(self, b, name, inp, channels):
        b1 = self._conv_bn(b, f"{name}_b1", inp, 32, (1, 1))
        b2 = self._conv_bn(b, f"{name}_b2a", inp, 32, (1, 1))
        b2 = self._conv_bn(b, f"{name}_b2b", b2, 32, (3, 3))
        b3 = self._conv_bn(b, f"{name}_b3a", inp, 32, (1, 1))
        b3 = self._conv_bn(b, f"{name}_b3b", b3, 32, (3, 3))
        b3 = self._conv_bn(b, f"{name}_b3c", b3, 32, (3, 3))
        b.add_vertex(f"{name}_cat", MergeVertex(), b1, b2, b3)
        b.add_layer(f"{name}_up", nn.ConvolutionLayer(
            n_out=channels, kernel=(1, 1), convolution_mode="same",
            activation="identity"), f"{name}_cat")
        b.add_vertex(f"{name}_scale", ScaleVertex(scale=0.17), f"{name}_up")
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        b.add_layer(f"{name}_out", nn.ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    def _block_b(self, b, name, inp, channels):
        b1 = self._conv_bn(b, f"{name}_b1", inp, 128, (1, 1))
        b2 = self._conv_bn(b, f"{name}_b2a", inp, 128, (1, 1))
        b2 = self._conv_bn(b, f"{name}_b2b", b2, 128, (1, 7))
        b2 = self._conv_bn(b, f"{name}_b2c", b2, 128, (7, 1))
        b.add_vertex(f"{name}_cat", MergeVertex(), b1, b2)
        b.add_layer(f"{name}_up", nn.ConvolutionLayer(
            n_out=channels, kernel=(1, 1), convolution_mode="same",
            activation="identity"), f"{name}_cat")
        b.add_vertex(f"{name}_scale", ScaleVertex(scale=0.10), f"{name}_up")
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        b.add_layer(f"{name}_out", nn.ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    def _block_c(self, b, name, inp, channels):
        b1 = self._conv_bn(b, f"{name}_b1", inp, 192, (1, 1))
        b2 = self._conv_bn(b, f"{name}_b2a", inp, 192, (1, 1))
        b2 = self._conv_bn(b, f"{name}_b2b", b2, 192, (1, 3))
        b2 = self._conv_bn(b, f"{name}_b2c", b2, 192, (3, 1))
        b.add_vertex(f"{name}_cat", MergeVertex(), b1, b2)
        b.add_layer(f"{name}_up", nn.ConvolutionLayer(
            n_out=channels, kernel=(1, 1), convolution_mode="same",
            activation="identity"), f"{name}_cat")
        b.add_vertex(f"{name}_scale", ScaleVertex(scale=0.20), f"{name}_up")
        b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        b.add_layer(f"{name}_out", nn.ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        na, nb_, nc = self.blocks
        b = (graph_builder().seed(self.seed).updater(self.updater)
             .weight_init("relu")
             .add_inputs("input")
             .set_input_types(input=nn.InputType.convolutional(h, w, c)))
        node = self._conv_bn(b, "stem1", "input", 32, (3, 3), (2, 2), "valid")
        node = self._conv_bn(b, "stem2", node, 32, (3, 3), mode="valid")
        node = self._conv_bn(b, "stem3", node, 64, (3, 3))
        b.add_layer("stem_pool", nn.SubsamplingLayer(kernel=(3, 3),
                                                     stride=(2, 2)), node)
        node = self._conv_bn(b, "stem4", "stem_pool", 80, (1, 1), mode="valid")
        node = self._conv_bn(b, "stem5", node, 192, (3, 3), mode="valid")
        node = self._conv_bn(b, "stem6", node, 256, (3, 3), (2, 2), "valid")
        for i in range(na):
            node = self._block_a(b, f"a{i}", node, 256)
        # Reduction-A
        r1 = self._conv_bn(b, "redA_b1", node, 384, (3, 3), (2, 2), "valid")
        r2 = self._conv_bn(b, "redA_b2a", node, 192, (1, 1))
        r2 = self._conv_bn(b, "redA_b2b", r2, 192, (3, 3))
        r2 = self._conv_bn(b, "redA_b2c", r2, 256, (3, 3), (2, 2), "valid")
        b.add_layer("redA_pool", nn.SubsamplingLayer(
            kernel=(3, 3), stride=(2, 2)), node)
        b.add_vertex("redA_cat", MergeVertex(), r1, r2, "redA_pool")
        node = "redA_cat"  # 384+256+256 = 896 channels
        for i in range(nb_):
            node = self._block_b(b, f"b{i}", node, 896)
        # Reduction-B
        r1 = self._conv_bn(b, "redB_b1a", node, 256, (1, 1))
        r1 = self._conv_bn(b, "redB_b1b", r1, 384, (3, 3), (2, 2), "valid")
        r2 = self._conv_bn(b, "redB_b2a", node, 256, (1, 1))
        r2 = self._conv_bn(b, "redB_b2b", r2, 256, (3, 3), (2, 2), "valid")
        r3 = self._conv_bn(b, "redB_b3a", node, 256, (1, 1))
        r3 = self._conv_bn(b, "redB_b3b", r3, 256, (3, 3))
        r3 = self._conv_bn(b, "redB_b3c", r3, 256, (3, 3), (2, 2), "valid")
        b.add_layer("redB_pool", nn.SubsamplingLayer(
            kernel=(3, 3), stride=(2, 2)), node)
        b.add_vertex("redB_cat", MergeVertex(), r1, r2, r3, "redB_pool")
        node = "redB_cat"  # 384+256+256+896 = 1792 channels
        for i in range(nc):
            node = self._block_c(b, f"c{i}", node, 1792)
        b.add_layer("gap", nn.GlobalPoolingLayer(pooling_type="avg"), node)
        b.add_layer("bottleneck", nn.DenseLayer(
            n_out=self.embedding_size, activation="identity",
            has_bias=False), "gap")
        b.add_layer("emb_norm", nn.BatchNormalization(activation="identity"),
                    "bottleneck")
        b.add_layer("out", nn.OutputLayer(n_out=self.num_classes,
                                          activation="softmax",
                                          loss="mcxent"), "emb_norm")
        b.set_outputs("out")
        return ComputationGraph(b.build()).init()


class YOLO2(ZooModel):
    """zoo/model/YOLO2.java: Darknet19 backbone + the YOLOv2 passthrough —
    the 26×26×512 mid-level features reorg (SpaceToDepth block 2) and
    concatenate with the 13×13×1024 deep path before the detection conv
    emitting B·(5+C) channels per cell (same raw-head convention as
    TinyYOLO; pair with ops.losses yolo_loss for training)."""

    def __init__(self, num_classes: int = 80, num_boxes: int = 5,
                 seed: int = 123, updater=None,
                 input_shape: Tuple[int, int, int] = (416, 416, 3)):
        self.num_classes = num_classes
        self.num_boxes = num_boxes
        self.seed = seed
        self.updater = updater or nn.Adam(learning_rate=1e-3)
        self.input_shape = input_shape

    def init(self) -> ComputationGraph:
        h, w, c = self.input_shape
        b = (graph_builder().seed(self.seed).updater(self.updater)
             .weight_init("relu")
             .add_inputs("input")
             .set_input_types(input=nn.InputType.convolutional(h, w, c)))
        idx = 0

        def conv(inp, n, k):
            nonlocal idx
            idx += 1
            b.add_layer(f"c{idx}", nn.ConvolutionLayer(
                n_out=n, kernel=(k, k), convolution_mode="same",
                activation="identity", has_bias=False), inp)
            b.add_layer(f"bn{idx}", nn.BatchNormalization(
                activation="leakyrelu"), f"c{idx}")
            return f"bn{idx}"

        def pool(inp):
            nonlocal idx
            idx += 1
            b.add_layer(f"p{idx}", nn.SubsamplingLayer(
                kernel=(2, 2), stride=(2, 2)), inp)
            return f"p{idx}"

        x = conv("input", 32, 3)
        x = pool(x)
        x = conv(x, 64, 3)
        x = pool(x)
        x = conv(conv(conv(x, 128, 3), 64, 1), 128, 3)
        x = pool(x)
        x = conv(conv(conv(x, 256, 3), 128, 1), 256, 3)
        x = pool(x)
        x = conv(conv(conv(conv(conv(x, 512, 3), 256, 1), 512, 3),
                      256, 1), 512, 3)
        route = x  # 26×26×512 passthrough source
        x = pool(x)
        x = conv(conv(conv(conv(conv(x, 1024, 3), 512, 1), 1024, 3),
                      512, 1), 1024, 3)
        x = conv(conv(x, 1024, 3), 1024, 3)
        # passthrough: 1×1 squeeze → reorg to 13×13×256 → concat
        sq = conv(route, 64, 1)
        b.add_layer("reorg", nn.conf.SpaceToDepthLayer(block_size=2), sq)
        b.add_vertex("route_cat", MergeVertex(), x, "reorg")
        x = conv("route_cat", 1024, 3)
        depth = self.num_boxes * (5 + self.num_classes)
        b.add_layer("detect", nn.ConvolutionLayer(
            n_out=depth, kernel=(1, 1), convolution_mode="same",
            activation="identity"), x)
        b.set_outputs("detect")
        return ComputationGraph(b.build()).init()
