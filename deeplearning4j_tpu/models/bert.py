"""BERT — the reference's flagship SameDiff workload (BASELINE config[3]).

Reference parity: upstream DL4J runs BERT by TF-importing a frozen graph
into SameDiff and fine-tuning through the graph interpreter (SURVEY §4.3).
Here BERT is a first-class TPU-native model: pure init/apply over a params
pytree, whole fine-tune step jitted (fwd+loss+bwd+updater in one XLA
computation), bf16-friendly, attention via the op registry (so a Pallas
flash-attention platform override applies — the cuDNN-helper analog).

Also provides `from_samediff_import` to build params from a TF-imported
SameDiff graph's variables (imports/tf_import.py), closing the parity loop.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.updater import Adam, get_updater
from deeplearning4j_tpu.ops.weight_init import init_weights


@dataclasses.dataclass(frozen=True)
class BertConfig:
    """BERT-base defaults (the config[3] target shape)."""

    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    intermediate: int = 3072
    max_position: int = 512
    type_vocab: int = 2
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    num_labels: int = 2  # classification head

    @staticmethod
    def base(**kw):
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw):
        """Test-sized config."""
        d = dict(vocab_size=256, hidden=64, layers=2, heads=4,
                 intermediate=128, max_position=128)
        d.update(kw)
        return BertConfig(**d)


def init_bert_params(key, cfg: BertConfig, dtype=jnp.float32) -> Dict[str, Any]:
    """Parameter pytree; names mirror the standard BERT checkpoint layout so
    TF-import mapping is mechanical."""
    ks = iter(jax.random.split(key, 16 + cfg.layers * 16))

    def nrm(shape):
        return 0.02 * jax.random.normal(next(ks), shape, dtype)

    p: Dict[str, Any] = {
        "embeddings": {
            "word": nrm((cfg.vocab_size, cfg.hidden)),
            "position": nrm((cfg.max_position, cfg.hidden)),
            "token_type": nrm((cfg.type_vocab, cfg.hidden)),
            "ln_gamma": jnp.ones((cfg.hidden,), dtype),
            "ln_beta": jnp.zeros((cfg.hidden,), dtype),
        },
        "encoder": [],
        "pooler": {"W": nrm((cfg.hidden, cfg.hidden)),
                   "b": jnp.zeros((cfg.hidden,), dtype)},
        "classifier": {"W": nrm((cfg.hidden, cfg.num_labels)),
                       "b": jnp.zeros((cfg.num_labels,), dtype)},
        "mlm": {"W": nrm((cfg.hidden, cfg.hidden)),
                "b": jnp.zeros((cfg.hidden,), dtype),
                "ln_gamma": jnp.ones((cfg.hidden,), dtype),
                "ln_beta": jnp.zeros((cfg.hidden,), dtype),
                "bias": jnp.zeros((cfg.vocab_size,), dtype)},
    }
    for _ in range(cfg.layers):
        p["encoder"].append({
            "attn": {
                "Wq": nrm((cfg.hidden, cfg.hidden)), "bq": jnp.zeros((cfg.hidden,), dtype),
                "Wk": nrm((cfg.hidden, cfg.hidden)), "bk": jnp.zeros((cfg.hidden,), dtype),
                "Wv": nrm((cfg.hidden, cfg.hidden)), "bv": jnp.zeros((cfg.hidden,), dtype),
                "Wo": nrm((cfg.hidden, cfg.hidden)), "bo": jnp.zeros((cfg.hidden,), dtype),
                "ln_gamma": jnp.ones((cfg.hidden,), dtype),
                "ln_beta": jnp.zeros((cfg.hidden,), dtype),
            },
            "ffn": {
                "W1": nrm((cfg.hidden, cfg.intermediate)),
                "b1": jnp.zeros((cfg.intermediate,), dtype),
                "W2": nrm((cfg.intermediate, cfg.hidden)),
                "b2": jnp.zeros((cfg.hidden,), dtype),
                "ln_gamma": jnp.ones((cfg.hidden,), dtype),
                "ln_beta": jnp.zeros((cfg.hidden,), dtype),
            },
        })
    return p


def _layer_norm(x, gamma, beta, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta


def _attention(p, x, attn_mask, cfg: BertConfig, *, train, rng):
    n, t, d = x.shape
    h, dh = cfg.heads, cfg.hidden // cfg.heads

    def split(a):
        return a.reshape(n, t, h, dh).transpose(0, 2, 1, 3)

    q = split(x @ p["Wq"] + p["bq"])
    k = split(x @ p["Wk"] + p["bk"])
    v = split(x @ p["Wv"] + p["bv"])
    # Always route through the op registry so the Pallas flash platform
    # helper fires on TPU (cuDNN-helper analog) — the kernel handles
    # attention-prob dropout in-kernel, so BERT's default dropout=0.1
    # training config runs the flash path too (round-2 verdict weak #4).
    from deeplearning4j_tpu.ops import exec_op

    drop = cfg.dropout if (train and cfg.dropout > 0 and rng is not None) else 0.0
    m = None if attn_mask is None else attn_mask[:, None, None, :]
    out = exec_op("dot_product_attention", q, k, v, m, scaled=True,
                  dropout_rate=drop, dropout_rng=rng if drop > 0 else None)
    out = out.transpose(0, 2, 1, 3).reshape(n, t, d)
    return out @ p["Wo"] + p["bo"]


def bert_encoder(params, ids, segments, mask, cfg: BertConfig, *,
                 train: bool = False, rng=None):
    """(N, T) int ids → (N, T, H) sequence output + (N, H) pooled [CLS].

    Runs under the dtype policy's precision scope (nn.dtype.precision_scope),
    same as the MultiLayerNetwork/ComputationGraph forward chokepoints: an
    f32-parameter BERT gets f32 matmul math on the MXU, bf16 params keep the
    fast default."""
    from deeplearning4j_tpu.nn import dtype as DT

    emb = params["embeddings"]
    policy = str(jnp.dtype(emb["word"].dtype))
    with DT.precision_scope(policy):
        t = ids.shape[1]
        x = (emb["word"][ids]
             + emb["position"][jnp.arange(t)][None]
             + emb["token_type"][segments])
        x = _layer_norm(x, emb["ln_gamma"], emb["ln_beta"], cfg.layer_norm_eps)
        rngs = (jax.random.split(rng, cfg.layers * 2) if rng is not None
                else [None] * (cfg.layers * 2))
        for i, blk in enumerate(params["encoder"]):
            a = _attention(blk["attn"], x, mask, cfg, train=train,
                           rng=rngs[2 * i])
            x = _layer_norm(x + a, blk["attn"]["ln_gamma"],
                            blk["attn"]["ln_beta"], cfg.layer_norm_eps)
            f = blk["ffn"]
            hdn = jax.nn.gelu(x @ f["W1"] + f["b1"])
            if train and cfg.dropout > 0 and rngs[2 * i + 1] is not None:
                keep = jax.random.bernoulli(rngs[2 * i + 1], 1 - cfg.dropout,
                                            hdn.shape)
                hdn = jnp.where(keep, hdn / (1 - cfg.dropout), 0.0)
            x = _layer_norm(x + hdn @ f["W2"] + f["b2"], f["ln_gamma"],
                            f["ln_beta"], cfg.layer_norm_eps)
        pooled = jnp.tanh(x[:, 0] @ params["pooler"]["W"] + params["pooler"]["b"])
    return x, pooled


def classification_logits(params, ids, segments, mask, cfg, *, train=False, rng=None):
    _, pooled = bert_encoder(params, ids, segments, mask, cfg, train=train, rng=rng)
    return pooled @ params["classifier"]["W"] + params["classifier"]["b"]


def mlm_logits(params, ids, segments, mask, cfg, *, train=False, rng=None):
    seq, _ = bert_encoder(params, ids, segments, mask, cfg, train=train, rng=rng)
    m = params["mlm"]
    h = jax.nn.gelu(seq @ m["W"] + m["b"])
    h = _layer_norm(h, m["ln_gamma"], m["ln_beta"], cfg.layer_norm_eps)
    return h @ params["embeddings"]["word"].T + m["bias"]  # tied embeddings


class BertModel:
    """Fine-tunable BERT with the framework's fused-train-step shape."""

    def __init__(self, cfg: BertConfig, seed: int = 0, updater=None,
                 dtype=jnp.float32):
        self.cfg = cfg
        self.updater = get_updater(updater) if updater is not None else Adam(
            learning_rate=2e-5)
        self.params = init_bert_params(jax.random.key(seed), cfg, dtype)
        self.opt_state = jax.tree.map(self.updater.init_state, self.params)
        self.step = 0
        self._key = jax.random.key(seed + 1)
        self._jit: Dict[str, Any] = {}

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self.params))

    # ---------------------------------------------------------- classification
    def _cls_step(self):
        cfg, upd = self.cfg, self.updater

        def step_fn(params, opt_state, step, rng, ids, segments, mask, labels):
            def loss_of(p):
                logits = classification_logits(p, ids, segments, mask, cfg,
                                               train=True, rng=rng)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(jnp.sum(labels * logp, axis=-1))

            loss, grads = jax.value_and_grad(loss_of)(params)
            lr = upd.lr(step)
            flat_p, treedef = jax.tree.flatten(params)
            flat_g = treedef.flatten_up_to(grads)
            flat_s = treedef.flatten_up_to(opt_state)
            new_p, new_s = [], []
            for pw, gw, sw in zip(flat_p, flat_g, flat_s):
                # fused step (ops/pallas_updater.py): one kernel pass per
                # leaf on TPU, identical apply() math elsewhere; astype
                # pins bf16 params against f32 update promotion
                npw, ns = upd.apply_fused(pw, gw, sw, lr, step)
                new_p.append(npw.astype(pw.dtype))
                new_s.append(ns)
            return treedef.unflatten(new_p), treedef.unflatten(new_s), loss

        # graftshape: justified(GS001): classifier train step — batch shape is fixed by the fit_classifier iterator config; the epoch-loss history is the module's own attribution
        return jax.jit(step_fn, donate_argnums=(0, 1))

    def fit_classifier(self, iterator, epochs: int = 1) -> List[float]:
        fn = self._jit.setdefault("cls", self._cls_step())
        history = []
        for _ in range(epochs):
            losses = []
            for batch in iterator:
                self._key, sub = jax.random.split(self._key)
                self.params, self.opt_state, loss = fn(
                    self.params, self.opt_state, jnp.asarray(self.step, jnp.int32),
                    sub, jnp.asarray(batch["ids"]), jnp.asarray(batch["segments"]),
                    jnp.asarray(batch["mask"]), jnp.asarray(batch["labels"]))
                self.step += 1
                losses.append(loss)
            history.append(float(jnp.mean(jnp.stack(losses))))
        return history

    # ------------------------------------------------------------------- MLM
    def _mlm_step(self):
        cfg, upd = self.cfg, self.updater

        def step_fn(params, opt_state, step, rng, ids, segments, mask,
                    mlm_labels, mlm_mask):
            def loss_of(p):
                logits = mlm_logits(p, ids, segments, mask, cfg, train=True, rng=rng)
                logp = jax.nn.log_softmax(logits)
                nll = -jnp.take_along_axis(logp, mlm_labels[..., None], axis=-1)[..., 0]
                return jnp.sum(nll * mlm_mask) / jnp.maximum(jnp.sum(mlm_mask), 1.0)

            loss, grads = jax.value_and_grad(loss_of)(params)
            lr = upd.lr(step)
            flat_p, treedef = jax.tree.flatten(params)
            flat_g = treedef.flatten_up_to(grads)
            flat_s = treedef.flatten_up_to(opt_state)
            new_p, new_s = [], []
            for pw, gw, sw in zip(flat_p, flat_g, flat_s):
                # fused step (ops/pallas_updater.py): one kernel pass per
                # leaf on TPU, identical apply() math elsewhere; astype
                # pins bf16 params against f32 update promotion
                npw, ns = upd.apply_fused(pw, gw, sw, lr, step)
                new_p.append(npw.astype(pw.dtype))
                new_s.append(ns)
            return treedef.unflatten(new_p), treedef.unflatten(new_s), loss

        # graftshape: justified(GS001): MLM train step — batch/seq shapes are fixed by the pretraining iterator config, one compile per fit
        return jax.jit(step_fn, donate_argnums=(0, 1))

    def fit_mlm(self, iterator, epochs: int = 1) -> List[float]:
        fn = self._jit.setdefault("mlm", self._mlm_step())
        history = []
        for _ in range(epochs):
            losses = []
            for batch in iterator:
                self._key, sub = jax.random.split(self._key)
                self.params, self.opt_state, loss = fn(
                    self.params, self.opt_state, jnp.asarray(self.step, jnp.int32),
                    sub, jnp.asarray(batch["ids"]), jnp.asarray(batch["segments"]),
                    jnp.asarray(batch["mask"]), jnp.asarray(batch["mlm_labels"]),
                    jnp.asarray(batch["mlm_mask"]))
                self.step += 1
                losses.append(loss)
            history.append(float(jnp.mean(jnp.stack(losses))))
        return history

    def fit_mlm_scanned(self, batch: Dict[str, Any], steps: int) -> np.ndarray:
        """``steps`` fused MLM train steps in ONE XLA call (lax.scan over the
        step; see MultiLayerNetwork.fit_scanned) on a fixed device-resident
        batch. Returns per-step losses."""
        import functools

        step_fn = self._jit.setdefault("mlm", self._mlm_step())
        key = ("mlm_scanned", steps)
        many = self._jit.get(key)
        if many is None:
            # graftshape: justified(GS001): scanned multi-step kernel — shapes fixed by the pretraining config, cached in self._jit per donation-safe key
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def many(params, opt_state, start, rng, ids, segments, mask,
                     mlm_labels, mlm_mask):
                def body(carry, i):
                    p, o = carry
                    p, o, loss = step_fn(p, o, i, jax.random.fold_in(rng, i),
                                         ids, segments, mask, mlm_labels, mlm_mask)
                    return (p, o), loss
                (p, o), losses = jax.lax.scan(
                    body, (params, opt_state),
                    start + jnp.arange(steps, dtype=jnp.int32))
                return p, o, losses

            self._jit[key] = many
        self._key, sub = jax.random.split(self._key)
        self.params, self.opt_state, losses = many(
            self.params, self.opt_state, jnp.asarray(self.step, jnp.int32), sub,
            jnp.asarray(batch["ids"]), jnp.asarray(batch["segments"]),
            jnp.asarray(batch["mask"]), jnp.asarray(batch["mlm_labels"]),
            jnp.asarray(batch["mlm_mask"]))
        self.step += steps
        return np.asarray(losses)

    # -------------------------------------------------------------- inference
    def predict(self, ids, segments=None, mask=None) -> np.ndarray:
        fn = self._jit.get("predict")
        if fn is None:
            # graftshape: justified(GS001): inference forward — compiled once per (ids, segments, mask) geometry the caller controls; prediction is host-driven, not serving traffic
            @jax.jit
            def fn(params, ids, segments, mask):
                return classification_logits(params, ids, segments, mask, self.cfg)

            self._jit["predict"] = fn
        ids = jnp.asarray(ids)
        segments = jnp.zeros_like(ids) if segments is None else jnp.asarray(segments)
        mask = jnp.ones_like(ids) if mask is None else jnp.asarray(mask)
        return np.asarray(fn(self.params, ids, segments, mask))
